"""TpuEngine: continuous-batching paged-KV serving engine on JAX/XLA.

The part the reference delegates to vLLM/SGLang/TRT-LLM — here it is
framework-native and TPU-first:

- prefill and decode are two separately-compiled XLA programs with static
  shapes (prompt lengths bucketed, decode batch fixed-width with idle slots),
  so the steady state never recompiles;
- the paged KV cache lives in HBM as [num_blocks, block_size, kv_heads,
  head_dim] per layer, sharded over the TP mesh axis on kv_heads;
- sampling is fused into both programs (only token ids [B] return to host);
- device-side prefix-cache reuse: the host BlockAllocator content-addresses
  sealed blocks by chained sequence hash, prefill feeds only the un-cached
  suffix and attends over cached pages via the block table;
- device calls run in an executor thread so the asyncio control plane (request
  plane heartbeats, event publishing) never stalls behind the TPU.

Model-parallel execution: params carry NamedShardings from
parallel/mesh.py; XLA GSPMD inserts the ICI collectives (psum after
row-parallel matmuls). One engine process per TP slice, like one reference
worker per NCCL TP group.
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from functools import partial
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..kv_router.publisher import KvEventPublisher, WorkerMetricsPublisher
from ..llm.protocols.common import (
    FINISH_ERROR,
    FINISH_LENGTH,
    FINISH_STOP,
    BackendOutput,
    PreprocessedRequest,
)
from ..models import llama, registry
from ..models.vision import IMAGE_TOKEN_ID
from ..ops import attention as att
from ..parallel import mesh as meshlib
from ..runtime.config import ENV_KV_BLOCK_SIZE, env_int
from ..runtime.engine import Context
from ..runtime.errors import (
    ContextLengthError,
    GuidedRejectedError,
    InvalidRequestError,
)
from ..runtime.faults import FAULTS
from ..runtime.attribution import get_attribution
from ..runtime.flight_recorder import get_flight_recorder
from ..runtime.slo import get_slo_accountant, sla_t0_ns, spec_from_annotations
from ..runtime.tasks import spawn_bg
from ..runtime.logging import get_logger
from ..runtime.tracing import get_tracer
from ..tokens import TokenBlockSequence
from .allocator import BlockAllocator, OutOfBlocks
from .telemetry import StepStats
from .sampling import (
    TOP_LOGPROBS_K,
    apply_penalties,
    logprobs_of,
    sample_tokens,
    top_logprobs,
    update_counts,
)

log = get_logger("engine")


@dataclasses.dataclass
class TpuEngineConfig:
    model: llama.LlamaConfig
    num_blocks: int = 512
    # explicit values win; DTPU_KV_BLOCK_SIZE configures what callers leave open
    block_size: int = dataclasses.field(
        default_factory=lambda: env_int(ENV_KV_BLOCK_SIZE, 16)
    )
    max_batch_size: int = 8
    # max_context may exceed the largest prefill bucket: prompts prefill in
    # bounded chunks (one chunk per engine-loop tick, so running decodes
    # never starve behind a long prefill — the reference treats chunked
    # prefill as table stakes, lib/mocker/src/protocols.rs:112,
    # components/src/dynamo/trtllm/engine.py:119)
    max_context: int = 2048
    tp: int = 1
    # context parallelism: chunk prefill attention rides ring_extend_attention
    # over the sp mesh axis (parallel/ring.py) — the long-context scale path
    sp: int = 1
    # pipeline parallelism for SERVING (parallel/pp_serving.py): layer params
    # + paged KV stacked and sharded over a pp mesh axis, shard_map wavefront
    # forward. The reference forwards pipeline_parallel_size into its engines
    # (components/src/dynamo/trtllm/engine.py:118); here it is a first-class
    # engine dimension. pp>1 covers the core dense text path (no LoRA/
    # vision/sp/MoE/pallas yet).
    pp: int = 1
    prefill_buckets: Tuple[int, ...] = (64, 128, 256, 512, 1024, 2048)
    seed: int = 0
    # Pallas ragged decode kernel (ops/pallas_attention): None = auto-enable
    # on the TPU backend (28x over the pure-JAX gather path on v5e), force
    # with True/False (tests run it via the interpreter on CPU)
    use_pallas: Optional[bool] = None
    # decode horizon: run this many decode iterations inside one XLA program
    # (lax.scan, sampled tokens fed back device-side) so per-dispatch launch
    # latency amortizes over N tokens. Stop conditions are applied host-side
    # post-hoc (at most N-1 speculatively-decoded tokens are discarded).
    # None = auto-tune from the measured device round-trip at startup
    # (round-4 verdict #3: the best value tracks RTT, which spans ~1 ms on a
    # local chip to ~170 ms through a tunnel — no constant fits both).
    decode_steps: Optional[int] = None
    # in-flight decode horizons: each horizon's result readback starts at
    # dispatch on the fetch pool, so with depth>=2 the device->host RTT
    # (measured ~70-170 ms on tunneled TPUs; latency, not bandwidth —
    # concurrent fetches overlap) hides behind the next horizon's compute.
    # Each extra slot adds decode_steps tokens of emission latency and
    # speculation waste at stop; measured best on tunneled v5e: depth 2.
    # None = auto-tune with decode_steps.
    decode_pipeline: Optional[int] = None
    # multi-LoRA serving (lora/adapters.py): N static adapter slots baked
    # into the programs at build; hot-load/unload are in-place table updates
    # with zero recompiles. 0 disables (no lora ops in the hot path).
    lora_max_adapters: int = 0
    lora_rank: int = 16
    lora_targets: Tuple[str, ...] = ("wq", "wk", "wv", "wo")
    # pluggable logits processors (logits_processing/): STATIC (name, fn)
    # pairs traced into the programs; requests opt in by name via the
    # "logits_processors" annotation. () disables (zero hot-path cost).
    logits_processors: Tuple[Tuple[str, Any], ...] = ()
    # multimodal: vision tower config (models/vision.py). Prompts carry
    # image placeholder runs (image_token_id); prefill splices the encoded
    # patch embeddings over them (inputs_embeds path in models/llama.py).
    vision: Optional[Any] = None
    image_token_id: int = IMAGE_TOKEN_ID
    # speculative decoding (docs/speculative_decoding.md; the reference
    # exposes it through its vLLM adapter — draft-model speculation,
    # docs/features/speculative_decoding). A draft model config enables it:
    # the draft keeps a SHADOW paged KV cache addressed by the same block
    # tables as the main cache, drafts spec_k greedy tokens per round, and
    # ONE main-model forward over the k candidate positions verifies them
    # (query_len=k+1 rows of the unified ragged kernel). Greedy-equality is the
    # invariant: output is token-identical to the plain engine; the draft
    # only ever changes the acceptance rate. Eligible rows: temperature 0,
    # no penalties, no logprobs, no logits processors (mixed batches fall
    # back to the normal horizon program for the whole dispatch).
    spec_draft: Optional[llama.LlamaConfig] = None
    spec_k: int = 4
    # guided (grammar-constrained) decoding (dynamo_tpu/guided; reference
    # nvext guided_json/regex/choice + response_format). Grammars compile to
    # token-class tables applied INSIDE the decode programs; the FSM state
    # rides the horizon scan carry, so guided rows keep full pipelining.
    # 0 disables (no guided ops in the hot path). The caps bound the
    # per-slot device tables [B, states, classes]; grammars that compile
    # beyond them are rejected per request. Requires the engine to be
    # constructed with guided_vocab=(vocab byte forms, eos_id).
    guided_max_states: int = 0
    guided_max_classes: int = 320
    # mixed continuous batching (ops/pallas_unified + the mixed engine
    # step): when a prefill chunk and resident decode rows coexist, ONE
    # fused dispatch serves both — the chunk rides along with the decode
    # batch through the unified ragged paged-attention kernel instead of
    # stalling it behind a separate prefill program. None = defer to the
    # DTPU_MIXED env (default on). Auto-gated off for the paths the fused
    # program does not cover yet (pp/sp, spec decode, vision, LoRA,
    # multihost, windowed/softcapped families) — those fall back to the
    # split prefill/decode dispatches unchanged.
    mixed_admission: Optional[bool] = None
    # paged-KV storage precision (ops/quant.py; docs/operations.md "KV
    # precision"). "auto" defers to DTPU_KV_DTYPE (default "model" — exactly
    # today's behavior); "int8" stores the cache as int8 with per-block-per-
    # kv-head f32 scales, halving KV bytes in HBM, on the transfer wire and
    # in the KVBM tiers vs bf16 (quartering vs f32) and doubling effective
    # KV capacity per block budget, at a bounded quantization error
    # (amax/254 per element). The draft model's shadow cache stays in model
    # dtype — it is small and its values only steer acceptance, never output.
    kv_dtype: str = "auto"

    def __post_init__(self):
        bad = [b for b in self.prefill_buckets if b % self.block_size]
        if bad:
            raise ValueError(
                f"prefill_buckets {bad} not multiples of block_size {self.block_size}"
            )
        from ..ops.quant import resolve_kv_dtype

        self.kv_dtype = resolve_kv_dtype(self.kv_dtype)

    @property
    def kv_quantized(self) -> bool:
        return self.kv_dtype == "int8"

    @property
    def prefill_chunk(self) -> int:
        """Largest single prefill dispatch; longer prompts chunk at this."""
        return self.prefill_buckets[-1]

    @property
    def max_blocks_per_seq(self) -> int:
        return (self.max_context + self.block_size - 1) // self.block_size


def _model_param_bytes(mcfg) -> int:
    """Rough bf16 parameter footprint — the per-decode-step HBM traffic
    floor (every weight is read once per step at small batch)."""
    h = mcfg.hidden_size
    q = mcfg.num_heads * mcfg.head_dim
    kv = mcfg.num_kv_heads * mcfg.head_dim
    per_layer = h * (q + 2 * kv) + q * h + 3 * h * mcfg.intermediate_size
    embed = mcfg.vocab_size * h * (1 if mcfg.tie_embeddings else 2)
    n_experts = getattr(mcfg, "num_experts", 0) or 0
    if n_experts:
        # active experts only (top-k routing): traffic, not capacity
        top_k = getattr(mcfg, "num_experts_per_tok", 2) or 2
        moe_inter = getattr(mcfg, "moe_intermediate_size", mcfg.intermediate_size)
        per_layer = h * (q + 2 * kv) + q * h + 3 * h * moe_inter * top_k
    return 2 * (per_layer * mcfg.num_layers + embed)


def measure_device_rtt(device, tries: int = 3) -> float:
    """Median dispatch->readback round-trip for a trivial op. NOTE:
    np.asarray (a real fetch), not block_until_ready — on tunneled TPUs the
    latter returns early and under-reports by the full tunnel latency."""
    x = jax.device_put(jnp.zeros((8,), jnp.float32), device)
    np.asarray(x + 1)  # warm the op cache  # dtpu: ignore[HOST-SYNC] — deliberate: this IS the RTT probe
    samples = []
    for _ in range(tries):
        t0 = time.perf_counter()
        np.asarray(x + 1)  # dtpu: ignore[HOST-SYNC] — deliberate fetch: measuring the round-trip is the point
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2]


def autotune_decode_schedule(mcfg, device) -> Tuple[int, int]:
    """(decode_steps, decode_pipeline) from measured RTT + a roofline
    per-step estimate (round-4 verdict #3: replace constants).

    Model: a horizon must keep the device busy for >= ~1 RTT so that with
    pipeline depth 2 the readback of horizon N hides behind horizon N+1's
    compute. steps ~ 0.45 * RTT / t_step rounded to a power of two. The
    0.45 calibrates the pure-weights roofline t_step (1.46 ms for the bench
    model) to the measured grid: actual steps include KV gather + sampling
    (measured 2.6 ms/step), and at RTT ~100 ms the measured best was 32,
    which beat 64 — longer horizons waste speculative tokens at stop.
    Low-RTT devices keep short horizons (less speculation waste, lower
    emission latency) and skip pipelining."""
    bw = 816e9 if device.platform in ("tpu", "axon") else 5e10
    t_step = max(_model_param_bytes(mcfg) / bw, 1e-4)
    try:
        rtt = measure_device_rtt(device)
    except Exception:
        log.exception("RTT probe failed; using tunneled-TPU defaults")
        return 32, 2
    ratio = 0.45 * rtt / t_step
    steps = 8
    while steps < 64 and steps < ratio:
        steps *= 2
    pipeline = 2 if rtt > 2 * t_step else 1
    log.info(
        "decode schedule auto-tuned: rtt=%.1fms t_step~%.2fms -> steps=%d pipeline=%d",
        rtt * 1e3, t_step * 1e3, steps, pipeline,
    )
    return steps, pipeline


@dataclasses.dataclass
class _Seq:
    req: PreprocessedRequest
    context: Context
    out_queue: asyncio.Queue
    seq: TokenBlockSequence               # prompt + generated
    slot: int = -1
    block_ids: List[int] = dataclasses.field(default_factory=list)
    produced: int = 0
    last_token: int = 0
    cached_tokens: int = 0
    prefill_pos: int = 0                  # prompt tokens whose KV is written
    commit_upto: int = 0                  # prompt blocks content-addressed so far
    prefilled: bool = False               # prefill complete -> decode eligible
    # final chunk dispatched, first-token readback in flight (the loop must
    # neither prefill this sequence again nor decode it yet)
    prefill_inflight: bool = False
    # this request keeps output_counts maintained (penalties or an opted-in
    # logits processor) — batchmates' rows accumulate too and must be reset
    # before reuse
    counting: bool = False
    # multimodal: per-prompt-position soft-token override (image spans).
    # mm_embeds [prompt_len, H] model-dtype, mm_mask [prompt_len] bool.
    # Placeholder ids hash identically for different images, so mm requests
    # opt out of the content-addressed prefix cache entirely (no_cache).
    mm_embeds: Optional[np.ndarray] = None
    mm_mask: Optional[np.ndarray] = None
    no_cache: bool = False
    # speculative decoding: prompt positions whose DRAFT KV is written.
    # Independent of prefill_pos — the draft re-prefills from token ids even
    # over regions whose MAIN KV arrived by prefix-cache hit or disagg/kvbm
    # import, so draft coverage of the whole prompt is an invariant.
    draft_prefill_pos: int = 0
    # guided decoding: compiled token tables + current FSM state (host view;
    # the device copy rides the horizon carry and resyncs from this on every
    # chain break)
    guided_tables: Optional[Any] = None
    guided_state: int = 0
    # speculative decoding: this request can ride spec rounds (greedy, no
    # penalties/logprobs/processors/guidance — the same per-request-static
    # predicate _spec_eligible applies batch-wide). Ineligible requests skip
    # draft prefill: their draft KV would never be read.
    spec_ok: bool = True
    done: bool = False
    # lifecycle milestones (unix ns, 0 = not reached): stamped host-side by
    # the loop / accept path, turned into engine.queue / engine.prefill /
    # engine.decode spans + flight-recorder events when the request finishes
    t_queued: int = 0
    t_admitted: int = 0
    t_prefill_start: int = 0
    t_first_token: int = 0
    # SLO accounting (runtime/slo.py): the request's promise parsed from the
    # sla annotation at accept time; None = unclassified (no accounting)
    sla: Optional[Any] = None


@dataclasses.dataclass
class _Chain:
    """An in-flight multi-step decode dispatch: packed [2, N, B] results not
    yet fetched, the device-side carry for dispatching the next horizon
    without a host round-trip, and the per-slot sequence snapshot taken at
    dispatch time (results must never be applied to a sequence admitted into
    a recycled slot afterwards)."""
    packed: jax.Array
    tokens: jax.Array
    seq_lens: jax.Array
    steps: jax.Array
    seqs: List[Optional["_Seq"]] = dataclasses.field(default_factory=list)
    # fetch future (np.asarray on the fetch pool): started at dispatch so
    # pipelined horizons' device->host RTTs overlap instead of serializing
    fetch: Any = None
    # None => normal horizon ([N, B, 2+2K]); k => speculative horizon
    # ([rounds, B, 1+2k]: advance count + k candidate tokens + k logprobs).
    # The device carry (tokens/seq_lens/steps) means the same thing either
    # way, so spec and normal horizons chain on each other freely.
    spec_k: Optional[int] = None
    # guided decoding: device-resident FSM states after this horizon (chained
    # dispatches carry it forward without a host round-trip)
    g_state: Optional[jax.Array] = None


class TpuEngine:
    """AsyncEngine serving PreprocessedRequests with a real JAX model."""

    def __init__(
        self,
        config: TpuEngineConfig,
        params: Optional[llama.Params] = None,
        draft_params: Optional[llama.Params] = None,
        guided_vocab: Optional[Tuple[List[Optional[bytes]], int]] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        kv_publisher: Optional[KvEventPublisher] = None,
        metrics_publisher: Optional[WorkerMetricsPublisher] = None,
        kvbm=None,
        multihost=None,
        mh_ns: str = "",
    ):
        self.cfg = config
        self.mcfg = config.model
        # int8 paged KV (config.kv_dtype / DTPU_KV_DTYPE; ops/quant.py):
        # every cache-touching path below branches on this ONE flag
        self.kv_quantized = config.kv_quantized
        if self.kv_quantized:
            if config.pp > 1:
                raise ValueError(
                    "kv_dtype=int8 does not cover pp serving yet (the pp "
                    "wavefront stacks per-layer caches without the "
                    "quantize-on-write ops); use tp/sp or kv_dtype=model"
                )
            if multihost is not None:
                raise ValueError(
                    "kv_dtype=int8 does not cover multihost groups yet (the "
                    "replay table's kv gather/scatter state wiring moves "
                    "raw arrays); use kv_dtype=model"
                )
        # namespace on the multihost dispatch channel: dp ranks / disagg
        # roles sharing one group each get their own replay table
        self._mh_ns = mh_ns
        # multi-process execution (runtime/multihost.py): process 0 runs this
        # engine normally but broadcasts every jit dispatch; followers hold
        # their own handles of the same globally-sharded arrays and replay.
        # v1 covers the core text serving path — the side paths that touch
        # device state outside the registered ops are gated off.
        self._mh = multihost
        if multihost is not None:
            if config.lora_max_adapters > 0:
                raise ValueError("multihost serving does not cover LoRA yet")
            if config.vision is not None:
                raise ValueError("multihost serving does not cover vision yet")
            if kvbm is not None:
                raise ValueError("multihost serving does not cover kvbm tiers yet")
        if config.pp > 1:
            from ..parallel import pp_serving

            # family gate before any param placement, shared with
            # pp_serving._check_cfg so the operator-facing message lives
            # in one place
            registry.check_pp_supported(self.mcfg)
            if (config.lora_max_adapters or config.vision is not None
                    or config.sp > 1 or kvbm is not None
                    or config.logits_processors
                    or config.use_pallas):
                raise ValueError(
                    "pp serving covers the core dense text path (no LoRA/"
                    "vision/sp/kvbm/logits-processors/pallas yet)"
                )
            if mesh is None:
                mesh = pp_serving.make_pp_mesh(pp=config.pp, tp=config.tp)
            self.mesh = mesh
        else:
            self.mesh = mesh if mesh is not None else meshlib.make_mesh(tp=config.tp)
        # resolve the decode schedule before any program is built (both
        # knobs are baked into the compiled horizon program)
        if config.decode_steps is None or config.decode_pipeline is None:
            # probe a LOCAL device (multihost meshes span processes; RTT to
            # any local chip is representative)
            local = next(
                (d for d in self.mesh.devices.flat
                 if d.process_index == jax.process_index()),
                jax.local_devices()[0],
            )
            steps, pipeline = autotune_decode_schedule(self.mcfg, local)
            if config.decode_steps is None:
                config.decode_steps = steps
            if config.decode_pipeline is None:
                config.decode_pipeline = pipeline
        if config.spec_draft is not None:
            if config.pp > 1 or config.sp > 1:
                raise ValueError(
                    "speculative decoding covers the non-pp, non-sp engine"
                )
            if config.vision is not None or config.lora_max_adapters > 0:
                raise ValueError(
                    "speculative decoding covers the text path (no vision/"
                    "LoRA yet)"
                )
            if config.spec_draft.vocab_size != config.model.vocab_size:
                raise ValueError(
                    "draft and main model must share a vocabulary"
                    f" ({config.spec_draft.vocab_size} != "
                    f"{config.model.vocab_size})"
                )
            # a spec horizon advances at most rounds*k <= decode_steps
            # tokens, so _prepare_horizon's block booking (decode_steps per
            # horizon) covers it; k beyond the horizon budget can't be used
            config.spec_k = max(1, min(config.spec_k, config.decode_steps))
        self.guided_enabled = config.guided_max_states > 0
        if self.guided_enabled:
            if config.pp > 1:
                raise ValueError(
                    "guided decoding covers the non-pp engine (the pp "
                    "sampling epilogues do not carry the mask ops)"
                )
            if guided_vocab is None:
                raise ValueError(
                    "guided decoding needs guided_vocab=(vocab byte forms, "
                    "eos_id) — see guided.vocab_bytes_from_tokenizer"
                )
        if registry.is_gptoss(self.mcfg) or registry.is_gemma(self.mcfg):
            # the unified ragged kernel carries per-row window/sink/softcap
            # attributes (ops/pallas_unified), so use_pallas is no longer
            # rejected for these families: windowed/sink layers route
            # through the unified launch, full-attention layers keep the
            # split decode kernel. Only the ring (sp) path still lacks the
            # window masks.
            if config.sp > 1:
                raise ValueError(
                    "sliding-window attention (gpt-oss/gemma) does not ride"
                    " the ring (sp) path yet; use chunked prefill on sp=1"
                )
        # whether the Pallas kernels are active for this engine (one
        # resolution shared by _build_programs and the mixed gate below)
        self.use_pallas = self._resolve_use_pallas()
        # mixed continuous batching: a prefill chunk fuses into the decode
        # batch through ONE program (unified ragged paged attention). The
        # knob gates intent; the feature additionally requires the Pallas
        # kernels by default — on a pure-JAX engine the fused step would
        # run the O(R*Tq*T) reference attention, slower than the split
        # dispatches it replaces, so only an EXPLICIT mixed_admission=True
        # (--mixed on; CPU/interpret tests) forces it.
        #
        # MIXED GATE (the one documented exclusion site — tools/analysis
        # MIXED-GATE pins it; add a family here only with a baseline
        # entry). Remaining exclusions and why:
        #   pp/sp    — the fused step covers neither the wavefront nor the
        #              ring forward;
        #   vision   — per-chunk soft-token splicing is not threaded
        #              through the packed buffer yet;
        #   multihost — the fused program is not in the replay table.
        # Spec decode, LoRA and the windowed/sink/softcap families
        # (gpt-oss/gemma) ARE mixed-eligible: verify rides the unified
        # kernel as q_len=k+1 rows, per-row adapter ids thread through the
        # packed buffer, and window/sink/softcap are per-row kernel
        # attributes.
        mixed = config.mixed_admission
        if mixed is None:
            mixed = os.environ.get("DTPU_MIXED", "1").lower() not in (
                "0", "", "false", "off"
            )
        self.mixed_enabled = bool(
            mixed
            and (config.mixed_admission is True or self.use_pallas)
            and config.pp == 1
            and config.sp == 1
            and config.vision is None
            and multihost is None
        )
        self.kv_publisher = kv_publisher
        self.metrics_publisher = metrics_publisher
        self.allocator = BlockAllocator(config.num_blocks, config.block_size)
        self._host_rng = np.random.default_rng(config.seed)
        # multi-tier KV (kvbm/pool.py): sealed blocks write through to host
        # DRAM (G2) / disk (G3); admission onboards matched prefixes back
        self.kvbm = kvbm
        # fleet-wide KV reuse (kvbm/directory.py): serving glue attaches a
        # GlobalKvDirectory so tier offloads/evictions advertise/withdraw
        # on the shared directory plane (maintained in _publish_events)
        self.kv_directory = None
        # (block_id, seq_hash, priority): 0 = prompt-prefix blocks (highest
        # reuse odds -> offload first), 1 = decode-sealed blocks; the kvbm
        # priority queue transfers in that order (kvbm/pool.py OffloadQueue,
        # reference offload.rs:10-16)
        self._offload_pending: List[Tuple[int, int, int]] = []

        # --- place params + caches on the mesh ---
        self._forward = (
            None if config.pp > 1 else registry.forward_fn(self.mcfg, self.mesh)
        )
        self._lm_logits = registry.lm_logits_fn(self.mcfg)
        with self.mesh:
            if params is None:
                params = registry.init_params(
                    jax.random.PRNGKey(config.seed), self.mcfg
                )
            if config.pp > 1:
                from ..parallel import pp_serving

                self.params = pp_serving.place_serving_params(self.mesh, params)
                k, v = pp_serving.init_pp_caches(
                    self.mesh, self.mcfg.num_layers, config.num_blocks,
                    config.block_size, self.mcfg.num_kv_heads,
                    self.mcfg.head_dim, self.mcfg.dtype,
                )
                # ONE stacked array per list: donation, multihost state
                # wiring and the decode_multi scan carry are unchanged
                self.k_caches, self.v_caches = [k], [v]
            else:
                if self._eplb_enabled:
                    # EPLB: checkpoint/warm-loaded params carry LOGICAL
                    # expert stacks; expand to physical slots + seed the
                    # remap tables before sharding (models/moe.py). The
                    # physical count must divide over the EP shards.
                    from ..models import moe as moe_mod

                    tp_n = meshlib.tp_size(self.mesh)
                    if self.mcfg.num_physical_experts % tp_n:
                        raise ValueError(
                            f"num_experts + redundant_experts = "
                            f"{self.mcfg.num_physical_experts} must divide "
                            f"over tp={tp_n} for EP sharding"
                        )
                    for lp in params["layers"]:
                        moe_mod.ensure_eplb_layer(lp, self.mcfg)
                self.params = self._shard_params(params)
                self.k_caches, self.v_caches = self._init_caches()

        # --- speculative decoding: draft model + shadow paged cache ---
        # The draft cache mirrors the main cache's block geometry and is
        # addressed by the SAME block tables: content-addressed sharing is
        # safe (same block id => same token content => same draft KV, so
        # concurrent writes are idempotent), and block lifecycle needs no
        # second allocator.
        self.draft_params = None
        self.draft_k_caches = self.draft_v_caches = None
        self._spec_rounds = 0
        if config.spec_draft is not None:
            dcfg = config.spec_draft
            self._draft_forward = registry.forward_fn(dcfg, self.mesh)
            self._draft_logits = registry.lm_logits_fn(dcfg)
            self._spec_rounds = max(1, config.decode_steps // config.spec_k)
            with self.mesh:
                if draft_params is None:
                    draft_params = registry.init_params(
                        jax.random.PRNGKey(config.seed + 2), dcfg
                    )
                self.draft_params = self._shard_params(draft_params, dcfg)
                # the draft's shadow cache stays in model dtype even under
                # kv_dtype=int8: it is spec_k-steps small, and its values
                # only move the acceptance rate, never the emitted tokens
                self.draft_k_caches, self.draft_v_caches = self._init_caches(
                    dcfg, quantized=False
                )
        # acceptance telemetry (reference reports spec acceptance through
        # its engine metrics). rounds = per-ROW rounds applied (a horizon
        # with A active rows and R rounds adds A*R); emitted = tokens
        # advanced on device, BEFORE host-side stop truncation (the
        # discarded tail past a finish is included). acceptance rate =
        # emitted / (rounds * k), in (0, 1]; a perfect draft measures 1.0.
        self.spec_stats = {"rounds": 0, "emitted": 0, "k": config.spec_k}

        # --- slot state (decode batch is fixed-width) ---
        B = config.max_batch_size
        self._slots: List[Optional[_Seq]] = [None] * B
        self._tokens = np.zeros(B, np.int32)
        self._seq_lens = np.zeros(B, np.int32)
        self._block_tables = np.zeros((B, config.max_blocks_per_seq), np.int32)
        self._temps = np.zeros(B, np.float32)
        self._top_ks = np.zeros(B, np.int32)
        self._top_ps = np.ones(B, np.float32)
        self._min_ps = np.zeros(B, np.float32)
        self._pres = np.zeros(B, np.float32)
        self._freqs = np.zeros(B, np.float32)
        self._reps = np.ones(B, np.float32)
        self._lp_ns = np.zeros(B, np.int32)    # requested top-logprobs per slot
        self._lora_slots = np.zeros(B, np.int32)  # adapter slot per batch slot
        self._lp_masks = np.zeros(
            (B, max(1, len(config.logits_processors))), bool
        )  # per-slot logits-processor opt-ins
        self._seeds = np.zeros(B, np.uint32)
        # penalty state tables (device-resident; see engine/sampling.py)
        V = self.mcfg.vocab_size
        # device_put of HOST zeros with an explicit (replicated) sharding:
        # in multi-controller JAX a committed single-device array cannot seed
        # a mesh-spanning program, while an addressable-shard put works on
        # every process; on a single-device mesh this is identical to
        # jnp.zeros. XLA resharding on the first program call applies to both
        # paths equally.
        repl = NamedSharding(self.mesh, P())
        self.output_counts = jax.device_put(np.zeros((B, V), np.int32), repl)
        self.prompt_masks = jax.device_put(np.zeros((B, V), np.int8), repl)
        self._slot_dirty = np.zeros(B, bool)   # slot's penalty tables need reset

        # --- guided decoding slot state ---
        # Per-slot compressed automaton tables (guided/tokens.py): class map
        # [B, V] + transitions [B, S, C], uploaded as one versioned unit (the
        # tables only change on admission/release, never per step).
        if self.guided_enabled:
            S_cap, C_cap = config.guided_max_states, config.guided_max_classes
            self._g_vocab, self._g_eos = guided_vocab
            self._g_active = np.zeros(B, bool)
            self._g_state = np.zeros(B, np.int32)
            self._g_class = np.zeros((B, V), np.int32)
            self._g_trans = np.full((B, S_cap, C_cap), -1, np.int32)
            # upload bookkeeping: the [B] active mask changes on every
            # guided admission AND release (cheap re-upload, own version);
            # the big [B, V] / [B, S_cap, C_cap] tables change only when a
            # guided request is ADMITTED, and then only one slot's rows —
            # tracked per slot so _guided_dev scatters rows into the device
            # copies instead of re-uploading the whole unit
            self._g_active_version = 0
            self._g_dirty_slots: set = set()
            self._g_cache: Dict[Any, Any] = {}  # grammar key -> TokenTables
            if multihost is not None:
                # multihost: the device tables are REPLAY STATE (followers
                # hold their own handles, updated by the guided_active /
                # guided_row ops) — seed identical collective arrays on
                # every process, like output_counts above
                grepl = NamedSharding(self.mesh, P())
                self._g_dev_active = jax.device_put(
                    self._g_active.copy(), grepl
                )
                self._g_dev_class = jax.device_put(
                    self._g_class.copy(), grepl
                )
                self._g_dev_trans = jax.device_put(
                    self._g_trans.copy(), grepl
                )

        self._waiting: List[_Seq] = []
        self._prefill_rr = 0  # round-robin cursor over prefilling sequences
        # chained decode: FIFO of in-flight horizons (packed results + device
        # carry); results are fetched decode_pipeline-1 horizons behind the
        # dispatch front so readback RTT hides behind device compute
        self._chains: "deque[_Chain]" = deque()
        # device-resident copies of slot arrays, re-uploaded only when the
        # host copy changes (host<->device RPCs are the bottleneck on
        # tunneled TPUs: ~100ms per transfer vs ~0.03ms per dispatch)
        self._dev_cache: Dict[str, jax.Array] = {}
        self._loop_task: Optional[asyncio.Task] = None
        self._prefill_tasks: set = set()  # in-flight first-token readbacks
        self._last_published_load: Tuple[int, int, int] = (-1, -1, -1)
        self._wake = asyncio.Event()
        # engine health: False after a step-loop crash (watchdog deregisters
        # the worker; reference components/src/dynamo/vllm/engine_monitor.py)
        self.healthy = True
        self.on_crash: Optional[Any] = None  # callback(exc) scheduled on loop crash
        # step telemetry (engine/telemetry.py): callable(StepStats) invoked
        # after every prefill chunk / consumed decode horizon; None = off.
        # Workers wire EngineTelemetry.on_step; bench.py wires a collector.
        self.stats_hook: Optional[Any] = None
        self._executor = ThreadPoolExecutor(max_workers=1, thread_name_prefix="tpu-step")
        # result readback pool: each in-flight horizon's packed fetch runs on
        # its own thread; on tunneled devices the ~100ms RTT is latency, not
        # bandwidth, so concurrent fetches pipeline and the loop consumes at
        # device cadence
        self._fetch_executor = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="tpu-fetch"
        )
        self._offload_executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpu-offload"
        )
        # async host step-prep (engine/prep.py, DTPU_ASYNC_PREP): step N+1's
        # chunk packing + upload run on a prep thread under step N's device
        # compute. Multihost keeps serial prep (dispatch args are part of
        # the leader's replay-ordered broadcast).
        from .prep import ChunkPrep, async_prep_enabled

        self._prep = None
        if async_prep_enabled() and multihost is None:
            self._prep = ChunkPrep(self._chunk_arrays, upload=jnp.asarray)
        # multimodal vision tower (models/vision.py) + encoder cache
        self.vision_params = None
        self._encode_image_fn = None
        self.encoder_cache = None
        if config.vision is not None:
            if registry.is_moe(self.mcfg):
                raise ValueError("multimodal serving covers the dense family only")
            from ..llm.encoder_cache import EncoderCacheManager
            from ..models import vision as vis

            if config.vision.out_hidden_size != self.mcfg.hidden_size:
                raise ValueError(
                    "vision.out_hidden_size must match the language model "
                    f"hidden size ({self.mcfg.hidden_size})"
                )
            with self.mesh:
                self.vision_params = vis.init_params(
                    jax.random.PRNGKey(config.seed + 1), config.vision
                )
            vcfg = config.vision
            self._encode_image_fn = jax.jit(
                lambda p, img: vis.encode(p, vcfg, img)
            )
            self.encoder_cache = EncoderCacheManager()
        self._mm_zero: Dict[int, Tuple[jax.Array, jax.Array]] = {}
        # multi-LoRA adapter tables (static shapes; see lora/adapters.py)
        self.lora = None
        if config.lora_max_adapters > 0:
            if (registry.is_moe(self.mcfg) or registry.is_mla(self.mcfg)
                    or registry.is_gptoss(self.mcfg)
                    or registry.is_gemma(self.mcfg)):
                raise ValueError(
                    "LoRA serving covers the llama/qwen dense family only"
                )
            from ..lora import LoraAdapterTable

            with self.mesh:
                self.lora = LoraAdapterTable(
                    self.mcfg, config.lora_max_adapters, config.lora_rank,
                    config.lora_targets, dtype=self.mcfg.dtype,
                )
        # disaggregation: KV transfer in/out (engine/transfer.py)
        self.transfer_address: Optional[str] = None
        self._transfer_server = None
        self._transfer_client = None
        # per-chunk commit broadcast for streamed transfer (created with the
        # transfer server; _commit_prefilled_blocks fires it so streaming
        # fetches wake as each prefill chunk's blocks become addressable)
        self.kv_commits = None
        self._probe_load_fn = None  # EPLB load probe, jitted on first use
        self._build_programs()

    # ------------------------------------------------------ kv transfer wiring
    async def serve_transfer(self, host: str = "127.0.0.1") -> str:
        """Start the kv_fetch endpoint (prefill side of disaggregation)."""
        if self.cfg.pp > 1:
            # transfer gathers iterate per-layer cache lists; pp stacks them
            raise ValueError("pp serving does not cover KV transfer yet")
        from ..runtime.request_plane.tcp import TcpRequestServer
        from .transfer import KvCommitSignal, KvTransferServer

        if self.kv_commits is None:
            self.kv_commits = KvCommitSignal()
        srv = KvTransferServer(self, host=host)
        self._kv_transfer_srv = srv
        self._transfer_server = TcpRequestServer(srv.handle, host=host)
        self.transfer_address = await self._transfer_server.start()
        # co-resident clients (same-slice xPyD) find us here and move pages
        # device->device instead of over the wire (transfer.IciKvMover)
        from .transfer import LOCAL_SERVERS

        LOCAL_SERVERS[self.transfer_address] = srv
        return self.transfer_address

    def _get_transfer_client(self):
        if self._transfer_client is None:
            from .transfer import KvTransferClient

            self._transfer_client = KvTransferClient(self)
        return self._transfer_client

    @property
    def kv_bytes_per_block(self) -> int:
        """Wire/storage bytes of one KV block (the transfer-cost signal
        register_llm advertises for transfer-aware disagg routing)."""
        from ..kvbm.layout import kv_bytes_per_token

        return int(
            kv_bytes_per_token(self.mcfg, self.cfg.block_size, self.cfg.kv_dtype)
            * self.cfg.block_size
        )

    def _evacuation_plan(self, st) -> Optional[Dict[str, Any]]:
        """The evacuation reference an error-finish frame carries
        (docs/operations.md §13): the retry's router prices destinations by
        the cost of pulling this worker's sealed KV, and the replacement
        worker replays the plan as its ``kv_transfer`` fetch instead of
        recomputing the prefix. Tier streaming (``tier: True``) serves from
        the host tier, which survives engine-loop death and drain. None
        when the request has nothing fetchable (no transfer server, opted
        out of caching, or no full block computed yet)."""
        if self.transfer_address is None or getattr(st, "no_cache", False):
            return None
        seq = getattr(st, "seq", None)
        if seq is None:
            return None
        try:
            hashes = [int(h) for h in seq.sequence_hashes()]
        except Exception:
            return None
        n_tokens = len(st.req.token_ids) + int(st.produced)
        blocks = min(len(hashes), n_tokens // self.cfg.block_size)
        if blocks <= 0:
            return None
        return {
            "address": self.transfer_address,
            "hashes": hashes[:blocks],
            "num_tokens": blocks * self.cfg.block_size,
            "tier": True,
            "bytes_per_block": int(self.kv_bytes_per_block),
        }

    # ------------------------------------------------------------------ setup
    def _shard_params(self, params: llama.Params, mcfg=None) -> llama.Params:
        specs = registry.param_specs(mcfg if mcfg is not None else self.mcfg)
        mh = self._mh is not None

        def put(x, spec):
            if mh:
                # route through host: every process uploads its own shards of
                # the (identical) host weights; a committed device array from
                # random-init/warm-load is process-local and cannot be put to
                # a mesh that spans processes
                x = np.asarray(x)
            return jax.device_put(x, NamedSharding(self.mesh, spec))

        out: llama.Params = {"layers": []}
        for name, w in params.items():
            if name == "layers":
                continue
            out[name] = put(w, specs["top"].get(name, specs["default"]))
        for lp in params["layers"]:
            slp = {
                name: put(w, specs["layer"].get(name, specs["default"]))
                for name, w in lp.items()
            }
            out["layers"].append(slp)
        return out

    def _init_caches(
        self, mcfg=None, quantized: Optional[bool] = None
    ) -> Tuple[List[jax.Array], List[jax.Array]]:
        mcfg = mcfg if mcfg is not None else self.mcfg
        if quantized is None:
            quantized = self.kv_quantized
        shape = (
            self.cfg.num_blocks,
            self.cfg.block_size,
            mcfg.num_kv_heads,
            mcfg.head_dim,
        )
        tp_n = meshlib.tp_size(self.mesh)
        sharding = NamedSharding(
            self.mesh, registry.kv_cache_spec(mcfg, tp_n)
        )
        # host-side zeros: device_put shards them per-process (jnp.zeros would
        # commit to the local default device — invalid for a multi-host mesh)
        if quantized:
            from ..ops.quant import SCALE_DTYPE, QuantizedKV

            s_sharding = NamedSharding(
                self.mesh, registry.kv_scale_spec(mcfg, tp_n)
            )
            s_shape = (self.cfg.num_blocks, mcfg.num_kv_heads)

            def qzeros():
                return QuantizedKV(
                    jax.device_put(np.zeros(shape, np.int8), sharding),
                    jax.device_put(np.zeros(s_shape, SCALE_DTYPE), s_sharding),
                )

            k = [qzeros() for _ in range(mcfg.num_layers)]
            v = [qzeros() for _ in range(mcfg.num_layers)]
            return k, v
        zeros = partial(np.zeros, shape, mcfg.dtype)
        k = [jax.device_put(zeros(), sharding) for _ in range(mcfg.num_layers)]
        v = [jax.device_put(zeros(), sharding) for _ in range(mcfg.num_layers)]
        return k, v

    def _resolve_use_pallas(self) -> bool:
        """cfg.use_pallas, with None resolved to the auto rule: Mosaic DMA
        slices need the minor dim 128-aligned (head_dim is the page's minor
        dim, so odd head sizes fall back to pure JAX); the shard_map'd
        kernel shards the cache on kv_heads, so fewer kv heads than TP
        shards (MQA / MLA latent) falls back to the GSPMD pure-JAX path.
        The windowed/sink families (gpt-oss, gemma) are SUPPORTED by the
        unified kernel's per-row attributes but stay off the auto rule
        until a real-TPU run confirms the windowed chunk-start lowering
        (the PR 2 caveat protocol) — an explicit use_pallas=True routes
        their windowed/sink layers through the unified launch. pp serving
        never uses Pallas (construction rejects the combination)."""
        if self.cfg.pp > 1:
            return False
        if self.cfg.use_pallas is not None:
            return bool(self.cfg.use_pallas)
        mcfg = self.mcfg
        return (
            jax.default_backend() == "tpu"
            and mcfg.head_dim % 128 == 0
            and mcfg.num_kv_heads % meshlib.tp_size(self.mesh) == 0
            and not registry.is_gptoss(mcfg)
            and not registry.is_gemma(mcfg)
        )

    def _build_programs_pp(self) -> None:
        """pp>1 programs: same signatures/state layout as _build_programs so
        every call site (and the multihost replay table) is oblivious; the
        forward is the shard_map wavefront from parallel/pp_serving.py.
        LoRA/vision/logits-processor args are accepted and ignored (their
        features are gated off at construction).

        NOTE: the sampling/penalty/logprob epilogues deliberately mirror
        _build_programs rather than sharing a parameterized builder — the
        non-pp path is the measured-and-tuned TPU hot path and stays
        refactor-free; test_pp_serving pins the two token-identical. A
        sampling change must land in BOTH builders."""
        cfg, mcfg = self.cfg, self.mcfg
        from ..parallel import pp_serving

        logits_fn = self._lm_logits
        pf_fwd = pp_serving.make_pp_prefill_forward(
            self.mesh, mcfg, cfg.pp, cfg.tp
        )
        dc_fwd = pp_serving.make_pp_decode_forward(
            self.mesh, mcfg, cfg.pp, cfg.tp
        )
        repl = NamedSharding(self.mesh, P())

        def _fetchable(x):
            return jax.lax.with_sharding_constraint(x, repl)

        def pack_step(toks, lps, tlp_vals, tlp_ids):
            return jnp.concatenate(
                [
                    toks.astype(jnp.float32)[:, None],
                    lps[:, None],
                    tlp_ids.astype(jnp.float32),
                    tlp_vals,
                ],
                axis=-1,
            )

        def pen_need(pres, freqs, reps):
            return jnp.any((pres != 0.0) | (freqs != 0.0) | (reps != 1.0))

        def prefill(params, k_caches, v_caches, counts, tokens, positions,
                    block_table, new_block_ids, total_len, chunk_start, seeds,
                    steps, temp, top_k, top_p, min_p, pres, freq, rep,
                    prompt_masks, slot, lp_need, is_final, lora_tables,
                    lora_id, proc_masks, mm_embeds, mm_mask):
            hidden, k2, v2 = pf_fwd(
                params, k_caches[0], v_caches[0], tokens, positions,
                block_table, new_block_ids, total_len,
            )

            def sample_branch(counts):
                last_idx = jnp.argmax(positions == total_len - 1)
                logits = logits_fn(params, mcfg, hidden[last_idx][None])
                pen = apply_penalties(
                    logits, jnp.zeros_like(logits, jnp.int32),
                    prompt_masks[slot][None], pres, freq, rep,
                )
                tok = sample_tokens(pen, seeds, steps, temp, top_k, top_p, min_p)
                counts = jax.lax.cond(
                    pen_need(pres, freq, rep),
                    lambda c: c.at[slot, tok[0]].add(1),
                    lambda c: c,
                    counts,
                )
                lp = logprobs_of(logits, tok)
                tlp_vals, tlp_ids = top_logprobs(logits, lp_need)
                return counts, tok[0], lp[0], tlp_vals[0], tlp_ids[0]

            def no_sample(counts):
                K = TOP_LOGPROBS_K
                return (
                    counts, jnp.int32(0), jnp.float32(0.0),
                    jnp.zeros((K,), jnp.float32), jnp.zeros((K,), jnp.int32),
                )

            counts, tok, lp, tlp_vals, tlp_ids = jax.lax.cond(
                is_final, sample_branch, no_sample, counts
            )
            tok, lp, tlp_vals, tlp_ids = map(_fetchable, (tok, lp, tlp_vals, tlp_ids))
            return [k2], [v2], counts, tok, lp, tlp_vals, tlp_ids

        def decode(params, k_caches, v_caches, counts, tokens, positions,
                   block_tables, seq_lens, write_blocks, write_offsets, seeds,
                   steps, temps, top_ks, top_ps, min_ps, pres, freqs, reps,
                   prompt_masks, lp_need, lora_tables, lora_ids, proc_masks):
            hidden, k2, v2 = dc_fwd(
                params, k_caches[0], v_caches[0], tokens, positions,
                block_tables, seq_lens, write_blocks, write_offsets,
            )
            logits = logits_fn(params, mcfg, hidden)
            pen = apply_penalties(logits, counts, prompt_masks, pres, freqs, reps)
            toks = sample_tokens(pen, seeds, steps, temps, top_ks, top_ps, min_ps)
            counts = update_counts(
                counts, toks, seq_lens > 0, pen_need(pres, freqs, reps)
            )
            lps = logprobs_of(logits, toks)
            tlp_vals, tlp_ids = top_logprobs(logits, lp_need)
            toks, lps, tlp_vals, tlp_ids = map(
                _fetchable, (toks, lps, tlp_vals, tlp_ids)
            )
            return [k2], [v2], counts, toks, lps, tlp_vals, tlp_ids

        def decode_multi(params, k_caches, v_caches, counts, tokens, seq_lens,
                         block_tables, active, seeds, steps0, temps, top_ks,
                         top_ps, min_ps, pres, freqs, reps, prompt_masks,
                         lp_need, lora_tables, lora_ids, proc_masks):
            bs = cfg.block_size
            need_pen = pen_need(pres, freqs, reps)

            def one_step(carry, s):
                k_caches, v_caches, counts, tokens, seq_lens = carry
                positions = jnp.maximum(seq_lens - 1, 0)
                write_blocks = jnp.where(
                    active,
                    jnp.take_along_axis(
                        block_tables, (positions // bs)[:, None], axis=1
                    )[:, 0],
                    0,
                )
                write_offsets = jnp.where(active, positions % bs, 0)
                hidden, k2, v2 = dc_fwd(
                    params, k_caches[0], v_caches[0], tokens, positions,
                    block_tables, seq_lens, write_blocks, write_offsets,
                )
                logits = logits_fn(params, mcfg, hidden)
                pen = apply_penalties(
                    logits, counts, prompt_masks, pres, freqs, reps
                )
                toks = sample_tokens(
                    pen, seeds, steps0 + s, temps, top_ks, top_ps, min_ps
                )
                counts = update_counts(counts, toks, active, need_pen)
                lps = logprobs_of(logits, toks)
                tlp_vals, tlp_ids = top_logprobs(logits, lp_need)
                seq_lens = seq_lens + active.astype(jnp.int32)
                return (
                    ([k2], [v2], counts, toks, seq_lens),
                    pack_step(toks, lps, tlp_vals, tlp_ids),
                )

            (k_caches, v_caches, counts, tokens, seq_lens), packed = (
                jax.lax.scan(
                    one_step,
                    (k_caches, v_caches, counts, tokens, seq_lens),
                    jnp.arange(cfg.decode_steps),
                )
            )
            next_steps = steps0 + jnp.where(active, cfg.decode_steps, 0)
            return (
                k_caches, v_caches, counts, _fetchable(packed),
                tokens, seq_lens, next_steps,
            )

        def reset_slot(prompt_masks, counts, slot, row):
            return prompt_masks.at[slot].set(row), counts.at[slot].set(0)

        em_fwd = pp_serving.make_pp_embed_forward(
            self.mesh, mcfg, cfg.pp, cfg.tp
        )

        def embed(params, tokens, positions, last_idx):
            """Pooled dense-causal forward through the pipeline: no KV pages
            touched (embeddings never pollute the generation cache)."""
            hidden = em_fwd(params, tokens, positions)
            h = hidden[last_idx].astype(jnp.float32)
            return _fetchable(h / jnp.maximum(jnp.linalg.norm(h), 1e-9))

        def embed_chunk(params, k_caches, v_caches, tokens, positions,
                        block_table, new_block_ids, total_len, last_idx,
                        is_final):
            """Chunked pooled forward through the pipeline: inputs past the
            largest prefill bucket run like pp chunked prefill — each chunk
            writes its KV into TEMPORARY pages via the wavefront prefill
            forward (allocated by the caller, never committed, released
            after) and attends over the gathered prefix; the final chunk
            yields the normalized last-token hidden state. Same host-side
            protocol as the non-pp embed_chunk, so _run_embed is oblivious."""
            hidden, k2, v2 = pf_fwd(
                params, k_caches[0], v_caches[0], tokens, positions,
                block_table, new_block_ids, total_len,
            )
            vec = jax.lax.cond(
                is_final,
                lambda: (
                    lambda h: h / jnp.maximum(jnp.linalg.norm(h), 1e-9)
                )(hidden[last_idx].astype(jnp.float32)),
                lambda: jnp.zeros((mcfg.hidden_size,), jnp.float32),
            )
            return [k2], [v2], _fetchable(vec)

        self._prefill_fn = jax.jit(prefill, donate_argnums=(1, 2, 3))
        self._decode_fn = jax.jit(decode, donate_argnums=(1, 2, 3))
        self._decode_multi_fn = jax.jit(decode_multi, donate_argnums=(1, 2, 3))
        self._reset_slot_fn = jax.jit(reset_slot, donate_argnums=(0, 1))
        self._embed_fn = jax.jit(embed)
        self._embed_chunk_fn = jax.jit(embed_chunk, donate_argnums=(1, 2))
        if self._mh is not None:
            self._wire_multihost()

    def _build_programs(self) -> None:
        if self.cfg.pp > 1:
            return self._build_programs_pp()
        cfg, mcfg = self.cfg, self.mcfg
        fwd, logits_fn = self._forward, self._lm_logits
        lora_enabled = self.lora is not None
        quantized = self.kv_quantized

        vision_enabled = cfg.vision is not None

        def call_fwd(params, tokens, positions, attend, lora_tables, lora_ids,
                     mm_embeds=None, mm_mask=None):
            kw = {}
            if lora_enabled:
                from ..lora import make_lora_fn

                kw["lora"] = make_lora_fn(lora_tables, lora_ids)
            if mm_embeds is not None and vision_enabled:
                # splice vision soft tokens over placeholder positions; the
                # gather uses clipped ids (placeholders sit above the vocab)
                safe = jnp.clip(tokens, 0, mcfg.vocab_size - 1)
                base = params["embed"][safe]
                kw["inputs_embeds"] = jnp.where(
                    mm_mask[..., None], mm_embeds.astype(base.dtype), base
                )
                return fwd(params, mcfg, safe, positions, attend, **kw)
            if not kw:
                return fwd(params, mcfg, tokens, positions, attend)
            return fwd(params, mcfg, tokens, positions, attend, **kw)

        use_pallas = self.use_pallas
        if use_pallas:
            from ..ops import pallas_attention as pa
            from ..ops import pallas_unified as pun

            mesh = self.mesh
            # off-TPU (forced use_pallas in CPU tests) the kernel runs in the
            # Pallas interpreter
            interp = jax.default_backend() != "tpu"

            def paged_attention(q, kc, vc, tables, lens, **extra):
                if extra:
                    # windowed/sink/softcap layers (gpt-oss/gemma): the
                    # split decode kernel carries no per-row attributes —
                    # serve the decode batch as q_len=1 rows of the
                    # unified ragged kernel instead
                    B = q.shape[0]
                    win = extra.get("window")
                    return pun.sharded_ragged_paged_attention(
                        mesh, meshlib.AXIS_TP, q, kc, vc, tables,
                        jnp.arange(B, dtype=jnp.int32),
                        (lens > 0).astype(jnp.int32),
                        lens.astype(jnp.int32),
                        windows=(
                            jnp.full((B,), win, jnp.int32)
                            if win is not None else None
                        ),
                        sinks=extra.get("sinks"),
                        softcap=extra.get("softcap"),
                        interpret=interp,
                    )
                return pa.sharded_paged_decode_attention(
                    mesh, meshlib.AXIS_TP, q, kc, vc, tables, lens,
                    interpret=interp,
                )
        else:
            paged_attention = att.paged_decode_attention

        procs = cfg.logits_processors

        def pen_need(pres, freqs, reps):
            return jnp.any((pres != 0.0) | (freqs != 0.0) | (reps != 1.0))

        def counts_need(pres, freqs, reps, proc_masks):
            """output_counts must be maintained for penalties AND for any
            opted-in logits processor (processors read counts as documented
            on-device state — logits_processing/)."""
            need = pen_need(pres, freqs, reps)
            if procs:
                need = need | jnp.any(proc_masks)
            return need

        def run_procs(logits, masks, counts, steps, seq_lens):
            if not procs:
                return logits
            from ..logits_processing import apply_processors

            return apply_processors(procs, masks, logits, {
                "output_counts": counts, "steps": steps, "seq_lens": seq_lens,
            })

        # host-fetched outputs are pinned fully-replicated: on a single
        # process any addressable layout can be np.asarray'd, but the leader
        # of a multi-process mesh can only fetch data whose every shard is
        # addressable locally. A no-op on one device; an all-gather of a few
        # hundred bytes otherwise.
        repl = NamedSharding(self.mesh, P())

        def _fetchable(x):
            return jax.lax.with_sharding_constraint(x, repl)

        def pack_step(toks, lps, tlp_vals, tlp_ids):
            """[B] toks/lps + [B,K] top-logprob rows -> one [B, 2+2K] f32 row
            (token ids are exact in f32 below 2^24) so the host pays a single
            device->host fetch per horizon."""
            return jnp.concatenate(
                [
                    toks.astype(jnp.float32)[:, None],
                    lps[:, None],
                    tlp_ids.astype(jnp.float32),
                    tlp_vals,
                ],
                axis=-1,
            )

        # guided decoding ops (cfg.guided_max_states > 0): one [B, C] row
        # gather + one [B, V] class lookup per step. Callers pass g_* only
        # when guidance is built in — `is None` is a TRACE-time branch, so
        # the disabled engine's programs are bit-identical to before.
        GNEG = jnp.float32(-1e30)

        def gmask(logits, g_active, g_state, g_class, g_trans):
            """Mask logits to the tokens legal from each row's FSM state."""
            row = jnp.take_along_axis(
                g_trans, g_state[:, None, None], axis=1
            )[:, 0]                                             # [B, C]
            ok = jnp.take_along_axis(row, g_class, axis=1) >= 0  # [B, V]
            return jnp.where(g_active[:, None] & ~ok, GNEG, logits)

        def gstep(g_state, toks, g_active, g_class, g_trans):
            """Advance each row's FSM by its sampled token."""
            cls = jnp.take_along_axis(g_class, toks[:, None], axis=1)[:, 0]
            row = jnp.take_along_axis(
                g_trans, g_state[:, None, None], axis=1
            )[:, 0]
            nxt = jnp.take_along_axis(row, cls[:, None], axis=1)[:, 0]
            return jnp.where(g_active, jnp.maximum(nxt, 0), g_state)

        if cfg.sp > 1:
            from ..parallel import ring as ringlib

        def prefill(params, k_caches, v_caches, counts, tokens, positions,
                    block_table, new_block_ids, total_len, chunk_start, seeds,
                    steps, temp, top_k, top_p, min_p, pres, freq, rep,
                    prompt_masks, slot, lp_need, is_final, lora_tables,
                    lora_id, proc_masks, mm_embeds, mm_mask,
                    g_active=None, g_state=None, g_class=None, g_trans=None):
            # tokens/positions: [S_pad] — ONE chunk of the prompt (the whole
            # prompt when it fits a bucket); block_table: [max_blocks_per_seq]
            def attend(q, k_new, v_new, layer_idx, **extra):
                # extra: per-layer attention variants the model opts into
                # (sliding ``window``, per-head ``sinks`` — models/gptoss.py);
                # plain families pass nothing and nothing changes
                k_w, v_w = k_new, v_new
                if quantized:
                    # zero the chunk's PADDING rows before quantize-on-write:
                    # a bucket-padded chunk shares its last real block with
                    # pad rows (token 0 at position max_context-1) whose
                    # activations would otherwise enter the per-block amax
                    # and coarsen the real tokens' quantization. Pad rows
                    # are never attended (every mask keys off total_len),
                    # so zeros are safe — and exact for the amax.
                    valid = (positions < total_len)[:, None, None]
                    k_w = jnp.where(valid, k_new, 0.0)
                    v_w = jnp.where(valid, v_new, 0.0)
                kc, vc = att.write_prefill_kv(
                    k_caches[layer_idx], v_caches[layer_idx], k_w, v_w, new_block_ids
                )
                k_caches[layer_idx], v_caches[layer_idx] = kc, vc
                if cfg.sp > 1:
                    # context-parallel chunk attention: queries + chunk KV
                    # shard over the sp axis and rotate around the ring; the
                    # cached prefix is attended locally (parallel/ring.py).
                    # gather_kv dequantizes int8 caches, so the ring path
                    # rides quantization transparently.
                    k_ctx, v_ctx = att.gather_kv(kc, vc, block_table)
                    return ringlib.ring_extend_attention(
                        self.mesh, q, k_new, v_new, k_ctx, v_ctx,
                        positions, chunk_start, chunk_start,
                    )
                if use_pallas and extra:
                    # windowed/sink/softcap chunk (gpt-oss/gemma): the
                    # flash-extend kernel has no per-row attributes —
                    # serve the chunk as ONE ragged row of the unified
                    # kernel (segment at the context tail; window
                    # page-skip included) instead of the dense reference
                    # extend over the gathered context
                    win = extra.get("window")
                    return pun.sharded_ragged_paged_attention(
                        mesh, meshlib.AXIS_TP, q, kc, vc,
                        block_table[None],
                        jnp.zeros((1,), jnp.int32),
                        (total_len - chunk_start).astype(jnp.int32)[None],
                        total_len.astype(jnp.int32)[None],
                        windows=(
                            jnp.full((1,), win, jnp.int32)
                            if win is not None else None
                        ),
                        sinks=extra.get("sinks"),
                        softcap=extra.get("softcap"),
                        interpret=interp,
                    )
                from ..ops import pallas_prefill as pf

                flash_ok = (
                    use_pallas
                    and not extra
                    and q.shape[0] % pf.Q_TILE == 0
                    and block_table.shape[0] * cfg.block_size % pf.KV_TILE == 0
                )
                if flash_ok and quantized:
                    # raw-int8 gather: the flash kernel streams int8 context
                    # tiles + per-position scale columns and dequantizes
                    # in-register (half the context bytes vs bf16)
                    kq, vq, ks, vs = att.gather_kv_quant(kc, vc, block_table)
                    return pf.sharded_flash_extend_attention(
                        self.mesh, meshlib.AXIS_TP,
                        q, kq, vq, positions, total_len,
                        k_scales=ks, v_scales=vs, interpret=interp,
                    )
                k_ctx, v_ctx = att.gather_kv(kc, vc, block_table)
                if flash_ok:
                    # flash extend kernel (ops/pallas_prefill): O(tile) VMEM
                    # vs the dense [S, h, T] score tensor; TP rides a
                    # shard_map over heads (GSPMD cannot partition a custom
                    # call). Shapes that miss the tile grid fall back.
                    return pf.sharded_flash_extend_attention(
                        self.mesh, meshlib.AXIS_TP,
                        q, k_ctx, v_ctx, positions, total_len,
                        interpret=interp,
                    )
                return att.extend_attention(
                    q, k_ctx, v_ctx, positions, total_len, **extra
                )

            hidden = call_fwd(
                params, tokens, positions, attend, lora_tables, lora_id,
                mm_embeds=mm_embeds, mm_mask=mm_mask,
            )

            def sample_branch(counts):
                # logits at the last real token (positions are absolute; the
                # last real new token sits where position == total_len - 1)
                last_idx = jnp.argmax(positions == total_len - 1)
                logits = logits_fn(params, mcfg, hidden[last_idx][None])  # [1, V]
                pen = apply_penalties(
                    logits, jnp.zeros_like(logits, jnp.int32),
                    prompt_masks[slot][None], pres, freq, rep,
                )
                pen = run_procs(
                    pen, proc_masks[slot][None],
                    counts[slot][None], steps, total_len[None],
                )
                if g_active is not None:
                    # first generated token: FSM at g_state (0, or past the
                    # prior tokens on a disagg/migration resume). Full
                    # [B, ...] tables indexed by slot (not pre-sliced rows):
                    # the same device-resident unit the decode ops use, so
                    # multihost replays it as shared state instead of
                    # broadcasting megabyte rows per chunk.
                    pen = gmask(
                        pen, g_active[slot][None],
                        jnp.full((1,), g_state, jnp.int32),
                        g_class[slot][None], g_trans[slot][None],
                    )
                tok = sample_tokens(pen, seeds, steps, temp, top_k, top_p, min_p)
                # the first generated token must enter the output counts, or
                # the first decode step's penalties miss it
                counts = jax.lax.cond(
                    counts_need(pres, freq, rep, proc_masks[slot][None]),
                    lambda c: c.at[slot, tok[0]].add(1),
                    lambda c: c,
                    counts,
                )
                lp = logprobs_of(logits, tok)
                tlp_vals, tlp_ids = top_logprobs(logits, lp_need)
                return counts, tok[0], lp[0], tlp_vals[0], tlp_ids[0]

            def no_sample(counts):
                # intermediate chunk: KV written, no token sampled — skips
                # the full-vocab lm_head matmul entirely
                K = TOP_LOGPROBS_K
                return (
                    counts, jnp.int32(0), jnp.float32(0.0),
                    jnp.zeros((K,), jnp.float32), jnp.zeros((K,), jnp.int32),
                )

            counts, tok, lp, tlp_vals, tlp_ids = jax.lax.cond(
                is_final, sample_branch, no_sample, counts
            )
            tok, lp, tlp_vals, tlp_ids = map(_fetchable, (tok, lp, tlp_vals, tlp_ids))
            return k_caches, v_caches, counts, tok, lp, tlp_vals, tlp_ids

        def decode(params, k_caches, v_caches, counts, tokens, positions,
                   block_tables, seq_lens, write_blocks, write_offsets, seeds,
                   steps, temps, top_ks, top_ps, min_ps, pres, freqs, reps,
                   prompt_masks, lp_need, lora_tables, lora_ids, proc_masks,
                   g_active=None, g_state=None, g_class=None, g_trans=None):
            # tokens: [B]; block_tables: [B, max_blocks_per_seq]
            def attend(q, k_new, v_new, layer_idx, **extra):
                kc, vc = att.write_decode_kv(
                    k_caches[layer_idx], v_caches[layer_idx],
                    k_new[:, 0], v_new[:, 0], write_blocks, write_offsets,
                )
                k_caches[layer_idx], v_caches[layer_idx] = kc, vc
                out = paged_attention(
                    q[:, 0], kc, vc, block_tables, seq_lens, **extra
                )
                return out[:, None]

            hidden = call_fwd(
                params, tokens[:, None], positions[:, None], attend,
                lora_tables, lora_ids,
            )  # [B, 1, H]
            logits = logits_fn(params, mcfg, hidden[:, 0])  # [B, V]
            pen = apply_penalties(logits, counts, prompt_masks, pres, freqs, reps)
            pen = run_procs(pen, proc_masks, counts, steps, seq_lens)
            if g_active is not None:
                pen = gmask(pen, g_active, g_state, g_class, g_trans)
            toks = sample_tokens(pen, seeds, steps, temps, top_ks, top_ps, min_ps)
            counts = update_counts(
                counts, toks, seq_lens > 0, counts_need(pres, freqs, reps, proc_masks)
            )
            lps = logprobs_of(logits, toks)
            tlp_vals, tlp_ids = top_logprobs(logits, lp_need)
            toks, lps, tlp_vals, tlp_ids = map(
                _fetchable, (toks, lps, tlp_vals, tlp_ids)
            )
            return k_caches, v_caches, counts, toks, lps, tlp_vals, tlp_ids

        def decode_multi(params, k_caches, v_caches, counts, tokens, seq_lens,
                         block_tables, active, seeds, steps0, temps, top_ks,
                         top_ps, min_ps, pres, freqs, reps, prompt_masks,
                         lp_need, lora_tables, lora_ids, proc_masks,
                         g_active=None, g_state=None, g_class=None,
                         g_trans=None):
            """cfg.decode_steps decode iterations in one program: each step
            writes the fed token's KV, attends, samples, and feeds the sample
            back — tokens only reach the host once per horizon. seq_lens==0
            slots (inactive) write to scratch block 0 and are discarded.

            Returns the per-step results packed into ONE f32 array
            [N, B, 2+2K] (sampled token, its logprob, top-K logprob rows),
            plus the device-resident carry (tokens/seq_lens/steps) that lets
            the loop dispatch the next horizon without any host round-trip."""
            bs = cfg.block_size
            need_pen = counts_need(pres, freqs, reps, proc_masks)

            def one_step(carry, s):
                k_caches, v_caches, counts, tokens, seq_lens, g_st = carry
                positions = jnp.maximum(seq_lens - 1, 0)
                write_blocks = jnp.where(
                    active,
                    jnp.take_along_axis(
                        block_tables, (positions // bs)[:, None], axis=1
                    )[:, 0],
                    0,
                )
                write_offsets = jnp.where(active, positions % bs, 0)

                def attend(q, k_new, v_new, layer_idx, **extra):
                    kc, vc = att.write_decode_kv(
                        k_caches[layer_idx], v_caches[layer_idx],
                        k_new[:, 0], v_new[:, 0], write_blocks, write_offsets,
                    )
                    k_caches[layer_idx], v_caches[layer_idx] = kc, vc
                    out = paged_attention(
                        q[:, 0], kc, vc, block_tables, seq_lens, **extra
                    )
                    return out[:, None]

                hidden = call_fwd(
                    params, tokens[:, None], positions[:, None], attend,
                    lora_tables, lora_ids,
                )
                logits = logits_fn(params, mcfg, hidden[:, 0])
                pen = apply_penalties(logits, counts, prompt_masks, pres, freqs, reps)
                pen = run_procs(pen, proc_masks, counts, steps0 + s, seq_lens)
                if g_active is not None:
                    pen = gmask(pen, g_active, g_st, g_class, g_trans)
                toks = sample_tokens(
                    pen, seeds, steps0 + s, temps, top_ks, top_ps, min_ps
                )
                if g_active is not None:
                    g_st = gstep(g_st, toks, g_active, g_class, g_trans)
                counts = update_counts(counts, toks, active, need_pen)
                lps = logprobs_of(logits, toks)
                tlp_vals, tlp_ids = top_logprobs(logits, lp_need)
                seq_lens = seq_lens + active.astype(jnp.int32)
                return (
                    (k_caches, v_caches, counts, toks, seq_lens, g_st),
                    pack_step(toks, lps, tlp_vals, tlp_ids),
                )

            g0 = g_state if g_state is not None else jnp.zeros_like(tokens)
            (k_caches, v_caches, counts, tokens, seq_lens, g_out), packed = (
                jax.lax.scan(
                    one_step,
                    (k_caches, v_caches, counts, tokens, seq_lens, g0),
                    jnp.arange(cfg.decode_steps),
                )
            )
            next_steps = steps0 + jnp.where(active, cfg.decode_steps, 0)
            out = (
                k_caches, v_caches, counts, _fetchable(packed),
                tokens, seq_lens, next_steps,
            )
            return out + (g_out,) if g_active is not None else out

        if use_pallas:
            def ragged_attention(q, kc, vc, tables, q_starts, q_lens, lens,
                                 window=None, sinks=None, softcap=None):
                # scalar per-layer window -> per-row windows array (every
                # row of one launch shares the layer's bound)
                R = tables.shape[0]
                return pun.sharded_ragged_paged_attention(
                    self.mesh, meshlib.AXIS_TP, q, kc, vc, tables,
                    q_starts, q_lens, lens,
                    windows=(
                        jnp.full((R,), window, jnp.int32)
                        if window is not None else None
                    ),
                    sinks=sinks, softcap=softcap, interpret=interp,
                )
        else:
            ragged_attention = att.ragged_paged_attention

        def mixed_step(params, k_caches, v_caches, counts,
                       c_tokens, c_positions, c_block_table, c_new_block_ids,
                       c_total_len, c_chunk_start, c_slot, c_is_final,
                       c_lp_need,
                       d_tokens, d_positions, block_tables, d_seq_lens,
                       d_write_blocks, d_write_offsets,
                       seeds, steps, temps, top_ks, top_ps, min_ps, pres,
                       freqs, reps, prompt_masks, lp_need, lora_tables,
                       lora_ids, proc_masks,
                       g_active=None, g_state=None, c_g_state=None,
                       g_class=None, g_trans=None):
            """ONE fused continuous-batching step: a prefill chunk of one
            sequence (c_* args — the prefill() conventions) rides along with
            the resident decode batch (d_* args — the decode() conventions)
            through a single forward. The packed token buffer is
            [S_pad + B]: the chunk's bucketed tokens first, then one decode
            token per slot; attention is ONE unified ragged launch where row
            0 is the chunk (query_len = chunk_len) and rows 1..B are the
            decode slots (query_len = 1, or 0 when inactive). Sampling
            epilogues are copied verbatim from prefill()/decode() so mixed
            steps are token-identical to the split dispatches."""
            S_pad = c_tokens.shape[0]
            B = d_tokens.shape[0]
            chunk_len = c_total_len - c_chunk_start
            tokens = jnp.concatenate([c_tokens, d_tokens])
            positions = jnp.concatenate([c_positions, d_positions])
            active = d_seq_lens > 0

            def attend(q, k_new, v_new, layer_idx, **extra):
                # extra: per-layer attention variants (sliding window,
                # per-head sinks, softcap — gpt-oss/gemma) thread straight
                # into the unified launch as per-row attributes
                kc, vc = k_caches[layer_idx], v_caches[layer_idx]
                k_c, v_c = k_new[:S_pad], v_new[:S_pad]
                if quantized:
                    # same pad-row zeroing as the prefill attend: bucket
                    # padding must not enter the per-block quantize amax
                    validc = (c_positions < c_total_len)[:, None, None]
                    k_c = jnp.where(validc, k_c, 0.0)
                    v_c = jnp.where(validc, v_c, 0.0)
                kc, vc = att.write_prefill_kv(
                    kc, vc, k_c, v_c, c_new_block_ids
                )
                kc, vc = att.write_decode_kv(
                    kc, vc, k_new[S_pad:], v_new[S_pad:],
                    d_write_blocks, d_write_offsets,
                )
                k_caches[layer_idx], v_caches[layer_idx] = kc, vc
                tables = jnp.concatenate(
                    [c_block_table[None], block_tables], axis=0
                )
                q_starts = jnp.concatenate([
                    jnp.zeros((1,), jnp.int32),
                    S_pad + jnp.arange(B, dtype=jnp.int32),
                ])
                q_lens = jnp.concatenate([
                    chunk_len[None].astype(jnp.int32),
                    active.astype(jnp.int32),
                ])
                row_lens = jnp.concatenate([
                    c_total_len[None].astype(jnp.int32),
                    d_seq_lens.astype(jnp.int32),
                ])
                return ragged_attention(
                    q, kc, vc, tables, q_starts, q_lens, row_lens, **extra
                )

            if lora_enabled:
                # per-row adapter indices threaded through the packed
                # buffer: the chunk's tokens carry its slot's adapter, each
                # decode token its own — batched LoRA rides the same launch
                # (lora/adapters.make_lora_fn per-token branch)
                packed_lora_ids = jnp.concatenate([
                    jnp.full((S_pad,), lora_ids[c_slot], jnp.int32),
                    lora_ids.astype(jnp.int32),
                ])
            else:
                packed_lora_ids = lora_ids
            hidden = call_fwd(
                params, tokens, positions, attend, lora_tables,
                packed_lora_ids,
            )  # [S_pad + B, H]

            # -- decode epilogue: verbatim decode() ---------------------------
            logits = logits_fn(params, mcfg, hidden[S_pad:])  # [B, V]
            pen = apply_penalties(
                logits, counts, prompt_masks, pres, freqs, reps
            )
            pen = run_procs(pen, proc_masks, counts, steps, d_seq_lens)
            if g_active is not None:
                pen = gmask(pen, g_active, g_state, g_class, g_trans)
            toks = sample_tokens(
                pen, seeds, steps, temps, top_ks, top_ps, min_ps
            )
            counts = update_counts(
                counts, toks, active,
                counts_need(pres, freqs, reps, proc_masks),
            )
            lps = logprobs_of(logits, toks)
            tlp_vals, tlp_ids = top_logprobs(logits, lp_need)

            # -- chunk epilogue: verbatim prefill() (slot-sliced args) --------
            def sample_branch(counts):
                last_idx = jnp.argmax(c_positions == c_total_len - 1)
                logits1 = logits_fn(params, mcfg, hidden[last_idx][None])
                pen1 = apply_penalties(
                    logits1, jnp.zeros_like(logits1, jnp.int32),
                    prompt_masks[c_slot][None], pres[c_slot][None],
                    freqs[c_slot][None], reps[c_slot][None],
                )
                pen1 = run_procs(
                    pen1, proc_masks[c_slot][None], counts[c_slot][None],
                    jnp.zeros((1,), jnp.int32), c_total_len[None],
                )
                if g_active is not None:
                    pen1 = gmask(
                        pen1, g_active[c_slot][None],
                        jnp.full((1,), c_g_state, jnp.int32),
                        g_class[c_slot][None], g_trans[c_slot][None],
                    )
                tok1 = sample_tokens(
                    pen1, seeds[c_slot][None], jnp.zeros((1,), jnp.int32),
                    temps[c_slot][None], top_ks[c_slot][None],
                    top_ps[c_slot][None], min_ps[c_slot][None],
                )
                counts = jax.lax.cond(
                    counts_need(
                        pres[c_slot][None], freqs[c_slot][None],
                        reps[c_slot][None], proc_masks[c_slot][None],
                    ),
                    lambda c: c.at[c_slot, tok1[0]].add(1),
                    lambda c: c,
                    counts,
                )
                lp1 = logprobs_of(logits1, tok1)
                tlp_vals1, tlp_ids1 = top_logprobs(logits1, c_lp_need)
                return counts, tok1[0], lp1[0], tlp_vals1[0], tlp_ids1[0]

            def no_sample(counts):
                K = TOP_LOGPROBS_K
                return (
                    counts, jnp.int32(0), jnp.float32(0.0),
                    jnp.zeros((K,), jnp.float32), jnp.zeros((K,), jnp.int32),
                )

            counts, c_tok, c_lp, c_tlp_vals, c_tlp_ids = jax.lax.cond(
                c_is_final, sample_branch, no_sample, counts
            )
            toks, lps, tlp_vals, tlp_ids, c_tok, c_lp, c_tlp_vals, c_tlp_ids = map(
                _fetchable,
                (toks, lps, tlp_vals, tlp_ids, c_tok, c_lp, c_tlp_vals,
                 c_tlp_ids),
            )
            return (k_caches, v_caches, counts, toks, lps, tlp_vals, tlp_ids,
                    c_tok, c_lp, c_tlp_vals, c_tlp_ids)

        def reset_slot(prompt_masks, counts, slot, row):
            return prompt_masks.at[slot].set(row), counts.at[slot].set(0)

        def embed(params, tokens, positions, last_idx):
            """Pooled forward for /v1/embeddings (reference: the Embedding
            model type served by http/service/openai.rs:641): dense causal
            attention (no KV pages touched — embeddings never pollute the
            generation cache), last-token hidden state, L2-normalized.
            Padded tail positions can't affect earlier queries (causal)."""

            def attend(q, k_new, v_new, layer_idx, **extra):
                return att.causal_attention(q, k_new, v_new, **extra)

            hidden = fwd(params, mcfg, tokens, positions, attend)  # [S, H]
            h = hidden[last_idx].astype(jnp.float32)
            return _fetchable(h / jnp.maximum(jnp.linalg.norm(h), 1e-9))

        def embed_chunk(params, k_caches, v_caches, tokens, positions,
                        block_table, new_block_ids, total_len, last_idx,
                        is_final):
            """Chunked pooled forward: inputs past the largest prefill
            bucket run like chunked prefill — each chunk writes its KV into
            TEMPORARY pages (allocated, never committed, released after) and
            attends over the gathered prefix — but no token is sampled; the
            final chunk returns the normalized last-token hidden state."""

            def attend(q, k_new, v_new, layer_idx, **extra):
                k_w, v_w = k_new, v_new
                if quantized:
                    # same pad-row zeroing as the prefill attend: keep
                    # padding out of the per-block quantization amax
                    valid = (positions < total_len)[:, None, None]
                    k_w = jnp.where(valid, k_new, 0.0)
                    v_w = jnp.where(valid, v_new, 0.0)
                kc, vc = att.write_prefill_kv(
                    k_caches[layer_idx], v_caches[layer_idx],
                    k_w, v_w, new_block_ids,
                )
                k_caches[layer_idx], v_caches[layer_idx] = kc, vc
                k_ctx, v_ctx = att.gather_kv(kc, vc, block_table)
                return att.extend_attention(
                    q, k_ctx, v_ctx, positions, total_len, **extra
                )

            hidden = fwd(params, mcfg, tokens, positions, attend)
            vec = jax.lax.cond(
                is_final,
                lambda: (
                    lambda h: h / jnp.maximum(jnp.linalg.norm(h), 1e-9)
                )(hidden[last_idx].astype(jnp.float32)),
                lambda: jnp.zeros((mcfg.hidden_size,), jnp.float32),
            )
            return k_caches, v_caches, _fetchable(vec)

        # ---- speculative decoding programs (docs/speculative_decoding.md) --
        # Correctness rests on two paged-cache properties: (a) overwrite-is-
        # rollback — rejected candidate positions hold stale KV that is never
        # attended (every mask keys off seq_lens) and is overwritten in place
        # when the sequence reaches that position for real; (b) the bonus
        # token is capped so the advance per round is <= spec_k, which keeps
        # the draft cache's coverage invariant (the draft writes positions
        # start..start+k-1 each round, so the next round's reads never
        # outrun its writes) and keeps a horizon's total advance within
        # _prepare_horizon's decode_steps block booking.
        if self.cfg.spec_draft is not None:
            dcfg = self.cfg.spec_draft
            draft_fwd = self._draft_forward
            draft_logits = self._draft_logits
            sk = self.cfg.spec_k
            R = self._spec_rounds
            B = self.cfg.max_batch_size
            draft_use_pallas = (
                use_pallas
                and dcfg.head_dim % 128 == 0
                and dcfg.num_kv_heads % meshlib.tp_size(self.mesh) == 0
                # windowed/softcapped draft families (gpt-oss AND gemma)
                # keep the pure-JAX decode path: the draft loop uses the
                # split decode kernel, which has no per-row attributes —
                # only the MAIN model's windowed layers ride the unified
                # kernel (same auto-rule caution as _resolve_use_pallas)
                and not registry.is_gptoss(dcfg)
                and not registry.is_gemma(dcfg)
            )
            if draft_use_pallas:
                from ..ops import pallas_attention as dpa

                d_mesh = self.mesh
                d_interp = jax.default_backend() != "tpu"

                def draft_paged_attention(q, kc, vc, tables, lens, **extra):
                    return dpa.sharded_paged_decode_attention(
                        d_mesh, meshlib.AXIS_TP, q, kc, vc, tables, lens,
                        interpret=d_interp, **extra,
                    )
            else:
                draft_paged_attention = att.paged_decode_attention

            def draft_prefill_chunk(draft_params, dkc, dvc, tokens, positions,
                                    block_table, new_block_ids, total_len):
                """Write one bucketed chunk of the prompt's DRAFT KV (no
                sampling): same chunk/padding conventions as the main
                prefill so the host reuses _chunk_arrays verbatim."""

                def attend(q, k_new, v_new, layer_idx, **extra):
                    kc, vc = att.write_prefill_kv(
                        dkc[layer_idx], dvc[layer_idx], k_new, v_new,
                        new_block_ids,
                    )
                    dkc[layer_idx], dvc[layer_idx] = kc, vc
                    k_ctx, v_ctx = att.gather_kv(kc, vc, block_table)
                    return att.extend_attention(
                        q, k_ctx, v_ctx, positions, total_len, **extra
                    )

                draft_fwd(draft_params, dcfg, tokens, positions, attend)
                return dkc, dvc

            def spec_multi(params, draft_params, k_caches, v_caches, dkc, dvc,
                           tokens, seq_lens, block_tables, active, steps0,
                           lora_tables, lora_ids):
                """R speculative rounds in one program. Each round: sk greedy
                draft steps over the shadow cache, ONE main forward verifying
                the sk+1 candidate positions (query_len=sk+1 rows of the
                unified ragged kernel — the same launch mixed batching
                uses), then vectorized accept — advance n_match+1 capped at
                sk tokens per row. Packed result [R, B, 1+2sk]: advance count, the sk
                verified tokens, their logprobs. Carry (tokens/seq_lens/
                steps) matches decode_multi's, so spec horizons chain with
                normal ones."""
                bs = cfg.block_size

                def one_round(carry, _):
                    k_caches, v_caches, dkc, dvc, tokens, seq_lens = carry

                    def draft_step(dc, j):
                        dkc, dvc, dt = dc
                        pos = jnp.maximum(seq_lens - 1, 0) + j
                        wb = jnp.where(
                            active,
                            jnp.take_along_axis(
                                block_tables, (pos // bs)[:, None], axis=1
                            )[:, 0],
                            0,
                        )
                        wo = jnp.where(active, pos % bs, 0)

                        def attend(q, k_new, v_new, layer_idx, **extra):
                            kc2, vc2 = att.write_decode_kv(
                                dkc[layer_idx], dvc[layer_idx],
                                k_new[:, 0], v_new[:, 0], wb, wo,
                            )
                            dkc[layer_idx], dvc[layer_idx] = kc2, vc2
                            out = draft_paged_attention(
                                q[:, 0], kc2, vc2, block_tables,
                                seq_lens + j, **extra
                            )
                            return out[:, None]

                        hidden = draft_fwd(
                            draft_params, dcfg, dt[:, None], pos[:, None],
                            attend,
                        )
                        logits = draft_logits(draft_params, dcfg, hidden[:, 0])
                        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                        return (dkc, dvc, nxt), nxt

                    (dkc, dvc, _), drafts = jax.lax.scan(
                        draft_step, (dkc, dvc, tokens), jnp.arange(sk)
                    )
                    cand = jnp.concatenate(
                        [tokens[:, None], drafts.T], axis=1
                    )  # [B, sk+1]
                    start = jnp.maximum(seq_lens - 1, 0)
                    pos = start[:, None] + jnp.arange(sk + 1)[None, :]

                    def attend(q, k_new, v_new, layer_idx, **extra):
                        kc2, vc2 = k_caches[layer_idx], v_caches[layer_idx]
                        for s in range(sk + 1):
                            ps = start + s
                            wb = jnp.where(
                                active,
                                jnp.take_along_axis(
                                    block_tables, (ps // bs)[:, None], axis=1
                                )[:, 0],
                                0,
                            )
                            wo = jnp.where(active, ps % bs, 0)
                            kc2, vc2 = att.write_decode_kv(
                                kc2, vc2, k_new[:, s], v_new[:, s], wb, wo
                            )
                        k_caches[layer_idx], v_caches[layer_idx] = kc2, vc2
                        if not use_pallas:
                            # pure-JAX engines keep the batched extend op:
                            # the unified TWIN scores the whole packed
                            # buffer per row (O(B^2) verify FLOPs) — same
                            # fallback split the prefill/decode paths use
                            return att.paged_extend_attention(
                                q, kc2, vc2, block_tables, start,
                                seq_lens + sk, **extra
                            )
                        # verify rides the UNIFIED ragged kernel: each row
                        # is a segment of query_len = sk+1 candidate tokens
                        # at its context tail — the same launch the mixed
                        # step uses, not a separate prefix-extend entry
                        # point (window/sink/softcap extras included)
                        h, d_ = q.shape[2], q.shape[3]
                        out = ragged_attention(
                            q.reshape(B * (sk + 1), h, d_), kc2, vc2,
                            block_tables,
                            jnp.arange(B, dtype=jnp.int32) * (sk + 1),
                            jnp.where(active, sk + 1, 0).astype(jnp.int32),
                            jnp.where(active, seq_lens + sk, 0).astype(
                                jnp.int32
                            ),
                            **extra,
                        )
                        return out.reshape(B, sk + 1, h, d_)

                    hidden = call_fwd(
                        params, cand, pos, attend, lora_tables, lora_ids
                    )  # [B, sk+1, H]
                    logits = logits_fn(
                        params, mcfg, hidden.reshape(B * (sk + 1), -1)
                    ).reshape(B, sk + 1, -1)
                    m = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    lps = jnp.max(
                        jax.nn.log_softmax(
                            logits.astype(jnp.float32), axis=-1
                        ),
                        axis=-1,
                    )  # logprob of the greedy token at each position
                    match = m[:, :sk] == drafts.T
                    n_acc = jnp.sum(
                        jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1
                    )
                    adv = jnp.where(active, jnp.minimum(n_acc + 1, sk), 0)
                    carry_tok = jnp.where(
                        active,
                        jnp.take_along_axis(
                            m, jnp.maximum(adv - 1, 0)[:, None], axis=1
                        )[:, 0],
                        tokens,
                    )
                    packed_round = jnp.concatenate(
                        [
                            adv.astype(jnp.float32)[:, None],
                            m[:, :sk].astype(jnp.float32),
                            lps[:, :sk],
                        ],
                        axis=-1,
                    )  # [B, 1+2sk]
                    return (
                        (k_caches, v_caches, dkc, dvc, carry_tok,
                         seq_lens + adv),
                        packed_round,
                    )

                (k_caches, v_caches, dkc, dvc, tokens, seq_lens), packed = (
                    jax.lax.scan(
                        one_round,
                        (k_caches, v_caches, dkc, dvc, tokens, seq_lens),
                        None,
                        length=R,
                    )
                )
                next_steps = steps0 + jnp.sum(
                    packed[..., 0], axis=0
                ).astype(jnp.int32)
                return (
                    k_caches, v_caches, dkc, dvc, _fetchable(packed),
                    tokens, seq_lens, next_steps,
                )

            self._draft_prefill_fn = jax.jit(
                draft_prefill_chunk, donate_argnums=(1, 2)
            )
            self._spec_multi_fn = jax.jit(
                spec_multi, donate_argnums=(2, 3, 4, 5)
            )

        self._embed_chunk_fn = jax.jit(embed_chunk, donate_argnums=(1, 2))
        self._mixed_fn = jax.jit(mixed_step, donate_argnums=(1, 2, 3))
        self._prefill_fn = jax.jit(prefill, donate_argnums=(1, 2, 3))
        self._decode_fn = jax.jit(decode, donate_argnums=(1, 2, 3))
        self._decode_multi_fn = jax.jit(decode_multi, donate_argnums=(1, 2, 3))
        self._reset_slot_fn = jax.jit(reset_slot, donate_argnums=(0, 1))
        self._embed_fn = jax.jit(embed)
        if self._mh is not None:
            self._wire_multihost()

    def _wire_multihost(self) -> None:
        """Register every jitted op with the dispatch-replay table.

        ``state_in`` arg positions are the engine-owned globally-sharded
        arrays a follower substitutes with its OWN handles; ``state_out``
        output positions are what both sides store back (the donated caches
        and the device-resident decode carry). Everything else crosses the
        control channel as host numpy — in multi-controller JAX plain numpy
        inputs shard consistently on every process, while a committed
        single-device array cannot feed a mesh-spanning program (which is why
        the leader wrapper also downgrades its own args to numpy).
        """

        def _set_k(v):
            self.k_caches = v

        def _set_v(v):
            self.v_caches = v

        def _set_counts(v):
            self.output_counts = v

        def _set_pmasks(v):
            self.prompt_masks = v

        def _set_dk(v):
            self.draft_k_caches = v

        def _set_dv(v):
            self.draft_v_caches = v

        state_get = {
            "params": lambda: self.params,
            "k": lambda: self.k_caches,
            "v": lambda: self.v_caches,
            "counts": lambda: self.output_counts,
            "pmasks": lambda: self.prompt_masks,
            "lora": self._lora_tables,
        }
        state_set = {
            "k": _set_k, "v": _set_v,
            "counts": _set_counts, "pmasks": _set_pmasks,
        }
        if self.cfg.spec_draft is not None:
            state_get.update({
                "draft_params": lambda: self.draft_params,
                "dk": lambda: self.draft_k_caches,
                "dv": lambda: self.draft_v_caches,
            })
            state_set.update({"dk": _set_dk, "dv": _set_dv})

        def _set_g_active(v):
            self._g_dev_active = v

        def _set_g_class(v):
            self._g_dev_class = v

        def _set_g_trans(v):
            self._g_dev_trans = v

        if self._eplb_enabled:

            def _set_params(v):
                self.params = v

            # EPLB rebalance swaps the whole params pytree (one replayed op)
            state_set["params"] = _set_params
        if self.guided_enabled:
            state_get.update({
                "g_active_dev": lambda: self._g_dev_active,
                "g_class_dev": lambda: self._g_dev_class,
                "g_trans_dev": lambda: self._g_dev_trans,
            })
            state_set.update({
                "g_active_dev": _set_g_active,
                "g_class_dev": _set_g_class,
                "g_trans_dev": _set_g_trans,
            })
        ops = self._mh.router.table(
            ns=self._mh_ns, state_get=state_get, state_set=state_set,
        )
        # guided-arg positions appended to the sampler signatures when the
        # feature is compiled in (engine _build_programs); g_state travels
        # by value (resync) or as the carry sentinel
        g_prefill = (
            # 29 (g_state) travels by value — a scalar resume state
            {28: "g_active_dev", 30: "g_class_dev", 31: "g_trans_dev"}
            if self.guided_enabled else {}
        )
        g_decode = (
            {24: "g_active_dev", 26: "g_class_dev", 27: "g_trans_dev"}
            if self.guided_enabled else {}
        )
        g_multi = (
            {22: "g_active_dev", 24: "g_class_dev", 25: "g_trans_dev"}
            if self.guided_enabled else {}
        )
        ops.register(
            "prefill", self._prefill_fn,
            state_in={0: "params", 1: "k", 2: "v", 3: "counts",
                      19: "pmasks", 23: "lora", **g_prefill},
            state_out={0: "k", 1: "v", 2: "counts"},
        )
        ops.register(
            "decode", self._decode_fn,
            state_in={0: "params", 1: "k", 2: "v", 3: "counts",
                      19: "pmasks", 21: "lora", **g_decode},
            state_out={0: "k", 1: "v", 2: "counts"},
        )
        ops.register(
            "decode_multi", self._decode_multi_fn,
            state_in={0: "params", 1: "k", 2: "v", 3: "counts",
                      17: "pmasks", 19: "lora", **g_multi},
            state_out={0: "k", 1: "v", 2: "counts", 4: "carry_tokens",
                       5: "carry_seq_lens", 6: "carry_steps",
                       **({7: "carry_g"} if self.guided_enabled else {})},
            # tokens/seq_lens/steps arrive either as a host resync (numpy →
            # by value) or as the previous horizon's device carry (jax.Array
            # → sentinel; the follower substitutes its stored carry)
            carry_in={4: "carry_tokens", 5: "carry_seq_lens", 9: "carry_steps",
                      **({23: "carry_g"} if self.guided_enabled else {})},
        )
        if self._eplb_enabled:
            # EPLB rebalance as ONE replayed op: every MoE layer's stacked
            # plan (gather sources + routing tables) applies in a single
            # jitted params update, sharding pinned so the expert dim stays
            # on the EP axis on every process
            especs = registry.param_specs(self.mcfg)["layer"]
            esh = {
                k: NamedSharding(self.mesh, especs[k])
                for k in ("w_gate", "w_up", "w_down")
            }

            def eplb_apply_all(params, srcs, slots, nreps):
                # srcs [n_moe, E+R], slots [n_moe, E, R+1], nreps [n_moe, E]
                layers = []
                j = 0
                for lp in params["layers"]:
                    if "eplb_slots" not in lp:
                        layers.append(lp)
                        continue
                    new = dict(lp)
                    for k in ("w_gate", "w_up", "w_down"):
                        new[k] = jax.lax.with_sharding_constraint(
                            lp[k][srcs[j]], esh[k]
                        )
                    new["eplb_slots"] = slots[j]
                    new["eplb_nrep"] = nreps[j]
                    layers.append(new)
                    j += 1
                return {**params, "layers": layers}

            self._mh_eplb_apply = jax.jit(
                eplb_apply_all, donate_argnums=(0,)
            )
            ops.register(
                "eplb_apply", self._mh_eplb_apply,
                state_in={0: "params"}, state_out={0: "params"},
            )
        if self.guided_enabled:
            # guided-table sync: by-value incremental updates (the [B] mask
            # on admission/release, one slot's rows on a guided admission)
            # that BOTH sides store back — decode dispatches then reference
            # the tables as state, never re-broadcasting them
            grepl = NamedSharding(self.mesh, P())

            def guided_active(a):
                return jnp.asarray(a)

            def guided_row(gc, gt, crow, trow, slot):
                return gc.at[slot].set(crow), gt.at[slot].set(trow)

            self._mh_guided_active = jax.jit(
                guided_active, out_shardings=grepl
            )
            self._mh_guided_row = jax.jit(guided_row, donate_argnums=(0, 1))
            ops.register(
                "guided_active", self._mh_guided_active,
                state_in={}, state_out={0: "g_active_dev"},
            )
            ops.register(
                "guided_row", self._mh_guided_row,
                state_in={0: "g_class_dev", 1: "g_trans_dev"},
                state_out={0: "g_class_dev", 1: "g_trans_dev"},
            )
        ops.register(
            "reset_slot", self._reset_slot_fn,
            state_in={0: "pmasks", 1: "counts"},
            state_out={0: "pmasks", 1: "counts"},
        )
        ops.register("embed", self._embed_fn, state_in={0: "params"}, state_out={})
        if self.cfg.spec_draft is not None:
            # speculative decoding: the spec horizon's carry shares names
            # with decode_multi's, so spec and normal horizons chain on each
            # other across the replay table exactly as in-process
            ops.register(
                "spec_multi", self._spec_multi_fn,
                state_in={0: "params", 1: "draft_params", 2: "k", 3: "v",
                          4: "dk", 5: "dv", 11: "lora"},
                state_out={0: "k", 1: "v", 2: "dk", 3: "dv",
                           5: "carry_tokens", 6: "carry_seq_lens",
                           7: "carry_steps"},
                carry_in={6: "carry_tokens", 7: "carry_seq_lens",
                          10: "carry_steps"},
            )
            ops.register(
                "draft_prefill", self._draft_prefill_fn,
                state_in={0: "draft_params", 1: "dk", 2: "dv"},
                state_out={0: "dk", 1: "dv"},
            )
        if getattr(self, "_embed_chunk_fn", None) is not None:
            ops.register(
                "embed_chunk", self._embed_chunk_fn,
                state_in={0: "params", 1: "k", 2: "v"},
                state_out={0: "k", 1: "v"},
            )

        # KV transfer legs for disaggregation across a multihost group: the
        # gather REPLICATES its output over the mesh (a collective all-gather
        # of the tp shards) so the leader can read the page bytes host-side;
        # the scatter is a replayed collective taking pages by value.
        repl = NamedSharding(self.mesh, P())

        def kv_gather(k_caches, v_caches, ids):
            k = jnp.stack([kc[ids] for kc in k_caches])  # [L, n, bs, kvh, d]
            v = jnp.stack([vc[ids] for vc in v_caches])
            return k, v

        def kv_scatter(k_caches, v_caches, kp, vp, ids):
            new_k = [
                kc.at[ids].set(kp[i].astype(kc.dtype))
                for i, kc in enumerate(k_caches)
            ]
            new_v = [
                vc.at[ids].set(vp[i].astype(vc.dtype))
                for i, vc in enumerate(v_caches)
            ]
            return new_k, new_v

        self._mh_kv_gather = jax.jit(kv_gather, out_shardings=(repl, repl))
        self._mh_kv_scatter = jax.jit(kv_scatter)
        ops.register(
            "kv_gather", self._mh_kv_gather,
            state_in={0: "k", 1: "v"}, state_out={},
        )
        ops.register(
            "kv_scatter", self._mh_kv_scatter,
            state_in={0: "k", 1: "v"}, state_out={0: "k", 1: "v"},
        )
        self._mh_ops = ops
        if self._mh.is_leader:
            self._prefill_fn = ops.leader_fn("prefill")
            self._decode_fn = ops.leader_fn("decode")
            self._decode_multi_fn = ops.leader_fn("decode_multi")
            self._reset_slot_fn = ops.leader_fn("reset_slot")
            self._embed_fn = ops.leader_fn("embed")
            if self.cfg.spec_draft is not None:
                self._spec_multi_fn = ops.leader_fn("spec_multi")
                self._draft_prefill_fn = ops.leader_fn("draft_prefill")
            if self.guided_enabled:
                self._mh_guided_active = ops.leader_fn("guided_active")
                self._mh_guided_row = ops.leader_fn("guided_row")
            if self._eplb_enabled:
                self._mh_eplb_apply = ops.leader_fn("eplb_apply")
            if getattr(self, "_embed_chunk_fn", None) is not None:
                self._embed_chunk_fn = ops.leader_fn("embed_chunk")
            self._mh_kv_gather = ops.leader_fn("kv_gather")
            self._mh_kv_scatter = ops.leader_fn("kv_scatter")

    def follow(self) -> None:
        """Follower process body: replay leader dispatches until stop/EOF.

        The reference's analog is a non-leader TP rank blocking inside the
        engine's collective step loop (components/src/dynamo/vllm/main.py:67);
        here the loop is explicit because each JAX process must issue the
        same XLA programs itself.
        """
        assert self._mh is not None and not self._mh.is_leader
        self._mh_ops.follow()

    # ---------------------------------------------------------------- serving
    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        req = request if isinstance(request, PreprocessedRequest) else (
            PreprocessedRequest.from_obj(request)
        )
        n_prompt = len(req.token_ids) + len(req.prior_token_ids)
        if n_prompt >= self.cfg.max_context:
            raise ValueError(
                f"prompt {n_prompt} tokens exceeds engine max_context "
                f"{self.cfg.max_context}"
            )
        if n_prompt // self.cfg.block_size + 2 > self.cfg.num_blocks:
            # would wait forever in admission — no amount of eviction frees
            # enough pages for this prompt
            raise ContextLengthError(
                f"prompt {n_prompt} tokens cannot fit the KV pool "
                f"({self.cfg.num_blocks} blocks x {self.cfg.block_size})"
            )
        wanted_procs = req.annotations.get("logits_processors") or []
        if wanted_procs:
            known = {n for n, _ in self.cfg.logits_processors}
            bad = [n for n in wanted_procs if n not in known]
            if bad:
                raise InvalidRequestError(f"unknown logits processors {bad!r}")
        lora_name = req.annotations.get("lora")
        if lora_name:
            if self.lora is None:
                raise InvalidRequestError("engine built without LoRA support")
            if self.lora.slot_of(lora_name) == 0:
                raise InvalidRequestError(f"unknown LoRA adapter {lora_name!r}")
        guided_tables = None
        if req.sampling.guided is not None:
            if not self.guided_enabled:
                # soft specs (derived, e.g. from a forced tool_choice —
                # llm/preprocessor.py) degrade to unconstrained sampling;
                # explicit guided_* options fail loudly
                if not req.sampling.guided.get("soft"):
                    raise GuidedRejectedError(
                        "engine built without guided decoding "
                        "(guided_max_states=0)"
                    )
            else:
                try:
                    guided_tables = await self._compile_guided(
                        req.sampling.guided
                    )
                except ValueError:
                    if not req.sampling.guided.get("soft"):
                        raise
                    # reference behavior: a failed tool-choice derivation
                    # logs and serves unconstrained (common_ext.rs:190)
                    log.warning(
                        "soft guided grammar rejected; serving unconstrained"
                    )
        if req.annotations.get("op") == "embed":
            loop = asyncio.get_event_loop()
            block_ids: Optional[List[int]] = None
            S = len(req.token_ids)
            if S > self.cfg.prefill_chunk:
                # long input: temporary pages for the chunked pooled forward
                # (allocated here on the loop thread — the allocator is
                # single-threaded; never committed, released below)
                need = (S + self.cfg.block_size - 1) // self.cfg.block_size
                if not self.allocator.can_allocate(need):
                    raise ValueError(
                        f"no KV capacity for a {S}-token embedding "
                        f"({need} blocks needed); retry later"
                    )
                block_ids = self.allocator.allocate(need)
            try:
                vec = await loop.run_in_executor(
                    self._executor, self._run_embed, list(req.token_ids),
                    block_ids,
                )
            finally:
                if block_ids is not None:
                    self.allocator.release(block_ids)
            yield BackendOutput(
                finish_reason=FINISH_STOP,
                annotations={
                    "embedding": [float(v) for v in vec],
                    "input_tokens": len(req.token_ids),
                },
            )
            return
        self._ensure_loop()
        if req.annotations.get("images"):
            if self.cfg.vision is None:
                raise InvalidRequestError("engine built without a vision tower")
        all_tokens = list(req.token_ids) + list(req.prior_token_ids)
        st = _Seq(
            req=req,
            context=context,
            out_queue=asyncio.Queue(),
            seq=TokenBlockSequence(all_tokens, self.cfg.block_size),
            last_token=all_tokens[-1] if all_tokens else 0,
            guided_tables=guided_tables,
        )
        if guided_tables is not None and req.prior_token_ids:
            # disagg decode hop / migration resume: tokens generated so far
            # (on the prefill worker / the dead worker) already consumed
            # grammar transitions — seed the FSM past them instead of
            # restarting at 0 (which would let the grammar accept a fresh
            # full match appended to the prior output)
            try:
                st.guided_state = guided_tables.walk(
                    0, [int(t) for t in req.prior_token_ids]
                )
            except ValueError as e:
                raise GuidedRejectedError(
                    f"prior tokens violate the guided grammar: {e}"
                ) from e
        if self.cfg.spec_draft is not None:
            s = req.sampling
            st.spec_ok = (
                s.temperature == 0.0
                and s.logprobs == 0
                and s.presence_penalty == 0.0
                and s.frequency_penalty == 0.0
                and s.repetition_penalty == 1.0
                and not wanted_procs
                and guided_tables is None
            )
        if req.annotations.get("images"):
            loop_mm = asyncio.get_event_loop()
            st.mm_embeds, st.mm_mask = await loop_mm.run_in_executor(
                self._executor, self._encode_images, req
            )
            # prior_token_ids (migration replay / disagg decode hop) extend
            # the prompt past token_ids: pad the override arrays to the full
            # prefill length (generated text is never an image span)
            extra = len(all_tokens) - len(st.mm_mask)
            if extra > 0:
                st.mm_embeds = np.concatenate(
                    [st.mm_embeds,
                     np.zeros((extra, st.mm_embeds.shape[1]), np.float32)]
                )
                st.mm_mask = np.concatenate(
                    [st.mm_mask, np.zeros(extra, bool)]
                )
            # placeholder ids hash identically across different images:
            # never match or publish this prompt's blocks. (A future
            # refinement: salt the block hashes with each image's content
            # hash at its placeholder run, making mm prefixes cacheable
            # instead of uncacheable.)
            st.no_cache = True
        # disaggregated decode: pull the prefill worker's KV pages first so
        # admission sees them as a cached prefix (no recompute)
        flight = get_flight_recorder()
        kv_plan = req.kv_transfer
        if (kv_plan and kv_plan.get("tier")
                and getattr(self, "kv_directory", None) is not None
                and kv_plan.get("holder") == self.kv_directory.holder):
            # the planner picked us as the peer: our own G2/G3 already holds
            # these blocks, and the kvbm onboard below imports them without
            # a loopback wire copy. Drop the plan instead of self-fetching.
            kv_plan = None
        if kv_plan and kv_plan.get("address"):
            # global-directory plan (tier=True): pull from the peer's KVBM
            # G2/G3 tiers instead of its device cache. The fetch holds a
            # directory fetch lease that MUST be discharged on every path
            # (RESOURCE-LEAK "fetch-lease"): commit on any import, abort on
            # zero progress or failure — abort IS the recompute fallback,
            # never a stuck request.
            is_tier = bool(kv_plan.get("tier"))
            fetch_lease = (
                self.kv_directory.begin_fetch(
                    kv_plan.get("holder", ""),
                    [int(h) for h in kv_plan.get("hashes", [])],
                )
                if is_tier and self.kv_directory is not None else None
            )
            # the fetch lifecycle lands on the request's timeline (PR 16
            # gap): started/committed/aborted bracket the wire pull, so the
            # attribution plane charges this wait to kv_fetch and a stuck
            # fetch is visible as started-without-terminal
            flight.record(
                req.request_id, "fetch_started",
                holder=kv_plan.get("holder", ""), tier=is_tier,
                blocks=len(kv_plan.get("hashes", [])),
            )
            try:
                got = await self._get_transfer_client().fetch_and_import(
                    kv_plan["address"],
                    [int(h) for h in kv_plan.get("hashes", [])],
                    traceparent=req.annotations.get("traceparent"),
                    stream=bool(kv_plan.get("stream")),
                    tier=is_tier,
                )
                if fetch_lease is not None:
                    if got > 0:
                        self.kv_directory.commit_fetch(fetch_lease, got)
                    else:
                        self.kv_directory.abort_fetch(fetch_lease)
                if got > 0:
                    flight.record(
                        req.request_id, "fetch_committed", tokens=got,
                    )
                else:
                    flight.record(
                        req.request_id, "fetch_aborted",
                        reason="zero_progress",
                    )
                log.debug("imported %d transferred kv tokens for %s", got, req.request_id[:8])
                flight.record(
                    req.request_id, "transfer",
                    tokens=got, address=kv_plan["address"],
                )
            except Exception as e:
                if fetch_lease is not None:
                    self.kv_directory.abort_fetch(fetch_lease)
                log.exception("kv transfer failed; recomputing prefill locally")
                flight.record(
                    req.request_id, "fetch_aborted", reason=str(e)[:200],
                )
                flight.record(
                    req.request_id, "transfer",
                    tokens=0, error=str(e)[:200],
                    address=kv_plan["address"],
                )
        if self.kvbm is not None:
            try:
                await self._onboard_from_kvbm(st)
            except Exception:
                log.exception("kvbm onboard failed; prefilling from scratch")
        # disaggregated prefill: announce our pages on the way out
        is_prefill_side = req.annotations.get("disagg") == "prefill"
        st.sla = spec_from_annotations(req.annotations)
        st.t_queued = time.time_ns()
        queued_fields: Dict[str, Any] = dict(
            prompt_tokens=n_prompt, waiting=len(self._waiting),
        )
        if st.sla is not None:
            # the queued event carries the promise so /debug/requests?id=
            # can compute the budget breakdown (runtime/slo.py) at read time
            queued_fields.update(
                sla_class=st.sla.sla_class,
                ttft_target_s=st.sla.ttft_target_s,
                itl_target_s=st.sla.itl_target_s,
                deadline_s=st.sla.deadline_s,
            )
        flight.record(req.request_id, "queued", **queued_fields)
        self._waiting.append(st)
        self._wake.set()
        while True:
            item = await st.out_queue.get()
            if item is None:
                return
            if (
                is_prefill_side
                and item.finish_reason is not None
                and self.transfer_address is not None
                and not st.no_cache
            ):
                prompt_blocks = len(req.token_ids) // self.cfg.block_size
                item.kv_transfer = {
                    "address": self.transfer_address,
                    "hashes": [int(h) for h in st.seq.sequence_hashes()[:prompt_blocks]],
                    "num_tokens": prompt_blocks * self.cfg.block_size,
                    # this server speaks the block-window streaming protocol
                    "stream": True,
                }
            if item.finish_reason is not None:
                # observability BEFORE the final yield: consumers typically
                # return at the finish frame, which closes this generator at
                # the yield (code after it would never run)
                self._request_finished(st, item.finish_reason)
            yield item
            if item.finish_reason is not None:
                return

    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.create_task(self._loop())

    def stop(self) -> None:
        if self._loop_task is not None:
            self._loop_task.cancel()
        if getattr(self, "kv_directory", None) is not None:
            # drained worker checkpointing out: revoke the directory lease so
            # every advertisement withdraws in one call (peers stop planning
            # fetches against a worker that is gone). Async close rides the
            # running loop; with no loop, store-lease TTL expiry does it.
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                loop = None
            if loop is not None:
                spawn_bg(self.kv_directory.close())
        if self._transfer_server is not None:
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                loop = None  # no running loop (sync teardown): sockets close with us
            if loop is not None:
                # spawn_bg pins the task (the loop only weak-refs it) and
                # logs a failed stop; nothing joins it — stop() is the
                # shutdown path itself
                spawn_bg(self._transfer_server.stop(0.5))
        if getattr(self, "_kv_transfer_srv", None) is not None:
            self._kv_transfer_srv.close()
            if self.transfer_address is not None:
                from .transfer import LOCAL_SERVERS

                LOCAL_SERVERS.pop(self.transfer_address, None)
        self._executor.shutdown(wait=False)
        self._fetch_executor.shutdown(wait=False)
        if self._prep is not None:
            self._prep.stop()
        if self._mh is not None and self._mh.is_leader:
            # broadcasts __stop__ under the dispatch lock so an in-flight
            # dispatch can't slip a collective past the followers' exit
            self._mh_ops.close()

    # ---------------------------------------------------------------- EPLB
    @property
    def _eplb_enabled(self) -> bool:
        return (
            registry.is_moe(self.mcfg)
            and getattr(self.mcfg, "redundant_experts", 0) > 0
        )

    def measure_expert_load(self, token_ids: List[int]) -> np.ndarray:
        """[num_layers, E] tokens-per-logical-expert for a probe batch
        (models/eplb.py probe — dense forward, OFF the serving hot path;
        the reference collects the same statistic from its engines
        periodically). Call from the profiler / an ops endpoint with
        representative prompts, feed the summed counts to
        eplb_rebalance."""
        from ..models import eplb as eplb_mod

        if not self._eplb_enabled:
            raise ValueError("engine model has no EPLB (redundant_experts=0)")
        if self._mh is not None:
            raise ValueError(
                "the load probe is not in the multihost replay table; feed "
                "externally collected counts to eplb_rebalance instead"
            )
        if self._probe_load_fn is None:
            self._probe_load_fn = jax.jit(
                partial(eplb_mod.probe_expert_load, cfg=self.mcfg)
            )
        toks = jnp.asarray(np.asarray(token_ids, np.int32))
        pos = jnp.arange(len(token_ids), dtype=jnp.int32)
        return np.asarray(
            self._probe_load_fn(self.params, token_ids=toks, positions=pos)
        )

    def eplb_rebalance(self, counts: np.ndarray) -> Dict[str, Any]:
        """Re-plan the redundant-expert replicas from measured counts and
        swap the plan into the live params — table updates + a weight
        gather along the (sharded) expert dim, zero recompiles (the slot
        count is static). ``counts``: [E] aggregated, or [L, E] per layer.
        Output tokens are unchanged by construction (replicas carry the
        logical weights; only the load placement moves)."""
        from ..models import eplb as eplb_mod

        if not self._eplb_enabled:
            raise ValueError("engine model has no EPLB (redundant_experts=0)")
        counts = np.asarray(counts, np.float64)
        per_layer = counts.ndim == 2
        ep = meshlib.tp_size(self.mesh)
        E, R = self.mcfg.num_experts, self.mcfg.redundant_experts
        moe_layers = [
            i for i, lp in enumerate(self.params["layers"])
            if "eplb_slots" in lp
        ]
        # validate BEFORE mutating anything: a wrong-length counts vector
        # must not silently broadcast into a do-nothing plan or fail after
        # some layers were already swapped
        if per_layer:
            if counts.shape != (len(moe_layers), E):
                raise ValueError(
                    f"counts shape {counts.shape} != "
                    f"({len(moe_layers)} moe layers, {E} experts)"
                )
        elif counts.shape != (E,):
            raise ValueError(
                f"counts shape {counts.shape} != ({E} experts,)"
            )
        plans = [
            eplb_mod.plan(counts[n] if per_layer else counts, E, R, ep=ep)
            for n in range(len(moe_layers))
        ]

        def _apply() -> None:
            if self._mh is not None:
                # one replayed op applies every layer's plan: followers swap
                # their params handle in lockstep (state_out), shardings
                # pinned inside the jitted update
                self.params = self._mh_eplb_apply(
                    self.params,
                    np.stack([p.phys_src for p in plans]),
                    np.stack([p.slots for p in plans]),
                    np.stack([p.nrep for p in plans]),
                )
            else:
                for n, i in enumerate(moe_layers):
                    self.params["layers"][i] = eplb_mod.apply_plan(
                        self.params["layers"][i], plans[n]
                    )

        # the swap MUST run on the step executor: decode/prefill dispatches
        # read self.params on that (single) thread, and the multihost op
        # DONATES the old buffers — a swap racing an in-flight dispatch
        # would hand it deleted arrays (or, multihost, a stale handle the
        # followers no longer hold)
        self._executor.submit(_apply).result()
        return {
            "layers": len(plans),
            "redundant_experts": R,
            "max_shard_load": (
                plans[0].max_shard_load(
                    counts[0] if per_layer else counts, ep
                ) if plans else None
            ),
        }

    # ------------------------------------------------------- kvbm offload/onboard
    def _enqueue_offload_gather(self, pending: List[Tuple[int, int]]):
        """Event-loop thread: ENQUEUE the device-side page gathers for sealed
        blocks immediately (cheap async dispatch). Enqueue order is what
        guarantees the gather reads the pages before any later-dispatched
        decode/prefill can rewrite them after LRU eviction — the host fetch
        itself can then run lazily on the offload thread."""
        from ..ops import block_copy as bc

        ids = jnp.asarray(np.asarray([bid for bid, _, _ in pending], np.int32))
        gathered = []
        for kc, vc in zip(self.k_caches, self.v_caches):
            if self.kv_quantized:
                # payload + scale pages move as one unit (ops/block_copy)
                gathered.append((
                    bc.gather_blocks_quant(kc, ids),
                    bc.gather_blocks_quant(vc, ids),
                ))
            else:
                gathered.append((kc[ids], vc[ids]))  # [n, bs, kvh, d] each
        return gathered

    def _offload_fetch(self, pending: List[Tuple[int, int, int]], gathered) -> None:
        """Offload thread: fetch the already-gathered pages and hand them to
        the kvbm priority queue (prefix blocks outrank decode blocks; the
        kvbm worker does the tier writes). Best-effort: failures are logged,
        never fatal.

        Tier bytes are the STORAGE format (kvbm/layout.block_shape_for):
        model dtype for float caches — a bf16 model stores bf16 blocks, not
        2x-inflated float32 — and the flat int8+scales codec buffer for
        kv_dtype=int8 (bit-exact round trip, no float detour)."""
        t_offload = time.time_ns()
        offloaded_bytes = 0
        try:
            if self.kv_quantized:
                codec = self._kv_codec()
                n = len(pending)
                pay = np.empty((n,) + codec.payload_shape, np.int8)
                scl = np.empty((n,) + codec.scales_shape, np.float32)
                for li, (kq, vq) in enumerate(gathered):
                    pay[:, li, 0] = np.asarray(kq.data)
                    pay[:, li, 1] = np.asarray(vq.data)
                    scl[:, li, 0] = np.asarray(kq.scale)
                    scl[:, li, 1] = np.asarray(vq.scale)
                for i, (_, h, prio) in enumerate(pending):
                    self.kvbm.offload(
                        h, codec.encode(pay[i], scl[i]), priority=prio
                    )
                offloaded_bytes = len(pending) * codec.nbytes
                return
            store_dtype = np.dtype(self.mcfg.dtype)
            layers = []
            for k_dev, v_dev in gathered:
                k = np.asarray(k_dev, store_dtype)
                v = np.asarray(v_dev, store_dtype)
                layers.append(np.stack([k, v], axis=1))  # [n, 2, bs, kvh, d]
            arr = np.stack(layers, axis=1)               # [n, L, 2, bs, kvh, d]
            for i, (_, h, prio) in enumerate(pending):
                # copy: a view of arr would pin the whole n-block gather
                # buffer in the host tier for as long as one block lives
                self.kvbm.offload(h, arr[i].copy(), priority=prio)
            offloaded_bytes = int(arr.nbytes)
        except Exception:
            log.exception("kv offload failed (continuing without write-through)")
        finally:
            tracer = get_tracer()
            if tracer.enabled and offloaded_bytes:
                # background batch spanning many requests: its own trace,
                # not parented to any one request
                tracer.emit(
                    "kvbm.offload", t_offload, time.time_ns(),
                    blocks=len(pending), bytes=offloaded_bytes,
                )

    def _kv_codec(self):
        """The int8 block codec shared by the KVBM tiers and the native
        transfer arena (kvbm/layout.QuantizedBlockCodec)."""
        from ..kvbm.layout import QuantizedBlockCodec, block_shape_for

        codec = getattr(self, "_kv_codec_cached", None)
        if codec is None:
            codec = self._kv_codec_cached = QuantizedBlockCodec(
                block_shape_for(self.mcfg, self.cfg.block_size, "int8")
            )
        return codec

    def _scatter_blocks(self, local_ids: List[int], arr) -> None:
        """Executor thread: device scatter only — no allocator access here
        (the allocator is single-threaded on the event loop).

        ``arr`` is either float pages [n, L, 2, bs, kvh, d] or, for int8
        caches, a (payload int8 [n, L, 2, bs, kvh, d], scales f32
        [n, L, 2, kvh]) pair that scatters straight into the quantized cache
        — no float detour, bit-exact. Float pages arriving at a quantized
        cache (a float-cache transfer peer) quantize on the way in."""
        if isinstance(arr, tuple) and not self.kv_quantized:
            # quantized pages arriving at a float cache: dequantize
            # host-side BEFORE any branch — the multihost scatter below
            # (multihost engines are always float; int8+mh is gated at
            # construction) must see plain pages too
            from ..ops.quant import dequantize_blocks_np

            arr = dequantize_blocks_np(arr[0], arr[1])
        if self._mh is not None:
            # arr [n, L, 2, ...] -> kp/vp [L, n, ...] by value: the scatter
            # is a replayed collective (eager .at[].set on a mesh spanning
            # processes would be a leader-only dispatch and hang the group)
            kp = np.ascontiguousarray(np.moveaxis(arr[:, :, 0], 0, 1))
            vp = np.ascontiguousarray(np.moveaxis(arr[:, :, 1], 0, 1))
            self.k_caches, self.v_caches = self._mh_kv_scatter(
                self.k_caches, self.v_caches, kp, vp,
                np.asarray(local_ids, np.int32),
            )
            return
        ids = jnp.asarray(np.asarray(local_ids, np.int32))
        if self.kv_quantized:
            from ..ops import block_copy as bc
            from ..ops.quant import QuantizedKV, quantize_blocks_np

            if isinstance(arr, tuple):
                payload, scales = arr
            else:
                payload, scales = quantize_blocks_np(np.asarray(arr))
            for li in range(payload.shape[1]):
                self.k_caches[li] = bc.scatter_blocks_quant(
                    self.k_caches[li], ids,
                    QuantizedKV(
                        jnp.asarray(payload[:, li, 0]),
                        jnp.asarray(np.ascontiguousarray(scales[:, li, 0])),
                    ),
                )
                self.v_caches[li] = bc.scatter_blocks_quant(
                    self.v_caches[li], ids,
                    QuantizedKV(
                        jnp.asarray(payload[:, li, 1]),
                        jnp.asarray(np.ascontiguousarray(scales[:, li, 1])),
                    ),
                )
            return
        dtype = self.mcfg.dtype
        for li in range(arr.shape[1]):
            k = jnp.asarray(arr[:, li, 0], dtype)
            v = jnp.asarray(arr[:, li, 1], dtype)
            self.k_caches[li] = self.k_caches[li].at[ids].set(k)
            self.v_caches[li] = self.v_caches[li].at[ids].set(v)

    async def import_blocks(self, hashes: List[int], arr) -> int:
        """Import [n, L, 2, bs, kvh, d] pages (or an int8 (payload, scales)
        pair — see _scatter_blocks) as content-addressed cached pages.
        Shared by the kv transfer plane and kvbm onboarding. Allocator
        mutations stay on the event-loop thread; only the scatter runs in
        the executor."""
        n = (arr[0] if isinstance(arr, tuple) else arr).shape[0]
        try:
            local_ids = self.allocator.allocate(n)
        except OutOfBlocks:
            log.warning("no room to import %d blocks; skipping", n)
            return 0
        loop = asyncio.get_event_loop()
        try:
            await loop.run_in_executor(self._executor, self._scatter_blocks, local_ids, arr)
        except Exception:
            self.allocator.release(local_ids)
            raise
        for bid, h in zip(local_ids, hashes):
            self.allocator.commit(bid, h)
        self.allocator.release(local_ids)
        if n and self.kv_commits is not None:
            self.kv_commits.fire()
        return n

    async def _onboard_from_kvbm(self, st: "_Seq") -> None:
        """Pull a host/disk-cached prefix into device pages before admission."""
        if self.kvbm is None:
            return
        t_onboard = time.time_ns()
        bs = self.cfg.block_size
        hashes = st.seq.sequence_hashes()[: (len(st.seq) - 1) // bs]
        have = len(self.allocator.match_prefix(hashes))
        loop = asyncio.get_event_loop()
        # match_prefix can hit the G4 remote store (blocking socket): keep it
        # off the event loop, same as the load below
        n = await loop.run_in_executor(
            None, self.kvbm.match_prefix, hashes[have:]
        )
        if n == 0:
            return
        arr = await loop.run_in_executor(None, self.kvbm.load_prefix, hashes[have : have + n])
        if arr is None:
            return
        # format guard: disk/remote tiers survive restarts and are shared
        # fleet-wide, so blobs written under a DIFFERENT kv_dtype (or model
        # shape) can come back under the same content hashes — treat them as
        # a miss and recompute rather than crash the loop or import garbage
        if self.kv_quantized:
            codec = self._kv_codec()
            if (
                arr.dtype != np.uint8 or arr.ndim != 2
                or arr.shape[1] != codec.nbytes
            ):
                log.warning(
                    "kvbm blocks are not this engine's int8 codec format "
                    "(%s %s); skipping onboard — clear stale tiers via "
                    "/clear_kv_blocks", arr.dtype, arr.shape,
                )
                return
            # decode the flat int8+scales buffers to the (payload, scales)
            # pair the quantized scatter takes — the round trip never
            # touches floats, so onboarded blocks are bit-equal to what
            # was offloaded
            arr = codec.decode_many(arr)
        else:
            expect = (
                self.mcfg.num_layers, 2, self.cfg.block_size,
                self.mcfg.num_kv_heads, self.mcfg.head_dim,
            )
            if arr.ndim != 6 or arr.shape[1:] != expect:
                log.warning(
                    "kvbm blocks do not match this engine's KV layout "
                    "(%s vs %s); skipping onboard", arr.shape[1:], expect,
                )
                return
        got = await self.import_blocks(list(hashes[have : have + n]), arr)
        if got:
            log.debug("onboarded %d blocks from kvbm for %s", got, st.req.request_id[:8])
            get_flight_recorder().record(
                st.req.request_id, "onboard",
                blocks=got, tokens=got * bs,
            )
            tracer = get_tracer()
            if tracer.enabled:
                # int8 tiers decode to a (payload, scales) pair above
                nbytes = (
                    sum(int(a[:got].nbytes) for a in arr)
                    if isinstance(arr, tuple) else int(arr[:got].nbytes)
                )
                tracer.emit(
                    "kvbm.onboard", t_onboard, time.time_ns(),
                    traceparent=st.req.annotations.get("traceparent"),
                    request_id=st.req.request_id,
                    blocks=got, bytes=nbytes,
                )

    # ------------------------------------------------------------- step loop
    async def _loop(self) -> None:
        import os as _os

        loop = asyncio.get_event_loop()
        trace = _os.environ.get("DTPU_LOOP_TRACE")
        t_mark = time.perf_counter()

        def mark(phase: str) -> None:
            nonlocal t_mark
            now = time.perf_counter()
            if trace and now - t_mark > 0.002:
                import sys as _sys

                print(f"loop {phase:<10s} {(now - t_mark) * 1e3:6.1f} ms",
                      file=_sys.stderr, flush=True)
            t_mark = now

        try:
            while True:
                if not self._waiting and all(s is None for s in self._slots):
                    self._chains.clear()  # all snapshot seqs are done by now
                    self._wake.clear()
                    await self._wake.wait()
                mark("idle")
                # chaos drill hook: an armed engine.step fault crashes the
                # loop through the real crash path below (error finishes,
                # watchdog dereg, migration replay) — no-op unarmed
                await FAULTS.ainject("engine.step")
                self._admit_cancelled()
                self._try_admit()
                mark("admit")
                # chunked prefill: ONE bounded chunk per tick, so running
                # decodes keep making progress under a long prefill; round-
                # robin across prefilling sequences so a short prompt is not
                # starved behind a long one
                prefilling = [
                    s for s in self._slots
                    if s is not None and not s.done and not s.prefilled
                    and not s.prefill_inflight
                ]
                did_mixed = False
                mixed_blocked = False
                if prefilling:
                    pick = prefilling[self._prefill_rr % len(prefilling)]
                    self._prefill_rr += 1
                    if pick.context.is_stopped():
                        # client gone mid-prefill: stop burning chunks, free
                        # the slot at the next reap
                        pick.done = True
                        pick.out_queue.put_nowait(BackendOutput(
                            finish_reason="cancelled",
                            cumulative_tokens=pick.produced,
                        ))
                    else:
                        if pick.t_prefill_start == 0:
                            pick.t_prefill_start = time.time_ns()
                        chunk_from = pick.prefill_pos
                        # mixed continuous batching: when decode rows are
                        # resident (and no horizon is in flight to carry
                        # stale device state past the fused step), the chunk
                        # rides along with ONE decode step in a single
                        # program — decode never stalls behind the prefill
                        mixed_seqs = None
                        if self.mixed_enabled and not self._chains:
                            snap = self._decode_snapshot()
                            if any(s is not None for s in snap):
                                if self._prepare_mixed(snap):
                                    mixed_seqs = snap
                                else:
                                    # booking failed (block pressure /
                                    # context headroom): this prefill runs
                                    # split, and horizons must keep
                                    # pipelining rather than wait for a
                                    # fused step that cannot book
                                    mixed_blocked = True
                        t_step = time.perf_counter()
                        if mixed_seqs is not None:
                            results, res = await loop.run_in_executor(
                                self._executor, self._run_mixed_step, pick,
                                mixed_seqs,
                            )
                            did_mixed = True
                            self._commit_prefilled_blocks(pick)
                            for rst, tok, lp, tids, tvals in results:
                                self._accept_token(rst, tok, lp, tids, tvals)
                        else:
                            results = []
                            res = await loop.run_in_executor(
                                self._executor, self._run_prefill_chunk, pick
                            )
                            self._commit_prefilled_blocks(pick)
                        if res is not None:
                            fut = self._fetch_executor.submit(
                                self._fetch_prefill_result, *res
                            )
                            task = asyncio.ensure_future(
                                self._finish_prefill(res[0], fut)
                            )
                            self._prefill_tasks.add(task)
                            task.add_done_callback(self._prefill_tasks.discard)
                        self._step_stats(
                            "mixed" if mixed_seqs is not None else "prefill",
                            time.perf_counter() - t_step,
                            (pick.prefill_pos - chunk_from) + len(results),
                        )
                        mark("mixed" if mixed_seqs is not None else "prefill")
                has_active = any(
                    s is not None and not s.done and s.prefilled
                    for s in self._slots
                )
                # top up the horizon pipeline BEFORE fetching the oldest
                # results: readback RTT (hundreds of ms tunneled) overlaps
                # the in-flight horizons' device compute. Dispatch runs on
                # the executor: the first call jit-compiles (30-90s cold)
                # and must not stall the event loop's lease heartbeats.
                # while a mixed-eligible prefill is in progress, the pipeline
                # is NOT topped up: in-flight chains drain (their carry
                # predates the fused step's cache writes), and once empty
                # every tick runs one fused chunk+decode step until the
                # prefill completes — decode keeps advancing, prefill keeps
                # chunking, nothing stalls
                mixed_wait = (
                    self.mixed_enabled and bool(prefilling) and has_active
                    and not mixed_blocked
                )
                while (
                    has_active
                    and not self._waiting
                    and not did_mixed
                    and not mixed_wait
                    and len(self._chains) < self.cfg.decode_pipeline
                    and (not self._chains or self._can_chain(self._chains[-1]))
                    and self._prepare_horizon(depth=len(self._chains) + 1)
                ):
                    prev = self._chains[-1] if self._chains else None
                    snapshot = self._decode_snapshot()
                    chain = await loop.run_in_executor(
                        self._executor, self._dispatch_horizon, prev, snapshot
                    )
                    chain.fetch = self._fetch_executor.submit(np.asarray, chain.packed)
                    self._chains.append(chain)
                    mark("dispatch")
                if self._chains:
                    chain = self._chains.popleft()
                    t_step = time.perf_counter()
                    packed = await asyncio.wrap_future(chain.fetch)
                    mark("fetch")
                    emitted_before = sum(
                        s.produced for s in chain.seqs if s is not None
                    )
                    self._apply_packed(chain, packed)
                    self._step_stats(
                        "decode", time.perf_counter() - t_step,
                        sum(s.produced for s in chain.seqs if s is not None)
                        - emitted_before,
                    )
                    mark("apply")
                elif has_active and not did_mixed:
                    t_step = time.perf_counter()
                    results = await loop.run_in_executor(
                        self._executor, self._run_decode, self._decode_snapshot()
                    )
                    for rst, tok, lp, tids, tvals in results:
                        self._accept_token(rst, tok, lp, tids, tvals)
                    self._step_stats(
                        "decode", time.perf_counter() - t_step, len(results)
                    )
                elif self._prefill_tasks and not prefilling:
                    # nothing to compute until a first-token readback lands:
                    # park instead of busy-spinning through the loop
                    self._wake.clear()
                    try:
                        await asyncio.wait_for(self._wake.wait(), 0.05)
                    except asyncio.TimeoutError:
                        pass
                self._reap_finished()
                if self._offload_pending and self.kvbm is not None:
                    pending, self._offload_pending = self._offload_pending, []
                    # gather ENQUEUE happens here on the loop thread, in
                    # program order before any later horizon dispatch that
                    # could evict+rewrite the pages; only the host fetch is
                    # fire-and-forget (on its own thread so it never delays
                    # the decode executor)
                    gathered = self._enqueue_offload_gather(pending)
                    self._offload_executor.submit(
                        self._offload_fetch, pending, gathered
                    )
                await self._publish_events()
                mark("publish")
                await asyncio.sleep(0)
        except asyncio.CancelledError:
            pass
        except Exception as crash:
            log.exception("engine loop crashed")
            self.healthy = False
            if self.on_crash is not None:
                spawn_bg(self.on_crash(crash))
            for st in list(self._waiting) + [s for s in self._slots if s]:
                st.done = True
                evac = self._evacuation_plan(st)
                st.out_queue.put_nowait(BackendOutput(
                    finish_reason="error", cumulative_tokens=st.produced,
                    annotations={"evacuation": evac} if evac else {},
                ))
                if st.block_ids:
                    self.allocator.release(st.block_ids)
            self._waiting = []
            self._slots = [None] * self.cfg.max_batch_size
            self._seq_lens[:] = 0
            self._chains.clear()

    def _admit_cancelled(self) -> None:
        keep = []
        for st in self._waiting:
            if st.context.is_stopped():
                st.out_queue.put_nowait(
                    BackendOutput(finish_reason="cancelled", cumulative_tokens=0)
                )
            else:
                keep.append(st)
        self._waiting = keep

    def _free_slot(self) -> int:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return -1

    def _try_admit(self) -> List[_Seq]:
        admitted: List[_Seq] = []
        still: List[_Seq] = []
        for st in self._waiting:
            slot = self._free_slot()
            if slot < 0:
                still.append(st)
                continue
            prompt_len = len(st.seq)
            hashes = st.seq.sequence_hashes()
            # reuse at most the blocks strictly before the last prompt token so
            # prefill always has >=1 token to produce logits from
            reusable = min(len(hashes), (prompt_len - 1) // self.cfg.block_size)
            if st.no_cache:
                reusable = 0
            prefix_ids = self.allocator.acquire_prefix(hashes[:reusable])
            prefix_blocks = len(prefix_ids)
            blocks_needed = (
                (prompt_len + self.cfg.block_size - 1) // self.cfg.block_size
                - prefix_blocks
            )
            if not self.allocator.can_allocate(blocks_needed):
                self.allocator.release(prefix_ids)
                still.append(st)
                continue
            try:
                new_ids = self.allocator.allocate(blocks_needed)
            except OutOfBlocks:
                self.allocator.release(prefix_ids)
                still.append(st)
                continue
            st.block_ids = prefix_ids + new_ids
            st.cached_tokens = prefix_blocks * self.cfg.block_size
            # prompt blocks become content-addressed ONLY as their chunks'
            # KV is actually written (_commit_prefilled_blocks after each
            # chunk) — committing at admission would let a concurrent
            # request match pages that hold garbage, and a mid-prefill kill
            # would leak unwritten blocks into the reusable LRU
            st.commit_upto = prefix_blocks
            st.prefill_pos = st.cached_tokens
            st.slot = slot
            self._slots[slot] = st
            self._block_tables[slot].fill(0)
            self._block_tables[slot, : len(st.block_ids)] = st.block_ids
            self._seq_lens[slot] = prompt_len
            s = st.req.sampling
            self._temps[slot] = s.temperature
            self._top_ks[slot] = s.top_k
            self._top_ps[slot] = s.top_p
            self._min_ps[slot] = s.min_p
            self._pres[slot] = s.presence_penalty
            self._freqs[slot] = s.frequency_penalty
            self._reps[slot] = s.repetition_penalty
            self._lp_ns[slot] = min(max(s.logprobs, 0), TOP_LOGPROBS_K)
            seed = s.seed
            self._seeds[slot] = np.uint32(
                seed if seed is not None else self._host_rng.integers(1 << 32)
            )
            self._lora_slots[slot] = (
                self.lora.slot_of(st.req.annotations.get("lora"))
                if self.lora is not None else 0
            )
            self._lp_masks[slot, :] = False
            wanted = st.req.annotations.get("logits_processors") or []
            for k, (pname, _fn) in enumerate(self.cfg.logits_processors):
                if pname in wanted:
                    self._lp_masks[slot, k] = True
            if self.guided_enabled:
                if st.guided_tables is not None:
                    tt = st.guided_tables
                    S_g, C_g = tt.trans.shape
                    self._g_active[slot] = True
                    # guided_state was seeded at generate() (0, or walked
                    # over prior_token_ids for disagg/migration resumes)
                    self._g_state[slot] = st.guided_state
                    V_model = self._g_class.shape[1]
                    n = min(len(tt.class_of), V_model)
                    self._g_class[slot, :n] = tt.class_of[:n]
                    # model vocab beyond the tokenizer vocab has no byte
                    # form: map those ids to column C_g, which stays all -1
                    # (always-reject; the compile gate enforces C_g < cap)
                    self._g_class[slot, n:] = C_g
                    self._g_trans[slot].fill(-1)
                    self._g_trans[slot, :S_g, :C_g] = tt.trans
                    self._g_dirty_slots.add(slot)
                    self._g_active_version += 1
                elif self._g_active[slot]:
                    # previous occupant was guided: drop its mask before the
                    # new request's first dispatch. Non-guided -> non-guided
                    # turnover touches nothing (no upload on plain traffic).
                    self._g_active[slot] = False
                    self._g_active_version += 1
            # penalty tables: reset the slot's rows when this request uses
            # penalties (needs a fresh prompt mask) or a prior occupant left
            # them dirty. One tiny async dispatch; skipped entirely on the
            # common penalties-off path.
            has_pen = (
                s.presence_penalty != 0.0
                or s.frequency_penalty != 0.0
                or s.repetition_penalty != 1.0
            )
            st.counting = has_pen or bool(wanted)
            if st.counting or self._slot_dirty[slot]:
                row = np.zeros(self.mcfg.vocab_size, np.int8)
                if has_pen:
                    ids = np.asarray(st.seq.tokens(), np.int64)
                    # image placeholders sit above the vocab: they are not
                    # sampleable, so they simply don't enter the mask
                    row[ids[ids < self.mcfg.vocab_size]] = 1
                self.prompt_masks, self.output_counts = self._reset_slot_fn(
                    self.prompt_masks, self.output_counts,
                    self._j(np.int32(slot)), self._j(row),
                )
            # counts accumulate for EVERY active slot while anyone counts
            # (update_counts scatters the full batch): a slot that shared a
            # batch with a counting request holds stale counts the next
            # occupant must not inherit
            batch_counting = st.counting or any(
                o is not None and o.counting for o in self._slots if o is not st
            )
            self._slot_dirty[slot] = batch_counting
            if st.counting:
                for j, other in enumerate(self._slots):
                    if other is not None and other is not st:
                        self._slot_dirty[j] = True
            admitted.append(st)
            st.t_admitted = time.time_ns()
            get_flight_recorder().record(
                st.req.request_id, "admitted",
                slot=slot, cached_tokens=st.cached_tokens,
                prompt_tokens=prompt_len,
            )
            log.debug(
                "admit %s: %d tokens (%d cached), slot %d",
                st.req.request_id[:8], prompt_len, st.cached_tokens, slot,
            )
        self._waiting = still
        return admitted

    def _bucket(self, n: int) -> int:
        for b in self.cfg.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"prefill of {n} tokens exceeds largest bucket "
            f"{self.cfg.prefill_buckets[-1]}"
        )

    def _commit_prefilled_blocks(self, st: _Seq) -> None:
        """Event-loop thread, after a chunk lands: content-address the prompt
        blocks whose KV the chunk just wrote (and queue their host-tier
        offload). Only written blocks ever become matchable."""
        if st.no_cache:
            return
        hashes = st.seq.sequence_hashes()
        upto = min(st.prefill_pos // self.cfg.block_size, len(hashes))
        for i in range(st.commit_upto, upto):
            self.allocator.commit(st.block_ids[i], hashes[i])
            if self.kvbm is not None:
                self._offload_pending.append((st.block_ids[i], hashes[i], 0))
        if upto > st.commit_upto and self.kv_commits is not None:
            # wake streaming transfer fetches: this chunk's blocks are now
            # addressable, so a decode-side pull overlapping our remaining
            # prefill compute can ship them immediately
            self.kv_commits.fire()
        st.commit_upto = max(st.commit_upto, upto)

    # -- device calls (run in executor thread) -------------------------------
    def _chunk_arrays(self, token_ids, start: int, chunk_len: int, block_ids):
        """One prefill chunk's padded host arrays (shared by generation
        prefill and chunked embeddings — the padding conventions MUST match:
        pad positions pin to max_context-1, pad rows write scratch block 0).

        Returns (tokens [S_pad], positions [S_pad], new_block_ids
        [S_pad//bs])."""
        bs = self.cfg.block_size
        S_pad = self._bucket(chunk_len)
        tokens = np.zeros(S_pad, np.int32)
        tokens[:chunk_len] = token_ids[start : start + chunk_len]
        positions = np.full(S_pad, self.cfg.max_context - 1, np.int32)
        positions[:chunk_len] = np.arange(start, start + chunk_len)
        new_block_ids = np.zeros(S_pad // bs, np.int32)
        real = block_ids[start // bs :][: S_pad // bs]
        new_block_ids[: len(real)] = real
        return tokens, positions, new_block_ids

    def _take_chunk_arrays(self, st: "_Seq", prompt, start: int,
                           chunk_len: int):
        """One chunk's packed arrays: the async step-prep pipeline's
        prebuild when it matches exactly (engine/prep.py — built and
        uploaded under the PREVIOUS step's device compute), else serial
        ``_chunk_arrays``. Returns ((tokens, positions, new_block_ids),
        device_uploads_or_None); outputs are byte-identical either way."""
        if self._prep is not None:
            got = self._prep.take(
                st.req.request_id, prompt, start, chunk_len, st.block_ids
            )
            if got is not None:
                return got
        return (
            self._chunk_arrays(prompt, start, chunk_len, st.block_ids),
            None,
        )

    def _schedule_next_chunk(self, st: "_Seq", prompt, is_final: bool) -> None:
        """Executor thread, right after a chunk's device call is dispatched
        (device compute is in flight from here): hand the NEXT chunk's
        packing + upload to the prep thread so step N+1's host prep runs
        under step N's device work."""
        if self._prep is None or is_final:
            return
        start = st.prefill_pos
        remaining = len(prompt) - start
        if remaining <= 0:
            return
        chunk_len = min(remaining, self.cfg.prefill_chunk)
        self._prep.schedule(
            st.req.request_id, prompt, start, chunk_len, st.block_ids
        )

    def _advance_draft_prefill(self, st: "_Seq", prompt) -> None:
        """Speculative decoding: bring the DRAFT cache's prompt coverage up
        to the main cache's. Driven off prefill_pos rather than the chunk
        just dispatched so regions the main cache acquired WITHOUT compute
        (prefix-cache hit, disagg/kvbm import set prefill_pos past 0) are
        draft-prefilled too — shared cached blocks get idempotent rewrites
        (same tokens => same draft KV). Draft coverage of the whole prompt
        is what keeps acceptance up; correctness never depends on it.
        Spec-ineligible requests skip it: their draft KV is never read
        (eligible batchmates cover shared prefix blocks themselves).
        Shared by the split prefill dispatch AND the fused mixed step."""
        if self.cfg.spec_draft is None or not st.spec_ok:
            return
        cap = self.cfg.prefill_chunk
        _j = self._j
        while st.draft_prefill_pos < st.prefill_pos:
            dstart = st.draft_prefill_pos
            dlen = min(st.prefill_pos - dstart, cap)
            dtok, dpos, dnb = self._chunk_arrays(
                prompt, dstart, dlen, st.block_ids
            )
            self.draft_k_caches, self.draft_v_caches = (
                self._draft_prefill_fn(
                    self.draft_params, self.draft_k_caches,
                    self.draft_v_caches, _j(dtok), _j(dpos),
                    _j(self._block_tables[st.slot]), _j(dnb),
                    _j(np.int32(dstart + dlen)),
                )
            )
            st.draft_prefill_pos = dstart + dlen

    def _run_prefill_chunk(self, st: _Seq):
        """Prefill ONE bounded chunk of st's prompt (reference chunked
        prefill, protocols.rs:112): writes the chunk's KV pages; the final
        chunk also samples the first token. Returns None for intermediate
        chunks, else the (st, tok, lp, tlp...) acceptance tuple."""
        prompt = st.seq.tokens()
        start = st.prefill_pos
        remaining = len(prompt) - start
        cap = self.cfg.prefill_chunk
        is_final = remaining <= cap
        chunk_len = remaining if is_final else cap
        (tokens, positions, new_block_ids), dev = self._take_chunk_arrays(
            st, prompt, start, chunk_len
        )
        S_pad = len(tokens)  # the bucketed width (_mm_chunk needs it)

        s = st.req.sampling
        total_len = start + chunk_len
        _j = self._j
        d_tokens, d_positions, d_new_blocks = (
            dev if dev is not None
            else (_j(tokens), _j(positions), _j(new_block_ids))
        )
        g_args = ()
        if self.guided_enabled:
            # full versioned device tables, indexed by slot in the program;
            # the FSM state travels by value (0, or walked over prior
            # tokens for disagg/migration resumes)
            ga, gc, gt = self._guided_dev()
            g_args = (ga, _j(np.int32(st.guided_state)), gc, gt)
        (self.k_caches, self.v_caches, self.output_counts, tok, lp, tlp_vals,
         tlp_ids) = self._prefill_fn(
            self.params, self.k_caches, self.v_caches, self.output_counts,
            d_tokens, d_positions,
            _j(self._block_tables[st.slot]),
            d_new_blocks, _j(np.int32(total_len)), _j(np.int32(start)),
            _j(np.array([self._seeds[st.slot]], np.uint32)),
            _j(np.array([0], np.int32)),
            _j(np.array([s.temperature], np.float32)),
            _j(np.array([s.top_k], np.int32)),
            _j(np.array([s.top_p], np.float32)),
            _j(np.array([s.min_p], np.float32)),
            _j(np.array([s.presence_penalty], np.float32)),
            _j(np.array([s.frequency_penalty], np.float32)),
            _j(np.array([s.repetition_penalty], np.float32)),
            self.prompt_masks, _j(np.int32(st.slot)),
            _j(np.bool_(self._lp_ns[st.slot] > 0)),
            _j(np.bool_(is_final)),
            self._lora_tables(), _j(np.int32(self._lora_slots[st.slot])),
            self._dev("proc_masks", self._lp_masks),
            *self._mm_chunk(st, start, chunk_len, S_pad),
            *g_args,
        )
        st.prefill_pos = total_len
        self._schedule_next_chunk(st, prompt, is_final)
        self._advance_draft_prefill(st, prompt)
        if not is_final:
            return None
        # NO sync readback here: converting tok/lp on this thread would pay
        # a full device->host RTT per sequence, serializing admission (the
        # dominant cost at batch>=16 on tunneled devices). The loop fetches
        # on the fetch pool, overlapping RTTs across sequences.
        st.prefill_inflight = True
        tok.copy_to_host_async()
        lp.copy_to_host_async()
        want_tlp = self._lp_ns[st.slot] > 0
        return (st, tok, lp, tlp_ids if want_tlp else None,
                tlp_vals if want_tlp else None)

    def _mm_chunk(self, st: _Seq, start: int, chunk_len: int, S_pad: int):
        """Per-chunk soft-token override arrays for the prefill program.
        Tiny dummies when the engine has no vision tower (statically
        ignored), zeros for text-only requests on a vision engine."""
        if self.cfg.vision is None:
            if self._mh is not None:  # host dummies: see _j
                return (np.zeros((1, 1), self.mcfg.dtype), np.zeros((1,), bool))
            return (jnp.zeros((1, 1), self.mcfg.dtype), jnp.zeros((1,), bool))
        H = self.mcfg.hidden_size
        if st.mm_embeds is None:
            # text-only request on a vision engine: reuse one cached zero
            # pair per bucket instead of uploading S_pad x H zeros per chunk
            cached = self._mm_zero.get(S_pad)
            if cached is None:
                cached = (
                    jnp.zeros((S_pad, H), self.mcfg.dtype),
                    jnp.zeros((S_pad,), bool),
                )
                self._mm_zero[S_pad] = cached
            return cached
        embeds = np.zeros((S_pad, H), np.float32)
        mask = np.zeros((S_pad,), bool)
        span = slice(start, start + chunk_len)
        embeds[:chunk_len] = st.mm_embeds[span]
        mask[:chunk_len] = st.mm_mask[span]
        return (
            jnp.asarray(embeds, self.mcfg.dtype), jnp.asarray(mask)
        )

    def _encode_images(self, req: PreprocessedRequest) -> Tuple[np.ndarray, np.ndarray]:
        """Executor thread: decode+encode each image (through the encoder
        cache) and splice the patch embeddings over the prompt's placeholder
        runs. Returns (mm_embeds [L, H], mm_mask [L])."""
        from ..llm.encoder_cache import content_hash

        vcfg = self.cfg.vision
        H = self.mcfg.hidden_size
        tokens = np.asarray(req.token_ids, np.int64)
        L = len(tokens)
        embeds = np.zeros((L, H), np.float32)
        mask = tokens == self.cfg.image_token_id
        # contiguous placeholder runs, in order, one per image
        runs: List[Tuple[int, int]] = []
        i = 0
        while i < L:
            if mask[i]:
                j = i
                while j < L and mask[j]:
                    j += 1
                runs.append((i, j))
                i = j
            else:
                i += 1
        images = req.annotations.get("images") or []
        if len(runs) != len(images):
            raise ValueError(
                f"prompt has {len(runs)} image placeholder runs but request "
                f"carries {len(images)} images"
            )
        for (a, b), img in zip(runs, images):
            data = img["data"]
            key = content_hash(data)
            feats = self.encoder_cache.get(key)
            if feats is None:
                arr = np.frombuffer(data, np.float32).reshape(img["shape"])
                feats = np.asarray(
                    self._encode_image_fn(self.vision_params, jnp.asarray(arr)),
                    np.float32,
                )
                self.encoder_cache.set(key, feats)
            if b - a != feats.shape[0]:
                raise ValueError(
                    f"image placeholder run of {b - a} tokens != "
                    f"{feats.shape[0]} patch embeddings"
                )
            embeds[a:b] = feats
        return embeds, mask

    def _run_embed(self, token_ids: List[int],
                   block_ids: Optional[List[int]] = None) -> np.ndarray:
        S = len(token_ids)
        if block_ids is None:
            # fits one dispatch: dense causal forward, no pages touched
            S_pad = self._bucket(S)
            tokens = np.zeros(S_pad, np.int32)
            tokens[:S] = token_ids
            positions = np.arange(S_pad, dtype=np.int32)
            vec = self._embed_fn(
                self.params, self._j(tokens), self._j(positions),
                self._j(np.int32(S - 1)),
            )
            return np.asarray(vec)
        # chunked: the caller pre-allocated temporary pages (loop thread
        # owns the allocator); each chunk writes KV + attends over the
        # gathered prefix, the final chunk yields the pooled vector
        cap = self.cfg.prefill_chunk
        table = np.zeros(self.cfg.max_blocks_per_seq, np.int32)
        table[: len(block_ids)] = block_ids
        vec = None
        _j = self._j
        for start in range(0, S, cap):
            chunk_len = min(cap, S - start)
            is_final = start + chunk_len >= S
            tokens, positions, nbi = self._chunk_arrays(
                token_ids, start, chunk_len, block_ids
            )
            (self.k_caches, self.v_caches, vec) = self._embed_chunk_fn(
                self.params, self.k_caches, self.v_caches,
                _j(tokens), _j(positions), _j(table), _j(nbi),
                _j(np.int32(start + chunk_len)),
                _j(np.int32(chunk_len - 1)),
                _j(np.bool_(is_final)),
            )
        return np.asarray(vec)

    def _run_mixed_step(self, st: _Seq, seqs: List[Optional["_Seq"]]):
        """Executor thread: ONE fused dispatch serving st's next prefill
        chunk AND a single decode step for the ``seqs`` snapshot (the mixed
        continuous-batching step; engine _build_programs mixed_step).
        Returns (decode acceptance tuples like _run_decode's, prefill
        result tuple like _run_prefill_chunk's or None for intermediate
        chunks)."""
        prompt = st.seq.tokens()
        start = st.prefill_pos
        remaining = len(prompt) - start
        cap = self.cfg.prefill_chunk
        is_final = remaining <= cap
        chunk_len = remaining if is_final else cap
        (tokens, positions, new_block_ids), dev = self._take_chunk_arrays(
            st, prompt, start, chunk_len
        )
        (d_positions, d_seq_lens, write_blocks, write_offsets, steps) = (
            self._decode_dispatch_arrays(seqs)
        )
        lp_need = bool(np.any((self._lp_ns > 0) & (d_seq_lens > 0)))
        c_lp_need = self._lp_ns[st.slot] > 0
        _j = self._j
        g_args = ()
        if self.guided_enabled:
            # decode rows resync the host FSM states (mixed steps are never
            # chained); the chunk row's state travels by value like prefill
            g_active, g_class, g_trans = self._guided_dev()
            g_args = (
                g_active, _j(self._g_state.copy()),
                _j(np.int32(st.guided_state)), g_class, g_trans,
            )
        d_tokens, d_pos_chunk, d_new_blocks = (
            dev if dev is not None
            else (_j(tokens), _j(positions), _j(new_block_ids))
        )
        (self.k_caches, self.v_caches, self.output_counts, toks, lps,
         tlp_vals, tlp_ids, c_tok, c_lp, c_tlp_vals, c_tlp_ids) = (
            self._mixed_fn(
                self.params, self.k_caches, self.v_caches, self.output_counts,
                d_tokens, d_pos_chunk,
                _j(self._block_tables[st.slot]), d_new_blocks,
                _j(np.int32(start + chunk_len)), _j(np.int32(start)),
                _j(np.int32(st.slot)), _j(np.bool_(is_final)),
                _j(np.bool_(c_lp_need)),
                _j(self._tokens), _j(d_positions),
                _j(self._block_tables), _j(d_seq_lens),
                _j(write_blocks), _j(write_offsets),
                _j(self._seeds), _j(steps),
                _j(self._temps), _j(self._top_ks), _j(self._top_ps),
                _j(self._min_ps), _j(self._pres), _j(self._freqs),
                _j(self._reps),
                self.prompt_masks, _j(np.bool_(lp_need)),
                self._lora_tables(), _j(self._lora_slots),
                self._dev("proc_masks", self._lp_masks),
                *g_args,
            )
        )
        st.prefill_pos = start + chunk_len
        self._schedule_next_chunk(st, prompt, is_final)
        self._advance_draft_prefill(st, prompt)
        results = self._decode_results(seqs, toks, lps, tlp_ids, tlp_vals,
                                       lp_need)
        prefill_res = None
        if is_final:
            # same async-readback protocol as _run_prefill_chunk: the loop
            # hands these to the fetch pool so the D2H RTT overlaps
            st.prefill_inflight = True
            c_tok.copy_to_host_async()
            c_lp.copy_to_host_async()
            prefill_res = (st, c_tok, c_lp,
                           c_tlp_ids if c_lp_need else None,
                           c_tlp_vals if c_lp_need else None)
        return results, prefill_res

    def _book_decode_blocks(
        self, seqs: List[Optional["_Seq"]], extra_tokens: int
    ) -> bool:
        """Pre-allocate pages so every active (prefilled, unfinished)
        sequence in ``seqs`` can absorb ``extra_tokens`` more decode tokens.
        All-or-nothing: on any failure (context headroom, block pressure)
        every block this call took is given back — otherwise the fallback
        path itself starves (the blocks would sit idle until finish). The
        one booking routine behind both the horizon dispatch
        (_prepare_horizon) and the fused mixed step (_prepare_mixed), so
        the split and fused paths can never drift."""
        bs = self.cfg.block_size
        granted: List[Tuple[_Seq, int]] = []  # rollback on partial failure
        ok = True
        for st in seqs:
            if st is None or st.done or not st.prefilled:
                continue
            L = len(st.seq)
            if L + extra_tokens >= self.cfg.max_context:
                ok = False
                break
            needed = (L + extra_tokens) // bs + 1
            extra = needed - len(st.block_ids)
            if extra > 0:
                if not self.allocator.can_allocate(extra):
                    ok = False
                    break
                try:
                    new_ids = self.allocator.allocate(extra)
                except OutOfBlocks:
                    ok = False
                    break
                base = len(st.block_ids)
                st.block_ids.extend(new_ids)
                for off, bid in enumerate(new_ids):
                    self._block_tables[st.slot, base + off] = bid
                granted.append((st, len(new_ids)))
        if not ok:
            for st, count in granted:
                taken = st.block_ids[-count:]
                del st.block_ids[-count:]
                self.allocator.release(taken)
            return False
        return True

    def _prepare_mixed(self, seqs: List[Optional["_Seq"]]) -> bool:
        """Book a mixed step: the chunk's pages were booked at admission
        (_try_admit allocates the whole prompt), so this books the DECODE
        half — every active row gets headroom for the one token the fused
        step advances. False => fall back to the split prefill dispatch."""
        return self._book_decode_blocks(seqs, 1)

    def _prepare_horizon(self, depth: int = 1) -> bool:
        """Pre-allocate pages so every active sequence can absorb ``depth``
        more decode horizons (depth=2 when dispatching on top of an in-flight
        chain). False => fall back to the single-step program (block pressure
        or a sequence within a horizon of max_context)."""
        n = self.cfg.decode_steps
        if n <= 1:
            return False
        return self._book_decode_blocks(self._slots, depth * n)

    def _lora_tables(self):
        return self.lora.tables() if self.lora is not None else {}

    def _fetch_prefill_result(self, st, tok, lp, tlp_ids, tlp_vals):
        """Fetch pool thread: the blocking device->host conversion."""
        return (
            st, int(tok), float(lp),
            np.asarray(tlp_ids) if tlp_ids is not None else None,
            np.asarray(tlp_vals) if tlp_vals is not None else None,
        )

    async def _finish_prefill(self, st: "_Seq", fut) -> None:
        """Loop thread: apply a prefill's first token once its readback
        lands; the sequence becomes decode-eligible here."""
        try:
            _st, tok, lp, tlp_ids, tlp_vals = await asyncio.wrap_future(fut)
        except Exception:
            # readback died: fail the request instead of wedging the slot
            # (prefill_inflight stuck True would exclude it from every list
            # forever and busy-spin the loop)
            log.exception("prefill readback failed")
            st.prefill_inflight = False
            st.done = True
            evac = self._evacuation_plan(st)
            st.out_queue.put_nowait(BackendOutput(
                finish_reason="error", cumulative_tokens=st.produced,
                annotations={"evacuation": evac} if evac else {},
            ))
            self._wake.set()
            return
        st.prefill_inflight = False
        if st.done or self._slots[st.slot] is not st:
            return  # cancelled/reaped while the fetch was in flight
        st.prefilled = True
        self._accept_token(st, tok, lp, tlp_ids, tlp_vals)
        self._wake.set()

    def _j(self, host_val):
        """Dispatch-arg placement: single-process uploads eagerly
        (jnp.asarray, the tuned tunnel path); multihost passes host numpy
        through — the leader wrapper broadcasts host data, and pulling an
        uploaded array straight back would pay a blocking D2H per arg."""
        return host_val if self._mh is not None else jnp.asarray(host_val)

    def _dev(self, name: str, host_arr: np.ndarray) -> jax.Array:
        """Device-resident copy of a slot array, re-uploaded only on change
        (host<->device transfers are ~100ms RPCs on tunneled TPUs)."""
        if self._mh is not None:
            # multihost dispatches travel as host numpy anyway (the leader
            # wrapper would immediately pull a device copy back); snapshot so
            # later slot mutations can't race the in-flight frame
            return host_arr.copy()
        cached = self._dev_cache.get(name)
        if cached is None or not np.array_equal(
            self._dev_cache.get(name + "/host"), host_arr
        ):
            self._dev_cache[name] = jnp.asarray(host_arr)
            self._dev_cache[name + "/host"] = host_arr.copy()
        return self._dev_cache[name]

    async def _compile_guided(self, spec: Dict[str, Any]):
        """Grammar spec -> TokenTables, compiled off the event loop and
        cached by content (concurrent requests overwhelmingly share one
        schema). Raises ValueError for malformed grammars or ones whose
        automaton exceeds the engine's device-table caps."""
        import json as _json

        from ..guided import (
            RegexError, SchemaError, build_token_tables, compile_regex,
            guided_regex_pattern,
        )

        kind = spec.get("kind")
        key = _json.dumps(spec, sort_keys=True, default=str)

        def compile_():
            pattern = guided_regex_pattern(kind, spec.get("value"))
            # construction bound: subset construction can overshoot before
            # minimization shrinks it (generic JSON: ~5x), so allow headroom
            # over the engine cap — but check the MINIMIZED count before the
            # O(S x V) token product materializes anything vocab-sized
            dfa = compile_regex(
                pattern,
                max_states=min(32768, 32 * self.cfg.guided_max_states),
            )
            if dfa.num_states > self.cfg.guided_max_states:
                raise ValueError(
                    f"guided grammar needs {dfa.num_states} states > engine "
                    f"cap {self.cfg.guided_max_states}"
                )
            return build_token_tables(dfa, self._g_vocab, self._g_eos)

        def checked_compile():
            tt = compile_()
            if tt.num_classes >= self.cfg.guided_max_classes:
                # strict: column C_g of the padded table is the always-
                # reject class for model-vocab ids beyond the tokenizer
                # vocab
                raise ValueError(
                    f"guided grammar needs {tt.num_classes} token classes "
                    f">= engine cap {self.cfg.guided_max_classes}"
                )
            return tt

        # cache the in-flight FUTURE, not just the result: a burst of
        # requests sharing one schema (the common case) must not each run
        # the O(S x V) token-table product concurrently
        loop = asyncio.get_event_loop()
        task = self._g_cache.get(key)
        if task is None:
            task = asyncio.ensure_future(
                loop.run_in_executor(self._fetch_executor, checked_compile)
            )
            if len(self._g_cache) > 32:
                self._g_cache.pop(next(iter(self._g_cache)))
            self._g_cache[key] = task
        try:
            return await asyncio.shield(task)
        except (RegexError, SchemaError, ValueError) as e:
            # failures don't poison the cache (a later identical request
            # re-validates — caps may be config-reloaded across restarts)
            if self._g_cache.get(key) is task:
                del self._g_cache[key]
            raise GuidedRejectedError(f"guided grammar rejected: {e}") from e

    def _guided_dev(self):
        """Device copies of the guided tables. The [B] active mask
        re-uploads on its own version (admissions AND releases move it);
        the big tables upload once, then changed SLOTS scatter in as row
        updates (.at[slot].set — only the row crosses host->device, the
        rest is an on-device copy). [B, S, C] is far too big for _dev's
        per-dispatch content compare or per-admission full re-upload.

        Multihost: the tables are replay STATE — the leader pushes the same
        incremental updates through the guided_active/guided_row ops, so
        followers' handles stay in step and the decode dispatches reference
        them as state_in instead of broadcasting megabytes per horizon."""
        if self._mh is not None:
            if self._dev_cache.get("g/aver") != self._g_active_version:
                self._g_dev_active = self._mh_guided_active(
                    self._g_active.copy()
                )
                self._dev_cache["g/aver"] = self._g_active_version
            if self._g_dirty_slots:
                for slot in sorted(self._g_dirty_slots):
                    self._g_dev_class, self._g_dev_trans = (
                        self._mh_guided_row(
                            self._g_dev_class, self._g_dev_trans,
                            self._g_class[slot].copy(),
                            self._g_trans[slot].copy(),
                            np.int32(slot),
                        )
                    )
                self._g_dirty_slots.clear()
            return self._g_dev_active, self._g_dev_class, self._g_dev_trans
        if self._dev_cache.get("g/aver") != self._g_active_version:
            self._dev_cache["g/active"] = jnp.asarray(self._g_active)
            self._dev_cache["g/aver"] = self._g_active_version
        if self._dev_cache.get("g/class") is None:
            self._dev_cache["g/class"] = jnp.asarray(self._g_class)
            self._dev_cache["g/trans"] = jnp.asarray(self._g_trans)
            self._g_dirty_slots.clear()
        elif self._g_dirty_slots:
            gc, gt = self._dev_cache["g/class"], self._dev_cache["g/trans"]
            for slot in sorted(self._g_dirty_slots):
                gc = gc.at[slot].set(jnp.asarray(self._g_class[slot]))
                gt = gt.at[slot].set(jnp.asarray(self._g_trans[slot]))
            self._dev_cache["g/class"], self._dev_cache["g/trans"] = gc, gt
            self._g_dirty_slots.clear()
        return (
            self._dev_cache["g/active"],
            self._dev_cache["g/class"],
            self._dev_cache["g/trans"],
        )

    def _decode_snapshot(self) -> List[Optional["_Seq"]]:
        """Loop-thread snapshot of decode-eligible slots. MUST be taken on
        the loop thread in the same tick as _can_chain/_prepare_horizon: an
        async prefill finishing mid-dispatch would otherwise widen the
        active mask after those checks (stale carry token -> wrong KV)."""
        return [
            st if (st is not None and not st.done and st.prefilled) else None
            for st in self._slots
        ]

    def _dispatch_horizon(
        self, chain: Optional[_Chain], seqs: List[Optional["_Seq"]]
    ) -> _Chain:
        """Enqueue one multi-step decode over the loop-thread ``seqs``
        snapshot. With ``chain`` given, the carry (tokens/seq_lens/steps)
        comes straight from the in-flight dispatch — no host round-trip;
        otherwise it is synced up from host state."""
        B = self.cfg.max_batch_size
        active = np.zeros(B, bool)
        for i, st in enumerate(seqs):
            if st is not None:
                active[i] = True
        if chain is not None:
            tokens, seq_lens, steps = chain.tokens, chain.seq_lens, chain.steps
        else:
            seq_lens_np = np.zeros(B, np.int32)
            steps_np = np.zeros(B, np.int32)
            for i, st in enumerate(seqs):
                if st is None:
                    continue
                seq_lens_np[i] = len(st.seq)
                steps_np[i] = st.produced
                self._tokens[i] = st.last_token
            # host numpy feeds jit directly (same H2D copy jnp.asarray paid);
            # snapshot _tokens — the loop mutates it after dispatch. In
            # multihost mode numpy-vs-jax.Array is also the carry/resync
            # signal (engine _wire_multihost carry_in).
            tokens = self._tokens.copy()
            seq_lens = seq_lens_np
            steps = steps_np

        if self.cfg.spec_draft is not None and self._spec_eligible(seqs):
            (self.k_caches, self.v_caches, self.draft_k_caches,
             self.draft_v_caches, packed, tokens, seq_lens, steps) = (
                self._spec_multi_fn(
                    self.params, self.draft_params, self.k_caches,
                    self.v_caches, self.draft_k_caches, self.draft_v_caches,
                    tokens, seq_lens,
                    self._dev("tables", self._block_tables),
                    self._dev("active", active),
                    steps,
                    self._lora_tables(),
                    self._dev("lora_slots", self._lora_slots),
                )
            )
            packed.copy_to_host_async()
            return _Chain(
                packed, tokens, seq_lens, steps, seqs,
                spec_k=self.cfg.spec_k,
            )

        g_args = ()
        if self.guided_enabled:
            g_active, g_class, g_trans = self._guided_dev()
            g_state = (
                chain.g_state
                if chain is not None and chain.g_state is not None
                else self._g_state.copy()
            )
            g_args = (g_active, g_state, g_class, g_trans)
        res = self._decode_multi_fn(
            self.params, self.k_caches, self.v_caches, self.output_counts,
            tokens, seq_lens,
            self._dev("tables", self._block_tables),
            self._dev("active", active),
            self._dev("seeds", self._seeds),
            steps,
            self._dev("temps", self._temps),
            self._dev("top_ks", self._top_ks),
            self._dev("top_ps", self._top_ps),
            self._dev("min_ps", self._min_ps),
            self._dev("pres", self._pres),
            self._dev("freqs", self._freqs),
            self._dev("reps", self._reps),
            self.prompt_masks,
            jnp.bool_(bool(np.any(self._lp_ns[active] > 0))),
            self._lora_tables(),
            self._dev("lora_slots", self._lora_slots),
            self._dev("proc_masks", self._lp_masks),
            *g_args,
        )
        g_state_out = None
        if self.guided_enabled:
            (self.k_caches, self.v_caches, self.output_counts, packed,
             tokens, seq_lens, steps, g_state_out) = res
        else:
            (self.k_caches, self.v_caches, self.output_counts, packed,
             tokens, seq_lens, steps) = res
        # start the D2H readback immediately: by the time this horizon's turn
        # to be applied comes (decode_pipeline-1 horizons later) the bytes
        # are already on host and np.asarray is a no-wait copy
        packed.copy_to_host_async()
        return _Chain(
            packed, tokens, seq_lens, steps, seqs, g_state=g_state_out
        )

    def _spec_eligible(self, seqs: List[Optional["_Seq"]]) -> bool:
        """Every active row must be greedy with no sampling-state coupling:
        temperature 0 (verify argmax == sample_tokens at temp 0), no
        penalties / logits processors (spec skips the counts machinery), no
        top-logprobs (the packed spec format carries token logprobs only).
        Mixed batches fall back to the normal horizon for the whole dispatch
        — eligibility is per-request-static, so the set only changes on
        admission/finish, which already breaks chains via _can_chain."""
        for i, st in enumerate(seqs):
            if st is None:
                continue
            if (
                self._temps[i] != 0.0
                or self._lp_ns[i] != 0
                or self._pres[i] != 0.0
                or self._freqs[i] != 0.0
                or self._reps[i] != 1.0
                or bool(self._lp_masks[i].any())
                # guided rows need the per-step FSM mask, which the spec
                # draft/verify programs do not carry
                or (self.guided_enabled and bool(self._g_active[i]))
            ):
                return False
        return True

    def _can_chain(self, chain: _Chain) -> bool:
        """A new horizon may ride on ``chain``'s device carry only if every
        currently-active slot holds the same sequence it held at dispatch —
        an admission into a recycled slot would decode from a stale carry."""
        for i, st in enumerate(self._slots):
            if (
                st is not None and not st.done and st.prefilled
                and chain.seqs[i] is not st
            ):
                return False
        return True

    def _apply_packed(self, chain: _Chain, packed_np: np.ndarray) -> None:
        """Apply one consumed horizon [N, B, 2+2K]: feed each snapshot slot's
        tokens through stop handling in order; the speculated tail past a
        finish is discarded. Each sequence's surviving tokens leave as ONE
        BackendOutput — per-token queue round-trips made horizon emission
        the dominant serving cost at batch>=16 (~1ms/token of asyncio churn
        against a ~0.9ms/token device program)."""
        if chain.spec_k is not None:
            return self._apply_packed_spec(chain, packed_np)
        K = TOP_LOGPROBS_K
        toks = packed_np[:, :, 0].astype(np.int32)
        lps = packed_np[:, :, 1]
        tlp_ids = packed_np[:, :, 2 : 2 + K].astype(np.int32)
        tlp_vals = packed_np[:, :, 2 + K :]
        for i, st in enumerate(chain.seqs):
            if st is None or st.done:
                continue
            want_tlp = st.req.sampling.logprobs > 0
            self._accept_tokens(
                st, [int(t) for t in toks[:, i]], [float(x) for x in lps[:, i]],
                tlp_ids[:, i] if want_tlp else None,
                tlp_vals[:, i] if want_tlp else None,
            )

    def _apply_packed_spec(self, chain: _Chain, packed_np: np.ndarray) -> None:
        """Apply one speculative horizon [R, B, 1+2k]: each round contributed
        a variable 1..k tokens per row (the advance count in column 0); the
        rest flows through the same _accept_tokens stop handling as a normal
        horizon."""
        sk = chain.spec_k
        R = packed_np.shape[0]
        for i, st in enumerate(chain.seqs):
            if st is None or st.done:
                continue
            toks: List[int] = []
            lps: List[float] = []
            for r in range(R):
                adv = int(packed_np[r, i, 0])
                row = packed_np[r, i]
                toks.extend(int(t) for t in row[1 : 1 + adv])
                lps.extend(float(x) for x in row[1 + sk : 1 + sk + adv])
            self.spec_stats["rounds"] += R
            self.spec_stats["emitted"] += len(toks)
            self._accept_tokens(st, toks, lps, None, None)

    def _decode_dispatch_arrays(self, seqs: List[Optional["_Seq"]]):
        """Per-slot host arrays for ONE decode step over the ``seqs``
        snapshot — shared by _run_decode and _run_mixed_step so the
        write-block math and carry conventions can never drift between the
        split and fused paths. Also refreshes self._tokens with each row's
        fed token. Returns (positions, seq_lens, write_blocks,
        write_offsets, steps), all [B]."""
        bs = self.cfg.block_size
        B = self.cfg.max_batch_size
        positions = np.zeros(B, np.int32)
        seq_lens = np.zeros(B, np.int32)
        write_blocks = np.zeros(B, np.int32)
        write_offsets = np.zeros(B, np.int32)
        steps = np.zeros(B, np.int32)
        for i, st in enumerate(seqs):
            if st is None:
                continue
            L = len(st.seq)                    # includes the token being fed
            positions[i] = L - 1
            seq_lens[i] = L
            self._tokens[i] = st.last_token
            write_blocks[i] = st.block_ids[(L - 1) // bs]
            write_offsets[i] = (L - 1) % bs
            steps[i] = st.produced
        return positions, seq_lens, write_blocks, write_offsets, steps

    def _decode_results(self, seqs: List[Optional["_Seq"]], toks, lps,
                        tlp_ids, tlp_vals, lp_need: bool):
        """Device outputs of one decode step -> per-sequence acceptance
        tuples (shared by _run_decode and _run_mixed_step)."""
        toks_np = np.asarray(toks)
        lps_np = np.asarray(lps)
        tlp_ids_np = np.asarray(tlp_ids) if lp_need else None
        tlp_vals_np = np.asarray(tlp_vals) if lp_need else None
        results = []
        for i, st in enumerate(seqs):
            if st is None:
                continue
            if self._lp_ns[i] > 0 and tlp_ids_np is not None:
                results.append((st, int(toks_np[i]), float(lps_np[i]),
                                tlp_ids_np[i], tlp_vals_np[i]))
            else:
                results.append(
                    (st, int(toks_np[i]), float(lps_np[i]), None, None)
                )
        return results

    def _run_decode(self, seqs: List[Optional["_Seq"]]) -> List[Tuple[_Seq, int, float]]:
        (positions, seq_lens, write_blocks, write_offsets, steps) = (
            self._decode_dispatch_arrays(seqs)
        )
        lp_need = bool(np.any((self._lp_ns > 0) & (seq_lens > 0)))
        _j = self._j
        g_args = ()
        if self.guided_enabled:
            g_active, g_class, g_trans = self._guided_dev()
            # single-step dispatches are never chained: the host FSM state
            # (walked in _accept_tokens) is authoritative
            g_args = (g_active, _j(self._g_state.copy()), g_class, g_trans)
        (self.k_caches, self.v_caches, self.output_counts, toks, lps,
         tlp_vals, tlp_ids) = self._decode_fn(
            self.params, self.k_caches, self.v_caches, self.output_counts,
            _j(self._tokens), _j(positions),
            _j(self._block_tables), _j(seq_lens),
            _j(write_blocks), _j(write_offsets),
            _j(self._seeds), _j(steps),
            _j(self._temps),
            _j(self._top_ks), _j(self._top_ps),
            _j(self._min_ps), _j(self._pres),
            _j(self._freqs), _j(self._reps),
            self.prompt_masks, _j(np.bool_(lp_need)),
            self._lora_tables(), _j(self._lora_slots),
            self._dev("proc_masks", self._lp_masks),
            *g_args,
        )
        return self._decode_results(seqs, toks, lps, tlp_ids, tlp_vals,
                                    lp_need)

    # -- host-side token bookkeeping -----------------------------------------
    def _accept_token(
        self,
        st: _Seq,
        tok: int,
        logprob: float,
        tlp_ids: Optional[np.ndarray] = None,
        tlp_vals: Optional[np.ndarray] = None,
    ) -> None:
        self._accept_tokens(
            st, [tok], [logprob],
            tlp_ids[None] if tlp_ids is not None else None,
            tlp_vals[None] if tlp_vals is not None else None,
        )

    def _accept_tokens(
        self,
        st: _Seq,
        toks: List[int],
        logprobs: List[float],
        tlp_ids: Optional[np.ndarray] = None,   # [N, K]
        tlp_vals: Optional[np.ndarray] = None,  # [N, K]
    ) -> None:
        """Runs in the executor thread: pure host state mutation. Processes a
        run of sampled tokens for one sequence (a decode horizon, or a single
        token) and emits ONE BackendOutput; tokens past a finish are the
        discarded speculative tail."""
        emit_ids: List[int] = []
        emit_lps: List[float] = []
        tlp: Optional[List[Dict[int, float]]] = None
        n_tlp = min(st.req.sampling.logprobs, TOP_LOGPROBS_K)
        if n_tlp > 0 and tlp_ids is not None:
            tlp = []
        finish: Optional[str] = None
        first_ann = st.produced == 0
        stop_ids = set(st.req.stop.stop_token_ids)
        limit = st.req.stop.max_tokens
        cancelled = st.context.is_stopped()

        for n, tok in enumerate(toks):
            st.produced += 1
            # engine-level stop ids only; the worker Backend layer enforces
            # the tokenizer-specific EOS (llm/backend.py)
            if tok in stop_ids and st.produced > st.req.stop.min_tokens:
                finish = FINISH_STOP
                break  # stop token excluded from output
            emit_ids.append(tok)
            emit_lps.append(logprobs[n])
            if tlp is not None:
                tlp.append({
                    int(i): float(v)
                    for i, v in zip(tlp_ids[n][:n_tlp], tlp_vals[n][:n_tlp])
                })
            if limit is not None and st.produced >= limit:
                finish = FINISH_LENGTH
            elif cancelled:
                finish = "cancelled"

            if finish is None:
                L_before = len(st.seq)
                if L_before + 1 >= self.cfg.max_context:
                    finish = FINISH_LENGTH
                else:
                    sealed = st.seq.append(tok)
                    st.last_token = tok
                    if sealed is not None and not st.no_cache:
                        self.allocator.commit(
                            st.block_ids[sealed.position], sealed.sequence_hash
                        )
                        if self.kvbm is not None:
                            self._offload_pending.append(
                                (st.block_ids[sealed.position], sealed.sequence_hash, 1)
                            )
                    # ensure a block exists for the NEXT token's write position
                    needed_blocks = (L_before + 1) // self.cfg.block_size + 1
                    if needed_blocks > len(st.block_ids):
                        try:
                            (new_id,) = self.allocator.allocate(1)
                            st.block_ids.append(new_id)
                            self._block_tables[st.slot, len(st.block_ids) - 1] = new_id
                        except OutOfBlocks:
                            finish = FINISH_LENGTH  # out of memory: end gracefully
            if finish is not None:
                break

        if st.guided_tables is not None and emit_ids:
            # host replay of the device FSM over the tokens that survived
            # stop handling: authoritative for the next unchained dispatch.
            # Device-sampled tokens are always legal under the mask, so a
            # step failure means table corruption — fail the request, not
            # the engine loop.
            try:
                st.guided_state = st.guided_tables.walk(
                    st.guided_state, emit_ids
                )
                if 0 <= st.slot < len(self._g_state):
                    self._g_state[st.slot] = st.guided_state
            except ValueError:
                log.exception("guided FSM desync")
                finish = FINISH_ERROR

        ann: Dict[str, Any] = {}
        if first_ann:
            ann = {
                "cached_tokens": st.cached_tokens,
                "input_tokens": len(st.req.token_ids),
            }
            # echo the router's routing decision back on the metrics frame
            # (protocols/common.py documents worker_id as a first-chunk
            # annotation) so the frontend's flight record can attribute the
            # request to the worker that actually served it
            wid = (st.req.annotations or {}).get("worker_id")
            if wid is not None:
                ann["worker_id"] = wid
        if first_ann and (emit_ids or finish is not None) and st.t_first_token == 0:
            st.t_first_token = time.time_ns()
            get_flight_recorder().record(
                st.req.request_id, "first_token", slot=st.slot,
            )
        out = BackendOutput(
            token_ids=emit_ids,
            finish_reason=finish,
            cumulative_tokens=st.produced,
            logprobs=emit_lps if emit_ids else None,
            top_logprobs=tlp if (tlp and emit_ids) else None,
            annotations=ann,
        )
        st.out_queue.put_nowait(out)
        if finish is not None:
            st.done = True

    def _reap_finished(self) -> None:
        for i, st in enumerate(self._slots):
            if st is None:
                continue
            if st.done or st.context.is_killed():
                self.allocator.release(st.block_ids)
                self._slots[i] = None
                self._seq_lens[i] = 0
                if self.guided_enabled and self._g_active[i]:
                    # freed slot must not mask the next occupant's first
                    # dispatch (admission overwrites the tables, but a
                    # non-guided successor would otherwise inherit them)
                    self._g_active[i] = False
                    self._g_active_version += 1
                if not st.done:
                    st.out_queue.put_nowait(
                        BackendOutput(finish_reason="cancelled", cumulative_tokens=st.produced)
                    )

    def _request_finished(self, st: "_Seq", finish_reason: str) -> None:
        """Emit the request's engine-phase spans (queue / prefill / decode,
        parented on the cross-plane traceparent annotation) and close its
        flight-recorder timeline. Host-side bookkeeping only."""
        flight = get_flight_recorder()
        rid = st.req.request_id
        if st.sla is not None:
            self._slo_finished(st, finish_reason)
        flight.finish(
            rid,
            error=("engine error finish" if finish_reason == FINISH_ERROR else None),
            error_class="engine_error" if finish_reason == FINISH_ERROR else None,
            finish_reason=finish_reason,
            tokens=st.produced,
            **({"sla_class": st.sla.sla_class} if st.sla is not None else {}),
        )
        # critical-path attribution (runtime/attribution.py): fold the
        # closed timeline into the worker's rolling per-(model, class)
        # phase aggregates — the /debug/worker "where does p99 go" view
        try:
            timeline = flight.timeline(rid)
            if timeline is not None:
                get_attribution().observe_flight(
                    st.req.model,
                    st.sla.sla_class if st.sla is not None else "unclassified",
                    timeline,
                )
        except Exception:
            log.exception("attribution observe failed for %s", rid[:8])
        tracer = get_tracer()
        if not tracer.enabled:
            return
        tp = st.req.annotations.get("traceparent")
        status = "ERROR" if finish_reason == FINISH_ERROR else "OK"
        if st.t_queued and st.t_admitted:
            tracer.emit(
                "engine.queue", st.t_queued, st.t_admitted,
                traceparent=tp, request_id=rid,
            )
        prefill_start = st.t_prefill_start or st.t_admitted
        if prefill_start and st.t_first_token:
            tracer.emit(
                "engine.prefill", prefill_start, st.t_first_token,
                traceparent=tp, request_id=rid,
                prompt_tokens=len(st.req.token_ids),
                cached_tokens=st.cached_tokens,
            )
        if st.t_first_token:
            tracer.emit(
                "engine.decode", st.t_first_token, time.time_ns(),
                traceparent=tp, request_id=rid, status=status,
                tokens=st.produced, finish=finish_reason,
            )

    def _slo_finished(self, st: "_Seq", finish_reason: str) -> None:
        """Feed the worker-side SLO ledger from the milestone timestamps the
        loop already stamped (host-side scalars — no device sync). TTFT is
        anchored on the frontend receipt stamp riding the sla annotation
        when present (same-host wall clock), else on engine queue entry;
        ITL is the request's mean decode gap."""
        spec = st.sla
        now_ns = time.time_ns()
        t0 = sla_t0_ns(st.req.annotations) or st.t_queued
        ttft_s = (
            (st.t_first_token - t0) / 1e9 if st.t_first_token else None
        )
        itl_s = None
        if st.t_first_token and st.produced > 1:
            itl_s = (now_ns - st.t_first_token) / 1e9 / (st.produced - 1)
        e2e_s = (now_ns - t0) / 1e9
        met = get_slo_accountant().record(
            st.req.model, spec,
            ttft_s=ttft_s, itl_s=itl_s,
            output_tokens=st.produced, e2e_s=e2e_s,
        )
        fields: Dict[str, Any] = dict(
            sla_class=spec.sla_class,
            met=met,
            ttft_ms=(None if ttft_s is None else round(ttft_s * 1e3, 3)),
            ttft_target_ms=round(spec.ttft_target_s * 1e3, 3),
            itl_ms=(None if itl_s is None else round(itl_s * 1e3, 3)),
            itl_target_ms=round(spec.itl_target_s * 1e3, 3),
        )
        if spec.deadline_s > 0:
            fields["deadline_remaining_s"] = round(spec.deadline_s - e2e_s, 3)
        if not met or finish_reason == FINISH_ERROR:
            get_flight_recorder().record(
                st.req.request_id, "slo_violation", **fields
            )

    def _step_stats(self, phase: str, duration_s: float, tokens: int) -> None:
        """Feed one StepStats to the hook — scalars the loop already holds;
        never forces a device sync (engine/telemetry.py)."""
        hook = self.stats_hook
        if hook is None:
            return
        spec_acc = None
        if self.cfg.spec_draft is not None and self.spec_stats["rounds"]:
            spec_acc = self.spec_stats["emitted"] / (
                self.spec_stats["rounds"] * self.spec_stats["k"]
            )
        # async step-prep accounting: only chunk-carrying phases consume a
        # prebuild (engine/prep.py take())
        prep = (
            self._prep.pop_last()
            if self._prep is not None and phase in ("prefill", "mixed")
            else None
        )
        try:
            hook(StepStats(
                phase=phase,
                duration_s=duration_s,
                batch_occupancy=sum(
                    1 for s in self._slots if s is not None and not s.done
                ),
                batch_size=self.cfg.max_batch_size,
                tokens=int(tokens),
                queue_depth=len(self._waiting),
                kv_active_blocks=self.allocator.active_blocks,
                kv_free_blocks=self.allocator.free_blocks,
                kv_total_blocks=self.cfg.num_blocks,
                spec_acceptance=spec_acc,
                prep_hit=(prep["hit"] if prep is not None else None),
                prep_build_s=(prep["build_s"] if prep is not None else 0.0),
                prep_wait_s=(prep["wait_s"] if prep is not None else 0.0),
            ))
        except Exception:
            log.exception("stats hook failed")

    async def _publish_events(self) -> None:
        stored, removed = self.allocator.drain_events()
        if self.kvbm is not None:
            # tier evictions: blocks gone from G2+G3 AND not resident in G1
            # are no longer servable anywhere -> tell the router
            gone = [
                h for h in self.kvbm.drain_evicted()
                if self.allocator._by_hash.get(h) is None
            ]
            if gone:
                removed = removed + [gone]
            if self.kv_directory is not None:
                # fleet directory upkeep rides the same consolidated cadence:
                # advertise fresh tier offloads, withdraw what no tier holds.
                # Best-effort — a directory-plane wobble (or armed
                # directory.publish fault) must never stall the event loop;
                # the TTL lease ages out anything a failed withdraw left
                try:
                    fresh = self.kvbm.drain_stored()
                    by_tier: Dict[str, List[int]] = {}
                    for h in fresh:
                        t = self.kvbm.tier_of(h)
                        if t is not None:
                            by_tier.setdefault(t, []).append(h)
                    fmt = "int8" if self.kv_quantized else "model"
                    for t, hs in sorted(by_tier.items()):
                        await self.kv_directory.publish(hs, t, fmt)
                    if gone:
                        await self.kv_directory.unpublish(gone)
                except Exception:
                    log.warning(
                        "kv directory upkeep failed (continuing)",
                        exc_info=True,
                    )
            # a device-evicted block still in G2/G3/G4 is still servable (we
            # onboard on demand): don't tell the router it's gone — the
            # consolidated view, like the reference's kv_consolidator
            # (lib/llm/src/block_manager/kv_consolidator). Remote membership
            # is one batched RPC per event batch, off the event loop (the G4
            # socket blocks; same treatment as match_prefix above).
            loop_ = asyncio.get_event_loop()
            filtered = []
            for batch in removed:
                servable = set(await loop_.run_in_executor(
                    None, self.kvbm.filter_servable, batch
                ))
                gone_batch = [h for h in batch if h not in servable]
                if gone_batch:
                    filtered.append(gone_batch)
            removed = filtered
        if self.kv_publisher is not None:
            for batch in stored:
                await self.kv_publisher.stored(batch)
            for batch in removed:
                await self.kv_publisher.removed(batch)
        if self.metrics_publisher is not None:
            # publish on KV events AND whenever load changed: releases emit
            # no events (blocks just move to the reusable cache), and a
            # stale active-block report would leave the router seeing
            # phantom load on an idle worker
            running = sum(
                1 for s in self._slots if s is not None and not s.done
            )
            load = (self.allocator.active_blocks, len(self._waiting), running)
            if stored or removed or load != self._last_published_load:
                self._last_published_load = load
                await self.metrics_publisher.publish(
                    active_decode_blocks=load[0],
                    num_requests_waiting=load[1],
                    num_requests_active=running,
                    total_blocks=self.cfg.num_blocks,
                )

    # -- introspection -------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        snap = {
            "running": sum(1 for s in self._slots if s is not None),
            "waiting": len(self._waiting),
            "active_blocks": self.allocator.active_blocks,
            "cached_blocks": self.allocator.cached_blocks,
            "free_blocks": self.allocator.free_blocks,
        }
        if self.cfg.spec_draft is not None:
            snap["spec"] = dict(self.spec_stats)
        if self._eplb_enabled:
            snap["eplb"] = {
                "redundant_experts": self.mcfg.redundant_experts,
                "physical_experts": self.mcfg.num_physical_experts,
            }
        if self.kvbm is not None:
            snap["kvbm"] = {
                "g2_blocks": len(self.kvbm.host),
                "g3_blocks": len(self.kvbm.disk) if self.kvbm.disk is not None else 0,
                "offloaded": self.kvbm.offloaded,
                "onboarded": self.kvbm.onboarded,
            }
        return snap

    async def clear_kv_blocks(self, levels: Optional[List[str]] = None) -> Dict[str, Any]:
        """Runtime cache reset (reference block_manager/controller.rs
        cache-level commands + http/clear_kv_blocks.rs): drop the device
        prefix cache (g1) and/or the KVBM offload tiers (g2 host, g3 disk).
        Active requests keep their pinned blocks — only reusable cache is
        dropped. The router view stays honest: a g1 clear publishes a
        wholesale CLEARED event for this worker; tier clears ride the
        consolidated removed-event path."""
        if levels is not None and (
            not isinstance(levels, (list, tuple))
            or any(not isinstance(lv, str) for lv in levels)
        ):
            raise ValueError("levels must be a list of tier names")
        # None = clear everything; an explicit empty list clears nothing
        # (same semantics as the mocker)
        levels = [
            lv.lower()
            for lv in (levels if levels is not None else ["g1", "g2", "g3"])
        ]
        result: Dict[str, Any] = {}
        if "g1" in levels:
            before = self.allocator.cached_blocks
            self.allocator.clear()
            # clear() intentionally emits no per-hash events (comment there):
            # the wholesale CLEARED event resets this worker in the indexer
            if self.kv_publisher is not None:
                await self.kv_publisher.cleared()
            result["g1"] = before
        if self.kvbm is not None and ("g2" in levels or "g3" in levels):
            counts = self.kvbm.clear(
                host="g2" in levels, disk="g3" in levels
            )
            result.update({k: v for k, v in counts.items() if k in levels})
            # push the eviction notifications out now, not at the next step
            await self._publish_events()
        result["snapshot"] = self.snapshot()
        return result
