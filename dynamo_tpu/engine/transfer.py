"""KV block transfer between engines: the TPU-native NIXL analog.

The reference moves KV between prefill and decode GPUs over NIXL RDMA
(lib/memory/src/nixl.rs, dynamo.nixl_connect, docs/design_docs/
disagg_serving.md:20,54). On TPU the equivalent paths are:

1. **DCN / host-staging (implemented here, works everywhere):** prefill
   engine gathers the request's KV pages device->host, ships them over the
   request plane (msgpack bytes on TCP), decode engine scatters host->device
   into its own pages. Content addressing makes the protocol idempotent and
   failure-tolerant: blocks are requested *by sequence hash*; whatever the
   prefill side still holds is returned, and the decode side recomputes any
   missing suffix — no pinning handshake required.
2. **ICI collective-permute (same-pod slices):** planned fast path —
   jitted shard_map ppermute moving pages directly HBM->HBM across a shared
   mesh; requires a multi-slice deployment (interface reserved via
   TransferBackend).

Wire protocol (served as a normal endpoint, "kv_fetch"):
    request : {"hashes": [u64...], "layers": L, "dtype": str}
    response: one item {"matched": n, "shape": [...], "data": bytes}
              (data = np array [L, 2, n, bs, kvh, d] tobytes, C-order)
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime.engine import Context
from ..runtime.logging import get_logger
from ..runtime.request_plane.tcp import TcpClient
from ..tokens import SequenceHash

log = get_logger("engine.transfer")


class KvTransferServer:
    """Serves this engine's KV pages by sequence hash."""

    def __init__(self, engine):
        self.engine = engine  # TpuEngine (duck-typed: allocator, k/v_caches)

    async def handle(self, request: Any, context: Context) -> AsyncIterator[Dict]:
        hashes: List[SequenceHash] = list(request.get("hashes", []))
        alloc = self.engine.allocator
        # pin the matched prefix so eviction can't race the device gather
        block_ids = alloc.acquire_prefix(hashes)
        try:
            n = len(block_ids)
            if n == 0:
                yield {"matched": 0, "data": b"", "shape": []}
                return
            data, shape = await self._gather(block_ids)
            yield {"matched": n, "data": data, "shape": shape}
        finally:
            alloc.release(block_ids)

    async def _gather(self, block_ids: List[int]) -> Tuple[bytes, List[int]]:
        import asyncio

        loop = asyncio.get_event_loop()

        def gather():
            ids = jnp.asarray(np.asarray(block_ids, np.int32))
            layers = []
            for kc, vc in zip(self.engine.k_caches, self.engine.v_caches):
                k = np.asarray(kc[ids])   # [n, bs, kvh, d]
                v = np.asarray(vc[ids])
                layers.append(np.stack([k, v]))  # [2, n, bs, kvh, d]
            arr = np.stack(layers)               # [L, 2, n, bs, kvh, d]
            return arr.astype(np.float32).tobytes(), list(arr.shape)

        return await loop.run_in_executor(self.engine._executor, gather)


class KvTransferClient:
    """Fetches remote pages and imports them into a local engine's cache."""

    def __init__(self, engine, tcp_client: Optional[TcpClient] = None):
        self.engine = engine
        self._tcp = tcp_client or TcpClient()

    async def fetch_and_import(
        self, address: str, hashes: List[SequenceHash]
    ) -> int:
        """Pull blocks for ``hashes`` from ``address``; returns tokens imported.

        Already-cached local blocks are skipped (only the missing suffix is
        fetched). Imported blocks are committed content-addressed, so the
        engine's normal admission path picks them up as a cached prefix."""
        alloc = self.engine.allocator
        have = len(alloc.match_prefix(hashes))
        want = hashes[have:]
        if not want:
            return have * alloc.block_size
        stream = await self._tcp.call(address, {"hashes": [int(h) for h in want]})
        matched = 0
        data = b""
        shape: List[int] = []
        async for item in stream:
            matched = item.get("matched", 0)
            data = item.get("data", b"")
            shape = item.get("shape", [])
        if matched == 0:
            return have * alloc.block_size
        arr = np.frombuffer(data, np.float32).reshape(shape)
        imported = await self._import(arr, want[:matched])
        return (have + imported) * alloc.block_size

    async def _import(self, arr: np.ndarray, hashes: List[SequenceHash]) -> int:
        # wire layout [L, 2, n, bs, kvh, d] -> block-major [n, L, 2, ...]
        block_major = np.ascontiguousarray(np.moveaxis(arr, 2, 0))
        return await self.engine.import_blocks(list(hashes), block_major)

    async def close(self) -> None:
        await self._tcp.close()
