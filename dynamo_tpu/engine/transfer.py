"""KV block transfer between engines: the TPU-native NIXL analog.

The reference moves KV between prefill and decode GPUs over NIXL RDMA
(lib/memory/src/nixl.rs, dynamo.nixl_connect, docs/design_docs/
disagg_serving.md:20,54). On TPU the equivalent paths are:

1. **DCN / host-staging (works everywhere):** prefill engine gathers the
   request's KV pages device->host, ships them over the request plane
   (msgpack bytes on TCP), decode engine scatters host->device into its own
   pages. Content addressing makes the protocol idempotent and
   failure-tolerant: blocks are requested *by sequence hash*; whatever the
   prefill side still holds is returned, and the decode side recomputes any
   missing suffix — no pinning handshake required.
2. **ICI / device-to-device (same-slice xPyD, IciKvMover below):** when the
   prefill and decode engines are co-resident (one process, device groups of
   the same slice — the rank_mesh/dp layout engine/__main__.py builds), the
   pages never touch the host: a jitted gather on the source mesh, a
   ``jax.device_put`` reshard onto the destination mesh (PJRT issues direct
   device-to-device copies — ICI on a TPU pod), and a jitted scatter into
   the destination cache. ``KvTransferClient.fetch_and_import`` picks this
   path automatically when the transfer address resolves to a server in
   ``LOCAL_SERVERS`` (process-local registry), falling back to DCN on any
   failure. Bit-equality with the DCN path is pinned by
   tests/test_ici_transfer.py.

Wire protocol (served as a normal endpoint, "kv_fetch"):
    request : {"hashes": [u64...], "native_ok": bool}
    response: one item, either
      inline:  {"matched": n, "shape": [...], "data": bytes}
               (data = np array [L, 2, n, bs, kvh, d] tobytes, C-order)
      native:  {"matched": n, "block_shape": [L, 2, bs, kvh, d],
                "native": {"host", "port", "region", "slots": [...]}}
               — bulk bytes then move over the C++ agent
               (native/transfer/agent.cpp) with raw scatter/gather TCP,
               bypassing the Python request plane; the control message only
               carries slot indices. Slots are leased from a staging arena
               and freed by a follow-up {"free_slots": [...]} call (or by
               lease expiry, so a crashed client can't pin the arena).

Streamed protocol (FlowKV-style block-wise overlap, same endpoint):
    request : {"hashes": [u64...], "stream": true, "window": W,
               "wait_s": S, "native_ok": bool}
    response: a SEQUENCE of window items, then an eof frame
      window:  {"offset": k, "matched": m <= W, "wait_s": t, ...}
               with the same inline/native body as the blocking response,
               covering hashes[k : k+m]. The server serves whatever prefix
               the engine has content-addressed SO FAR and then *waits for
               more commits* (engine.kv_commits fires per prefill chunk —
               write_prefill_kv finalizes those blocks), so a decode-side
               client that connects while the prefill is still computing
               pulls early blocks under later chunks' compute. ``wait_s``
               is the server-side commit wait for that window; clients
               subtract it from inter-item latency when estimating wire
               bandwidth. The stream ends with {"eof": true, "served": n}
               once all requested hashes shipped or no new block committed
               within the wait budget.
    The protocol stays content-addressed and idempotent: a client that
    loses the stream mid-way re-requests ONLY the un-imported suffix (its
    imported prefix is already committed locally), so recovery is
    per-block, never whole-request.
"""

from __future__ import annotations

import itertools
import os
import time
import zlib
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# name -> dtype for wire fields lives with the block-storage layout:
# ONE spot (kvbm/layout) resolves ml_dtypes names like "bfloat16"
from ..kvbm.layout import dtype_from_name as _dtype_from_name
from ..ops.quant import SCALE_DTYPE
from ..runtime.engine import Context
from ..runtime.faults import FAULTS
from ..runtime.logging import get_logger
from ..runtime.request_plane.tcp import NoResponders, TcpClient
from ..runtime.resilience import RETRYABLE_DEFAULT, retry_policy
from ..runtime.tracing import get_tracer
from ..tokens import SequenceHash

log = get_logger("engine.transfer")

NATIVE_REGION = 1
SLOT_LEASE_S = 30.0

# -- streamed block-window protocol knobs ------------------------------------
# window width in blocks: small enough that the first window ships while the
# second prefill chunk still computes, large enough to amortize per-item
# request-plane overhead (a chunk of 512 tokens at bs=16 commits 32 blocks)
STREAM_WINDOW_BLOCKS = int(os.environ.get("DTPU_STREAM_WINDOW", "8"))
# how long a streaming fetch waits for the NEXT block to be committed before
# concluding the prefill side has nothing more (prefill crashed / request
# never landed there); the decode side then recomputes the missing suffix
STREAM_WAIT_S = float(os.environ.get("DTPU_STREAM_WAIT_S", "30.0"))
# commit-signal re-check tick while waiting (bounds a lost-wakeup stall)
_STREAM_POLL_S = 1.0
# consecutive progress-less resume attempts before the client gives up on
# the remaining suffix (progress resets the counter: recovery is per-block)
STREAM_MAX_RESUMES = 3


class KvCommitSignal:
    """Broadcast wakeup: "new blocks were content-addressed on this engine".

    The engine fires it from ``_commit_prefilled_blocks`` (event-loop
    thread, once per landed prefill chunk) and ``import_blocks``; streaming
    fetch handlers wait on it instead of polling the allocator. One shared
    future serves every concurrent waiter (``shield`` keeps one waiter's
    timeout from cancelling the others' wakeup); ``gen`` is a monotonic
    commit generation so a fire between ``wait`` calls is never lost.
    """

    def __init__(self) -> None:
        self.gen = 0
        self._fut: Optional["asyncio.Future"] = None

    def fire(self) -> None:
        self.gen += 1
        fut = self._fut
        if fut is not None and not fut.done():
            fut.set_result(None)

    async def wait(self, seen: int, timeout: float) -> int:
        """Return the current generation, blocking up to ``timeout`` only
        while it still equals ``seen``."""
        import asyncio

        if self.gen != seen:
            return self.gen
        if self._fut is None or self._fut.done():
            self._fut = asyncio.get_event_loop().create_future()
        try:
            await asyncio.wait_for(asyncio.shield(self._fut), timeout)
        except asyncio.TimeoutError:
            pass
        return self.gen

# process-local registry: transfer address -> KvTransferServer. A client
# whose target lives here skips the wire entirely (ICI device path).
LOCAL_SERVERS: Dict[str, "KvTransferServer"] = {}


# -- cross-process device-to-device plane (jax.experimental.transfer) --------
#
# The true NIXL analog: PJRT's transfer server moves device buffers directly
# between PROCESSES (ICI/DCN bulk transport on TPU pods, TCP on CPU), so
# disaggregated prefill/decode engines in separate OS processes exchange KV
# pages without host staging (reference lib/memory/src/nixl.rs:13,
# docs/design_docs/disagg_serving.md:20,54). One transfer server per process,
# shared by every engine in it; offers ride the existing kv_fetch control
# protocol as {"device": {uuid, address, shape, dtype, shards}}.
#
# The pull is shard-for-shard: the destination spec must reproduce the
# source's shard layout exactly (no implicit reshard on the wire). Pages are
# therefore canonicalized before await_pull onto a 1-D mesh of `shards`
# devices — [L, n, bs, kvh, d] sharded on kvh — where `shards` is negotiated
# down to what the client can host (a single-chip decoder pulling from a
# tp=8 prefill group gets a 1-shard layout; the reshard is a device_put on
# the source's own fabric, never the wire).

_DEVICE_PULL_CAP = 32   # outstanding un-pulled offers per server
# The transfer runtime has no cancel/unregister: an offer whose client died
# before pulling may keep its gathered page stacks alive runtime-side even
# after we drop our refs at expiry. Bound that worst case: after this many
# expired-unpulled offers the server stops making device offers entirely
# (DCN keeps serving) instead of leaking HBM without limit.
_DEVICE_LEAK_BUDGET = 128

_pull_uuids = itertools.count(int(time.time()) << 20)
_proc_xfer_server = None
_proc_xfer_conns: Dict[str, Any] = {}


def device_transfer_available() -> bool:
    if os.environ.get("DTPU_DEVICE_TRANSFER", "1") == "0":
        return False
    try:
        from jax.experimental import transfer  # noqa: F401
    except ImportError:
        return False
    return True


def process_transfer_server(host: str = "127.0.0.1"):
    """The per-process PJRT transfer server (serves pulls AND dials out).
    First caller's host wins; DTPU_XFER_HOST overrides (multi-machine)."""
    global _proc_xfer_server
    if _proc_xfer_server is None:
        from jax.experimental import transfer

        host = os.environ.get("DTPU_XFER_HOST", host)
        client = jax.local_devices()[0].client
        _proc_xfer_server = transfer.start_transfer_server(
            client, f"{host}:0", [f"{host}:0"]
        )
        log.info("device transfer server on %s", _proc_xfer_server.address())
    return _proc_xfer_server


def _xfer_connect(address: str):
    conn = _proc_xfer_conns.get(address)
    if conn is None:
        conn = _proc_xfer_conns[address] = process_transfer_server().connect(address)
    return conn


def mesh_is_addressable(mesh) -> bool:
    """True when every mesh device belongs to this process (single-process
    engine). Multihost groups gather per-process shards instead."""
    pi = jax.process_index()
    return all(d.process_index == pi for d in mesh.devices.flat)


async def import_pages_device(dst, hashes: List[SequenceHash], kp, vp) -> Optional[int]:
    """Scatter on-device page stacks [L, n, bs, kvh, d] into ``dst``'s cache
    as content-addressed blocks. Shared tail of the same-process ICI move and
    the cross-process device pull. Returns blocks imported, None on scatter
    failure (caller falls back / recomputes)."""
    import asyncio

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import registry
    from ..parallel import mesh as meshlib
    from .allocator import OutOfBlocks

    loop = asyncio.get_event_loop()
    n = int(kp.shape[1])
    try:
        dst_ids = dst.allocator.allocate(n)
    except OutOfBlocks:
        log.warning("device import: no room for %d blocks on dest", n)
        return 0
    dst_sh = NamedSharding(
        dst.mesh,
        P(None, *registry.kv_cache_spec(dst.mcfg, meshlib.tp_size(dst.mesh))),
    )

    def scatter():
        kpd = jax.device_put(kp, dst_sh)
        vpd = jax.device_put(vp, dst_sh)
        ids = jnp.asarray(np.asarray(dst_ids, np.int32))
        dst.k_caches, dst.v_caches = IciKvMover._scatter_fn(dst)(
            dst.k_caches, dst.v_caches, kpd, vpd, ids
        )

    try:
        await loop.run_in_executor(dst._executor, scatter)
    except Exception:
        log.exception("device import scatter failed")
        dst.allocator.release(dst_ids)
        return None
    for bid, h in zip(dst_ids, hashes):
        dst.allocator.commit(bid, h)
    dst.allocator.release(dst_ids)
    return n




class KvTransferServer:
    """Serves this engine's KV pages by sequence hash."""

    def __init__(self, engine, host: str = "127.0.0.1", arena_slots: int = 256):
        self.engine = engine  # TpuEngine (duck-typed: allocator, k/v_caches)
        self.host = host
        self._agent = None
        self._arena: Optional[np.ndarray] = None
        # slot -> (expiry, token): the token is a per-lease generation id so
        # a late/duplicate free_slots after expiry+re-lease cannot release
        # another client's fresh lease
        self._slot_lease: Dict[int, Tuple[float, int]] = {}
        self._lease_counter = 0
        self._arena_slots = arena_slots
        m = self.engine.mcfg
        bs = self.engine.cfg.block_size
        self._block_shape = [m.num_layers, 2, bs, m.num_kv_heads, m.head_dim]
        # wire bytes are the CACHE storage format: model dtype (bf16 halves
        # bytes vs f32), or for kv_dtype=int8 the flat payload+scales codec
        # buffer (halves them again) — blocks then round-trip bit-exactly
        # with no dequantize/requantize detour on either end
        self._quantized = bool(getattr(engine, "kv_quantized", False))
        if self._quantized:
            self._codec = engine._kv_codec()
            self._arena_dtype = np.dtype(np.uint8)
            self._block_nbytes = self._codec.nbytes
        else:
            self._codec = None
            self._arena_dtype = np.dtype(m.dtype)
            self._block_nbytes = (
                int(np.prod(self._block_shape)) * self._arena_dtype.itemsize
            )
        # cross-process device plane: uuid -> (expiry, (k, v) device arrays)
        self._xfer = None
        self._pull_pending: Dict[int, Tuple[float, tuple]] = {}
        self._pull_leaked = 0  # expired-unpulled offers (see _DEVICE_LEAK_BUDGET)

    def _ensure_native(self) -> bool:
        """Lazy: the arena (GiB-scale for big models) and agent come up on
        the first native-capable fetch, not at serve_transfer time."""
        if self._agent is not None:
            return True
        try:
            from ..transfer import NativeAgent, native_available

            if not native_available():
                return False
            block_elems = self._block_nbytes // self._arena_dtype.itemsize
            self._arena = np.zeros(
                (self._arena_slots, block_elems), self._arena_dtype
            )
            self._agent = NativeAgent(host=self.host)
            self._agent.register(
                NATIVE_REGION, self._arena, self._block_nbytes,
            )
            log.info(
                "native transfer agent serving on %s:%d (%.0f MiB arena)",
                self.host, self._agent.port, self._arena.nbytes / 2**20,
            )
            return True
        except Exception:
            log.exception("native transfer agent unavailable; inline payloads only")
            self._agent = None
            return False

    # -- device plane --------------------------------------------------------
    def _ensure_device(self) -> bool:
        if self._xfer is not None:
            return True
        if not device_transfer_available():
            return False
        if not mesh_is_addressable(self.engine.mesh):
            return False  # multihost groups: per-process shard plumbing TBD
        try:
            self._xfer = process_transfer_server(self.host)
        except Exception:
            log.exception("device transfer server unavailable")
            return False
        return True

    async def _offer_device(self, block_ids: List[int], client_shards: int):
        """Gather pages onto a canonical pull layout and register the pull.
        Returns the offer dict, or None (at capacity / gather failure)."""
        import asyncio

        now = time.monotonic()
        expired = [u for u, (t, _) in self._pull_pending.items() if t <= now]
        if expired:
            self._pull_leaked += len(expired)
            log.warning(
                "%d device offer(s) expired unpulled (%d lifetime)",
                len(expired), self._pull_leaked,
            )
            for u in expired:
                self._pull_pending.pop(u, None)
        if self._pull_leaked >= _DEVICE_LEAK_BUDGET:
            return None  # leak budget exhausted: DCN from here on
        if len(self._pull_pending) >= _DEVICE_PULL_CAP:
            return None
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        eng = self.engine
        kvh = eng.mcfg.num_kv_heads
        tp = int(eng.mesh.shape.get("tp", 1))
        shards = max(1, min(tp, int(client_shards), kvh))
        while shards > 1 and kvh % shards:
            shards -= 1
        devs = list(eng.mesh.devices.flat)[:shards]
        pull_sh = NamedSharding(
            Mesh(np.array(devs), ("x",)), P(None, None, None, "x", None)
        )
        loop = asyncio.get_event_loop()
        # reserve the slot BEFORE the gather await: concurrent fetches must
        # not all pass the cap check and overshoot it together. The inf
        # expiry keeps the in-flight reservation out of the expiry scan (a
        # slow first-call compile must not be counted as a leak).
        uuid = next(_pull_uuids)
        self._pull_pending[uuid] = (float("inf"), ())

        def gather():
            ids = jnp.asarray(np.asarray(block_ids, np.int32))
            k, v = IciKvMover._gather_fn(eng)(eng.k_caches, eng.v_caches, ids)
            return jax.device_put(k, pull_sh), jax.device_put(v, pull_sh)

        try:
            k, v = await loop.run_in_executor(eng._executor, gather)
            self._xfer.await_pull(uuid, [k, v])
        except Exception:
            # await_pull failures included: leaving the inf-expiry
            # reservation behind would permanently burn a cap slot
            log.exception("device offer failed; falling back to the wire")
            self._pull_pending.pop(uuid, None)
            return None
        # hold refs until pulled+freed (or expiry drops ours; the transfer
        # runtime keeps its own until the pull lands). Lease starts NOW —
        # the gather above may have taken a compile-scale pause.
        self._pull_pending[uuid] = (time.monotonic() + SLOT_LEASE_S, (k, v))
        return {
            "uuid": uuid,
            "address": self._xfer.address(),
            "shape": list(k.shape),
            "dtype": k.dtype.name,
            "shards": shards,
        }

    def _reclaim_leases(self, leases: List[Tuple[int, int]]) -> None:
        """Synchronously drop every (slot, token) lease the client never
        freed — the token match keeps re-leased slots untouched. Shared by
        the one-shot native branch (failed gather) and the streaming
        handler (client gone mid-stream)."""
        for slot, token in leases:
            lease = self._slot_lease.get(slot)
            if lease is not None and lease[1] == token:
                self._slot_lease.pop(slot, None)

    def _lease_slots(self, n: int) -> Optional[Tuple[List[int], int]]:
        now = time.monotonic()
        free = [
            s for s in range(self._arena_slots)
            if self._slot_lease.get(s, (0.0, 0))[0] < now
        ]
        if len(free) < n:
            return None
        self._lease_counter += 1
        token = self._lease_counter
        slots = free[:n]
        for s in slots:
            self._slot_lease[s] = (now + SLOT_LEASE_S, token)
        return slots, token

    def _trace_serve(self, request: Any, start_ns: int, wire: str,
                     matched: int, nbytes: int) -> None:
        """Span for one served fetch, parented on the traceparent the client
        shipped in the handshake — the decode-side pull and this prefill-side
        serve land in the same trace. Emitted just before the result yields
        (wrapping an async generator in a span context would hold the
        ambient contextvar across the yield)."""
        tracer = get_tracer()
        if tracer.enabled:
            tracer.emit(
                "kv.transfer.serve", start_ns, time.time_ns(),
                traceparent=request.get("traceparent"),
                wire=wire, blocks=matched, bytes=nbytes,
            )

    async def handle(self, request: Any, context: Context) -> AsyncIterator[Dict]:
        if "free_slots" in request:
            token = request.get("token")
            for s in request["free_slots"]:
                lease = self._slot_lease.get(int(s))
                if lease is not None and lease[1] == token:
                    self._slot_lease.pop(int(s), None)
            yield {"ok": True}
            return
        if "free_pull" in request:
            self._pull_pending.pop(int(request["free_pull"]), None)
            yield {"ok": True}
            return
        if request.get("tier"):
            async for item in self._handle_tier_stream(request):
                yield item
            return
        if request.get("stream"):
            async for item in self._handle_stream(request):
                yield item
            return
        t_serve = time.time_ns()
        hashes: List[SequenceHash] = list(request.get("hashes", []))
        native_ok = bool(request.get("native_ok")) and self._ensure_native()
        # int8 caches serve the wire + native planes only: the device-pull /
        # ICI fast paths move raw cache arrays and do not carry the
        # payload+scales pair yet
        device_ok = (
            bool(request.get("device_ok"))
            and not self._quantized
            and self._ensure_device()
        )
        alloc = self.engine.allocator
        # pin the matched prefix so eviction can't race the device gather
        block_ids = alloc.acquire_prefix(hashes)
        try:
            n = len(block_ids)
            if n == 0:
                self._trace_serve(request, t_serve, "none", 0, 0)
                yield {"matched": 0, "data": b"", "shape": []}
                return
            if device_ok:
                offer = await self._offer_device(
                    block_ids, int(request.get("device_shards", 1))
                )
                if offer is not None:
                    self._trace_serve(request, t_serve, "device", n, 0)
                    yield {"matched": n, "device": offer}
                    return
            leased = self._lease_slots(n) if native_ok else None
            if leased is not None:
                slots, token = leased
                try:
                    checksums = await self._gather_into_arena(block_ids, slots)
                except BaseException:
                    # failed mid-serve: the client never learns these slot
                    # numbers, so nothing would free them until SLOT_LEASE_S
                    # expiry — the same capacity bleed the streaming branch
                    # reclaims on abnormal exit
                    self._reclaim_leases([(s, token) for s in slots])
                    raise
                self._trace_serve(
                    request, t_serve, "native", n, n * self._block_nbytes
                )
                yield {
                    "matched": n,
                    "block_shape": self._block_shape,
                    "dtype": self._arena_dtype.name,
                    "kv_dtype": "int8" if self._quantized else "model",
                    "block_bytes": self._block_nbytes,
                    "native": {
                        "host": self.host,
                        "port": self._agent.port,
                        "region": NATIVE_REGION,
                        "slots": slots,
                        "token": token,
                        # end-to-end integrity: the client re-checksums what
                        # it fetched. If this lease expired mid-read and the
                        # slots were re-gathered for another request, the
                        # torn bytes fail the check and the client recomputes
                        # instead of importing poison into its
                        # content-addressed prefix cache
                        "crc32": checksums,
                    },
                }
            else:
                data, shape, dtype_name, scales = await self._gather(block_ids)
                item = {
                    "matched": n, "data": data, "shape": shape,
                    "dtype": dtype_name,
                }
                if scales is not None:
                    item["scales"] = scales  # f32 [L, 2, n, kvh] raw bytes
                self._trace_serve(
                    request, t_serve, "inline", n,
                    len(data) + (len(scales) if scales is not None else 0),
                )
                yield item
        finally:
            alloc.release(block_ids)

    async def _window_item(
        self, ids: List[int], native_ok: bool, stream_leases: List[Tuple[int, int]]
    ) -> Tuple[Dict[str, Any], int]:
        """Gather ONE window of blocks into a response item (native when the
        arena has room, inline otherwise). Returns (item, nbytes)."""
        take = len(ids)
        leased = self._lease_slots(take) if native_ok else None
        if leased is not None:
            slots, token = leased
            stream_leases.extend((s, token) for s in slots)
            checksums = await self._gather_into_arena(ids, slots)
            return {
                "matched": take,
                "block_shape": self._block_shape,
                "dtype": self._arena_dtype.name,
                "kv_dtype": "int8" if self._quantized else "model",
                "block_bytes": self._block_nbytes,
                "native": {
                    "host": self.host,
                    "port": self._agent.port,
                    "region": NATIVE_REGION,
                    "slots": slots,
                    "token": token,
                    "crc32": checksums,
                },
            }, take * self._block_nbytes
        data, shape, dtype_name, scales = await self._gather(ids)
        item: Dict[str, Any] = {
            "matched": take, "data": data, "shape": shape, "dtype": dtype_name,
        }
        nbytes = len(data)
        if scales is not None:
            item["scales"] = scales
            nbytes += len(scales)
        return item, nbytes

    async def _handle_tier_stream(self, request: Any) -> AsyncIterator[Dict]:
        """Serve sealed blocks straight from the KVBM host/disk tiers
        (G2/G3) as block windows — the fleet-wide KV reuse serve path
        (kvbm/directory.py). Blocks ship in their STORAGE format, which is
        already block-major: float caches [L, 2, bs, kvh, d] model dtype,
        int8 caches the flat codec buffer — both bit-exact on the wire, no
        re-encode on either side. Unlike the device-cache stream there is
        no commit signal to wait on (tier blocks are sealed: present or
        not) and no arena leases to reclaim; the run simply ends at the
        first hash no local tier holds (the client recomputes the rest).
        Per-block crc32 lets the client reject torn disk reads."""
        import asyncio

        t_serve = time.time_ns()
        hashes: List[SequenceHash] = list(request.get("hashes", []))
        n = len(hashes)
        window = max(1, int(request.get("window") or STREAM_WINDOW_BLOCKS))
        kvbm = getattr(self.engine, "kvbm", None)
        served = 0
        nbytes_total = 0
        loop = asyncio.get_event_loop()
        while kvbm is not None and served < n:
            blocks: List[np.ndarray] = []
            tier = "g2"
            for h in hashes[served : served + window]:
                # disk reads block; keep them off the event loop
                got = await loop.run_in_executor(None, kvbm.get_block, h)
                if got is None:
                    break
                b, b_tier = got
                if blocks and (
                    b.shape != blocks[0].shape or b.dtype != blocks[0].dtype
                ):
                    break  # mixed storage formats: end the run, don't mix
                blocks.append(b)
                tier = b_tier if b_tier == "g3" or tier == "g2" else tier
            if not blocks:
                break
            arr = np.stack(blocks)
            data = arr.tobytes()
            item = {
                "matched": len(blocks),
                "offset": served,
                "data": data,
                "dtype": arr.dtype.name,
                "shape": list(arr.shape),
                "fmt": "int8" if arr.ndim == 2 else "model",
                "tier": tier,
                # uint8 view, not .data: bf16 arrays refuse the PEP-3118
                # buffer export ("cannot include dtype 'E' in a buffer")
                "crc32": [
                    zlib.crc32(np.ascontiguousarray(b).view(np.uint8))
                    for b in blocks
                ],
            }
            if arr.ndim == 2:
                # flat int8 codec buffers: ship the logical block shape so a
                # peer (possibly float-cached) can build the decode codec
                item["block_shape"] = self._block_shape
            yield item
            served += len(blocks)
            nbytes_total += len(data)
            if len(blocks) < window:
                break  # run ended mid-window: nothing further is held
        self._trace_serve(request, t_serve, "tier", served, nbytes_total)
        yield {"eof": True, "served": served, "of": n}

    async def _handle_stream(self, request: Any) -> AsyncIterator[Dict]:
        """Block-window streaming fetch: serve committed blocks as windows,
        waiting on the engine's commit signal for blocks whose prefill chunk
        has not landed yet — the decode side overlaps its pull with the
        prefill side's remaining compute.

        Lease lifecycle: window leases are tracked per-stream; if the client
        disappears mid-stream (GeneratorExit / transport error) every lease
        it never freed is dropped immediately instead of pinning arena
        capacity for the full SLOT_LEASE_S — a cancelled fetch must not be
        a slow capacity bleed. On a clean eof the client's own free_slots
        calls (or normal expiry) reclaim the tail window."""
        t_serve = time.time_ns()
        hashes: List[SequenceHash] = list(request.get("hashes", []))
        n = len(hashes)
        window = max(1, int(request.get("window") or STREAM_WINDOW_BLOCKS))
        wait_budget = float(request.get("wait_s") or STREAM_WAIT_S)
        native_ok = bool(request.get("native_ok")) and self._ensure_native()
        alloc = self.engine.allocator
        sig = getattr(self.engine, "kv_commits", None)
        served = 0
        nbytes_total = 0
        wire = "none"
        stream_leases: List[Tuple[int, int]] = []
        clean_exit = False
        try:
            gen = sig.gen if sig is not None else 0
            t_window = time.monotonic()  # when we started waiting for the next window
            while served < n:
                block_ids = alloc.acquire_prefix(hashes)
                avail = len(block_ids)
                if avail <= served:
                    alloc.release(block_ids)
                    waited = time.monotonic() - t_window
                    if waited >= wait_budget or sig is None:
                        break  # no more commits coming: eof with what shipped
                    gen = await sig.wait(
                        gen, min(_STREAM_POLL_S, wait_budget - waited)
                    )
                    continue
                take = min(avail - served, window)
                waited = time.monotonic() - t_window
                try:
                    item, nbytes = await self._window_item(
                        block_ids[served : served + take], native_ok,
                        stream_leases,
                    )
                finally:
                    alloc.release(block_ids)
                item["offset"] = served
                item["wait_s"] = round(waited, 6)
                wire = "native" if "native" in item else "inline"
                yield item
                served += take
                nbytes_total += nbytes
                t_window = time.monotonic()
            self._trace_serve(
                request, t_serve, f"stream-{wire}", served, nbytes_total
            )
            clean_exit = True
            yield {"eof": True, "served": served, "of": n}
        finally:
            if not clean_exit:
                # client gone mid-stream: reclaim every lease it never freed
                self._reclaim_leases(stream_leases)

    def _gather_np(self, block_ids: List[int], dtype=None) -> np.ndarray:
        """Executor thread: device gather -> [L, 2, n, bs, kvh, d]; dtype
        None keeps the CACHE dtype (the wire default — bf16 models ship bf16
        bytes, not a 2x float32 inflation). Float caches only."""
        eng = self.engine
        if eng._mh is not None:
            # multihost group: the gather is a replayed collective whose
            # output is REPLICATED over the mesh, so this (leader) process
            # can read the full page bytes from its local copy
            k, v = eng._mh_kv_gather(
                eng.k_caches, eng.v_caches, np.asarray(block_ids, np.int32)
            )
            arr = np.stack([np.asarray(k), np.asarray(v)], axis=1)
            return arr if dtype is None else arr.astype(dtype)
        ids = jnp.asarray(np.asarray(block_ids, np.int32))
        layers = []
        for kc, vc in zip(self.engine.k_caches, self.engine.v_caches):
            k = np.asarray(kc[ids])   # [n, bs, kvh, d]
            v = np.asarray(vc[ids])
            layers.append(np.stack([k, v]))  # [2, n, bs, kvh, d]
        arr = np.stack(layers)               # [L, 2, n, bs, kvh, d]
        return arr if dtype is None else arr.astype(dtype)

    def _gather_quant_np(self, block_ids: List[int]):
        """Executor thread, int8 cache: -> (payload int8 [L, 2, n, bs, kvh,
        d], scales f32 [L, 2, n, kvh]) — the pair IS the wire format; no
        float materialization anywhere on the serving side."""
        eng = self.engine
        ids = jnp.asarray(np.asarray(block_ids, np.int32))
        pay, scl = [], []
        for kc, vc in zip(eng.k_caches, eng.v_caches):
            pay.append(np.stack([
                np.asarray(kc.data[ids]), np.asarray(vc.data[ids])
            ]))
            scl.append(np.stack([
                np.asarray(kc.scale[ids]), np.asarray(vc.scale[ids])
            ]))
        return np.stack(pay), np.stack(scl)

    async def _gather(self, block_ids: List[int]):
        """Inline wire payload: (data bytes, shape, dtype name, scales bytes
        or None). Scales present <=> the payload is int8."""
        import asyncio

        loop = asyncio.get_event_loop()

        def gather():
            if self._quantized:
                payload, scales = self._gather_quant_np(block_ids)
                return (
                    payload.tobytes(), list(payload.shape), "int8",
                    scales.tobytes(),
                )
            arr = self._gather_np(block_ids)
            return arr.tobytes(), list(arr.shape), arr.dtype.name, None

        return await loop.run_in_executor(self.engine._executor, gather)

    async def _gather_into_arena(
        self, block_ids: List[int], slots: List[int]
    ) -> List[int]:
        """Returns the per-slot crc32 of the bytes placed in the arena."""
        import asyncio

        loop = asyncio.get_event_loop()

        def gather() -> List[int]:
            n = len(block_ids)
            if self._quantized:
                payload, scales = self._gather_quant_np(block_ids)
                pb = np.moveaxis(payload, 2, 0)  # [n, L, 2, bs, kvh, d]
                sb = np.moveaxis(scales, 2, 0)   # [n, L, 2, kvh]
                # bulk pack, one concatenate: byte-identical to per-block
                # codec.encode (payload bytes then scale bytes, C-order)
                flat = np.concatenate([
                    np.ascontiguousarray(pb).reshape(n, -1).view(np.uint8),
                    np.ascontiguousarray(sb).reshape(n, -1).view(np.uint8),
                ], axis=1)
            else:
                arr = self._gather_np(block_ids)      # [L, 2, n, ...]
                block_major = np.moveaxis(arr, 2, 0)  # [n, L, 2, ...]
                flat = block_major.reshape(n, -1)
            sums = []
            for i, s in enumerate(slots):
                self._arena[s] = flat[i]
                sums.append(zlib.crc32(self._arena[s].view(np.uint8)))
            return sums

        return await loop.run_in_executor(self.engine._executor, gather)

    def close(self) -> None:
        if self._agent is not None:
            self._agent.close()
            self._agent = None


class IciKvMover:
    """Device->device KV page movement between two co-resident engines.

    The TPU analog of NIXL's GPU<->GPU RDMA leg (lib/memory/src/nixl.rs:13):
    no host staging, no wire bytes. Three steps, each ordered against the
    owning engine's dispatch stream by running on ITS step executor:

      1. jitted gather on the SOURCE mesh: pages -> [L, n, bs, kvh, d] (cache
         dtype preserved — no f32 round-trip)
      2. ``jax.device_put`` onto the destination mesh's KV sharding: PJRT
         emits direct device-to-device copies (ICI on a TPU pod; the source
         and dest groups of one slice never bounce off DCN)
      3. jitted scatter into the destination cache (donated, in-place)

    The source blocks stay pinned (allocator.acquire_prefix) across the
    gather so LRU eviction cannot rewrite them mid-copy.
    """

    def __init__(self, src_engine, dst_engine):
        assert src_engine is not dst_engine
        self.src = src_engine
        self.dst = dst_engine

    # jitted programs are cached on the engines (one per engine, reused by
    # every mover that touches the engine)
    @staticmethod
    def _gather_fn(engine):
        fn = getattr(engine, "_ici_gather_fn", None)
        if fn is None:
            def gather(k_caches, v_caches, ids):
                k = jnp.stack([kc[ids] for kc in k_caches])  # [L, n, bs, kvh, d]
                v = jnp.stack([vc[ids] for vc in v_caches])
                return k, v

            fn = engine._ici_gather_fn = jax.jit(gather)
        return fn

    @staticmethod
    def _scatter_fn(engine):
        fn = getattr(engine, "_ici_scatter_fn", None)
        if fn is None:
            def scatter(k_caches, v_caches, kp, vp, ids):
                new_k = [kc.at[ids].set(kp[i]) for i, kc in enumerate(k_caches)]
                new_v = [vc.at[ids].set(vp[i]) for i, vc in enumerate(v_caches)]
                return new_k, new_v

            # NOT donated: a dispatch failure after donation would leave the
            # engine pointing at deleted cache buffers while the caller falls
            # back to DCN and keeps serving. One cache copy per import — the
            # same cost the DCN import path (_scatter_blocks) already pays.
            fn = engine._ici_scatter_fn = jax.jit(scatter)
        return fn

    async def move(self, hashes: List[SequenceHash]) -> Optional[int]:
        """Copy the blocks for ``hashes`` src->dst device-side; returns blocks
        imported, or None on failure (caller falls back to the DCN path)."""
        import asyncio

        src, dst = self.src, self.dst
        loop = asyncio.get_event_loop()
        src_ids = src.allocator.acquire_prefix(hashes)  # pin (loop thread)
        if not src_ids:
            return 0
        try:
            n = len(src_ids)
            if not dst.allocator.can_allocate(n):
                # cheap pre-gather bail: don't burn a source-side gather
                # (stealing prefill step time) on a transfer that can't land
                log.warning("ici move: no room for %d blocks on dest", n)
                return 0

            def gather():
                ids = jnp.asarray(np.asarray(src_ids, np.int32))
                return IciKvMover._gather_fn(src)(src.k_caches, src.v_caches, ids)

            try:
                # [L, n, bs, kvh, d]: kv heads keep their TP sharding; the
                # device_put inside import_pages_device reshards onto the
                # destination mesh — the D2D hop.
                kp, vp = await loop.run_in_executor(src._executor, gather)
            except Exception:
                log.exception("ici move failed; falling back to DCN")
                return None
            got = await import_pages_device(dst, list(hashes[:n]), kp, vp)
            if got is None:
                log.warning("ici move scatter failed; falling back to DCN")
            return got
        finally:
            src.allocator.release(src_ids)


class KvTransferClient:
    """Fetches remote pages and imports them into a local engine's cache."""

    def __init__(self, engine, tcp_client: Optional[TcpClient] = None):
        self.engine = engine
        self._tcp = tcp_client or TcpClient()

    async def _fetch_item(self, address: str, req: Dict[str, Any]) -> Dict[str, Any]:
        """One wire fetch (request + drained single-item stream), replayed
        through the shared policy (scope transfer.pull): the protocol is
        content-addressed and idempotent, so a dropped connection retries
        safely; exhausted retries surface to the caller, which recomputes
        the prefill locally instead of failing the request."""
        async def once() -> Dict[str, Any]:
            await FAULTS.ainject("transfer.pull")
            stream = await self._tcp.call(address, req)
            item: Dict[str, Any] = {}
            async for it in stream:
                item = it
            return item

        # NoResponders is how the tcp client reports EVERY transport loss
        # (refused connect, reset mid-stream) — it is not a ConnectionError
        # subclass, so it must be named retryable explicitly. The attempt
        # timeout bounds a HUNG (not dropped) server so the decode side
        # falls back to recompute instead of stalling the request.
        return await retry_policy(
            "transfer.pull", max_attempts=3, base_delay_s=0.05, max_delay_s=1.0,
            attempt_timeout_s=30.0,
            retryable=RETRYABLE_DEFAULT + (NoResponders,),
        ).acall(once)

    def _block_nbytes(self) -> int:
        """Wire bytes of one block on THIS engine's cache format (the one
        byte-accounting source, kvbm/layout via the engine property) — used
        to price device-fabric moves that never materialize host bytes."""
        return int(self.engine.kv_bytes_per_block)

    async def fetch_and_import(
        self, address: str, hashes: List[SequenceHash],
        traceparent: Optional[str] = None, stream: bool = False,
        tier: bool = False,
    ) -> int:
        """Pull blocks for ``hashes`` from ``address``; returns tokens imported.

        Already-cached local blocks are skipped (only the missing suffix is
        fetched). Imported blocks are committed content-addressed, so the
        engine's normal admission path picks them up as a cached prefix.

        ``stream=True`` takes the block-window streaming protocol: windows
        import as the serving side commits them, overlapping the wire with
        the prefill side's remaining compute; a mid-stream loss resumes
        from the first un-imported block (never a whole-request restart).

        ``tier=True`` pulls from the peer's KVBM host/disk tiers (G2/G3)
        instead of its device cache — the fleet-wide KV reuse onboard path
        (kvbm/directory.py): same per-block resume semantics, blocks arrive
        in storage format and import bit-exactly for both float and int8.

        ``traceparent`` continues the request's trace: a ``kv.transfer.pull``
        span (wire path + bytes + blocks) is emitted here and shipped in the
        handshake so the serving side's span joins the same trace. Observed
        (bytes, seconds) per wire feed the process bandwidth estimator that
        prices future routing decisions."""
        from ..runtime.bandwidth import get_bandwidth_estimator

        tracer = get_tracer()
        info: Dict[str, Any] = {
            "wire": "none", "bytes": 0, "blocks": 0, "xfer_s": 0.0,
        }
        t0 = time.time_ns()
        status = "OK"
        tokens = 0
        try:
            if tier:
                tokens = await self._pull_tier(address, hashes, traceparent, info)
            else:
                tokens = await self._pull(address, hashes, traceparent, info, stream)
            return tokens
        except Exception:
            status = "ERROR"
            raise
        finally:
            # streamed pulls accumulate wire-active time per window (server
            # commit waits subtracted); blocking pulls are wire-active for
            # the whole call
            xfer_s = info["xfer_s"] or (time.time_ns() - t0) / 1e9
            get_bandwidth_estimator().observe(
                info["wire"], info["bytes"], xfer_s
            )
            if tracer.enabled:
                tracer.emit(
                    "kv.transfer.pull", t0, time.time_ns(),
                    traceparent=traceparent, status=status, address=address,
                    wire=info["wire"], bytes=info["bytes"],
                    blocks=info["blocks"], tokens=tokens,
                    streamed=bool(stream),
                )

    async def _pull(
        self, address: str, hashes: List[SequenceHash],
        traceparent: Optional[str], info: Dict[str, Any],
        stream: bool = False,
    ) -> int:
        alloc = self.engine.allocator
        have = len(alloc.match_prefix(hashes))
        want = hashes[have:]
        if not want:
            return have * alloc.block_size
        # same-process server (same-slice xPyD): pages move HBM->HBM over
        # the device fabric, skipping the wire entirely. DTPU_ICI_TRANSFER=0
        # forces the wire path (used by the DCN-protocol tests).
        local = (
            LOCAL_SERVERS.get(address)
            if os.environ.get("DTPU_ICI_TRANSFER", "1") != "0" else None
        )
        if local is not None and (
            not mesh_is_addressable(local.engine.mesh)
            or not mesh_is_addressable(self.engine.mesh)
        ):
            # a multihost engine's gather/scatter are group collectives; the
            # in-process mover would dispatch them leader-only and hang the
            # group — take the wire protocol instead
            local = None
        if local is not None and (
            getattr(local.engine, "kv_quantized", False)
            or getattr(self.engine, "kv_quantized", False)
        ):
            # int8 caches: the ICI mover's gather/scatter move raw cache
            # arrays, not the payload+scales pair — wire protocol instead
            # (which ships the half-width int8 blocks anyway)
            local = None
        if local is not None and local.engine is not self.engine:
            if stream:
                moved = await self._ici_stream(local.engine, want, info)
            else:
                t_ici = time.monotonic()
                moved = await IciKvMover(local.engine, self.engine).move(list(want))
                if moved:
                    info.update(
                        bytes=moved * self._block_nbytes(),
                        xfer_s=time.monotonic() - t_ici,
                    )
            if moved is not None:
                info.update(wire="ici", blocks=moved)
                return (have + moved) * alloc.block_size
            # device path failed: fall through to the DCN protocol
        if stream:
            imported = await self._pull_stream(address, want, traceparent, info)
            return (have + imported) * alloc.block_size
        from ..transfer import native_available

        # device offers are only solicited when the pull could land: room to
        # allocate, local devices to land on (the offer gathers pages server-
        # side; asking for one we'd discard wastes prefill step time)
        device_ok = (
            device_transfer_available()
            and mesh_is_addressable(self.engine.mesh)
            and not getattr(self.engine, "kv_quantized", False)
            and alloc.can_allocate(len(want))
        )
        req = {
            "hashes": [int(h) for h in want],
            "native_ok": native_available(),
        }
        if traceparent:
            # the serving side parents its kv.transfer.serve span on this
            req["traceparent"] = traceparent
        if device_ok:
            req["device_ok"] = True
            req["device_shards"] = len(jax.local_devices())
        item = await self._fetch_item(address, req)
        matched = item.get("matched", 0)
        if matched == 0:
            return have * alloc.block_size
        if "device" in item:
            got = await self._device_pull(address, item, list(want[:matched]))
            if got is not None:
                dev = item["device"]
                info.update(
                    wire="device", blocks=got,
                    bytes=2 * int(np.prod(dev["shape"]))
                    * _dtype_from_name(dev["dtype"]).itemsize,
                )
                return (have + got) * alloc.block_size
            # cross-process device pull failed: one retry over the wire
            req.pop("device_ok", None)
            item = await self._fetch_item(address, req)
            matched = item.get("matched", 0)
            if matched == 0 or "device" in item:
                return have * alloc.block_size
        if "native" in item:
            block_major = await self._native_fetch(address, item, matched)
            if block_major is None:
                return have * alloc.block_size
            info.update(
                wire="native",
                bytes=matched * int(item.get("block_bytes", 0)),
            )
        else:
            dtype = _dtype_from_name(item.get("dtype", "float32"))
            arr = np.frombuffer(item.get("data", b""), dtype).reshape(
                item.get("shape", [])
            )
            info.update(
                wire="inline",
                bytes=len(item.get("data", b""))
                + len(item.get("scales", b"")),
            )
            if "scales" in item:
                # int8 wire: payload [L, 2, n, bs, kvh, d] + scales
                # [L, 2, n, kvh] — import the pair as-is (the engine
                # scatter quantize/dequantizes only on a cache-mode
                # mismatch; matched int8 ends round-trip bit-exactly)
                L, _, n = arr.shape[:3]
                scales = np.frombuffer(
                    item["scales"], SCALE_DTYPE
                ).reshape(L, 2, n, arr.shape[4])
                block_major = (
                    np.ascontiguousarray(np.moveaxis(arr, 2, 0)),
                    np.ascontiguousarray(np.moveaxis(scales, 2, 0)),
                )
            else:
                block_major = np.ascontiguousarray(np.moveaxis(arr, 2, 0))
        imported = await self.engine.import_blocks(
            list(want[:matched]), block_major
        )
        info["blocks"] = imported
        return (have + imported) * alloc.block_size

    async def _ici_stream(
        self, src_engine, want: List[SequenceHash], info: Dict[str, Any]
    ) -> Optional[int]:
        """Streamed same-process transfer: move the committed prefix over
        the device fabric window by window, waiting on the source engine's
        commit signal while later prefill chunks are still computing.
        Returns blocks moved, or None when the first move fails outright
        (caller falls back to the wire)."""
        mover = IciKvMover(src_engine, self.engine)
        sig = getattr(src_engine, "kv_commits", None)
        moved_total = 0
        active_s = 0.0
        failed = False
        gen = sig.gen if sig is not None else 0
        t_window = time.monotonic()
        while moved_total < len(want):
            t_move = time.monotonic()
            moved = await mover.move(list(want[moved_total:]))
            if moved is None:
                failed = True
                break
            if moved:
                active_s += time.monotonic() - t_move
                moved_total += moved
                t_window = time.monotonic()
                continue
            waited = time.monotonic() - t_window
            if waited >= STREAM_WAIT_S or sig is None:
                break  # source has nothing more coming: recompute the rest
            gen = await sig.wait(
                gen, min(_STREAM_POLL_S, STREAM_WAIT_S - waited)
            )
        info.update(
            bytes=moved_total * self._block_nbytes(),
            xfer_s=active_s,
        )
        if failed and not moved_total:
            return None  # nothing moved: let the caller try the wire
        return moved_total

    async def _pull_tier(
        self, address: str, hashes: List[SequenceHash],
        traceparent: Optional[str], info: Dict[str, Any],
    ) -> int:
        """Onboard blocks from a PEER's KVBM host/disk tiers (G2/G3) — the
        global-directory fetch path (kvbm/directory.py). Same resume
        discipline as ``_pull_stream``: each window imports as it lands, a
        mid-stream loss re-requests from the first un-imported block, and
        STREAM_MAX_RESUMES progress-less attempts abandon the suffix to
        recompute. Blocks arrive in the peer's storage format: ``model``
        windows are already block-major pages; ``int8`` windows are flat
        codec buffers decoded to the (payload, scales) pair — both import
        bit-exactly (a float window at an int8 cache quantizes on scatter,
        and vice versa dequantizes, exactly like every other wire)."""
        import asyncio

        alloc = self.engine.allocator
        have = len(alloc.match_prefix(hashes))
        want = list(hashes[have:])
        n = len(want)
        if n == 0:
            return have * alloc.block_size
        imported = 0
        resumes = 0
        while imported < n:
            req: Dict[str, Any] = {
                "tier": True,
                "hashes": [int(h) for h in want[imported:]],
                "window": STREAM_WINDOW_BLOCKS,
            }
            if traceparent:
                req["traceparent"] = traceparent
            eof = False
            progressed = False
            try:
                await FAULTS.ainject("fetch.peer_tier")
                stream = await self._tcp.call(address, req)
                t_prev = time.monotonic()
                async for item in stream:
                    if item.get("eof"):
                        eof = True
                        break
                    # chaos hook: a mid-fetch window fault drops the stream
                    # through the real per-block resume path (no-op unarmed)
                    await FAULTS.ainject("fetch.peer_tier")
                    m = int(item.get("matched", 0))
                    if m <= 0:
                        continue
                    raw = np.frombuffer(
                        item.get("data", b""),
                        _dtype_from_name(item.get("dtype", "float32")),
                    ).reshape(item.get("shape", []))
                    crcs = item.get("crc32")
                    if crcs is not None and any(
                        zlib.crc32(np.ascontiguousarray(raw[i]).view(np.uint8))
                        != crcs[i]
                        for i in range(m)
                    ):
                        # torn tier read server-side: don't import poison
                        # under a valid content hash — recompute instead
                        log.warning(
                            "peer-tier window checksum mismatch from %s; "
                            "abandoning fetch at %d/%d blocks",
                            address, imported, n,
                        )
                        info["blocks"] = imported
                        return (have + imported) * alloc.block_size
                    if item.get("fmt") == "int8":
                        from ..kvbm.layout import BlockShape, QuantizedBlockCodec

                        L, _, bs, kvh, d = item["block_shape"]
                        codec = QuantizedBlockCodec(BlockShape(
                            num_layers=L, block_size=bs, num_kv_heads=kvh,
                            head_dim=d, dtype=np.dtype(np.int8),
                        ))
                        block_major = codec.decode_many(raw)
                    else:
                        block_major = raw  # storage format IS block-major
                    leg = max(time.monotonic() - t_prev, 1e-9)
                    w_hashes = list(want[imported : imported + m])
                    got = await self.engine.import_blocks(w_hashes, block_major)
                    info["wire"] = "tier"
                    info["bytes"] += len(item.get("data", b""))
                    info["xfer_s"] += leg
                    imported += got
                    progressed = progressed or got > 0
                    if got < m:
                        # local allocator full: keep what landed
                        info["blocks"] = imported
                        return (have + imported) * alloc.block_size
                    t_prev = time.monotonic()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning(
                    "peer-tier fetch from %s lost after %d/%d blocks (%r); "
                    "resuming from the first missing block",
                    address, imported, n, e,
                )
            if eof:
                break
            if progressed:
                resumes = 0
            else:
                resumes += 1
                if resumes > STREAM_MAX_RESUMES:
                    log.warning(
                        "peer-tier fetch from %s exhausted %d resume attempts "
                        "at %d/%d blocks; recomputing the remaining suffix",
                        address, STREAM_MAX_RESUMES, imported, n,
                    )
                    break
                await asyncio.sleep(min(0.05 * resumes, 0.5))
        info["blocks"] = imported
        return (have + imported) * alloc.block_size

    async def _pull_stream(
        self, address: str, want: List[SequenceHash],
        traceparent: Optional[str], info: Dict[str, Any],
    ) -> int:
        """Consume the block-window streaming protocol: import each window
        as it arrives, resume from the first un-imported block on any
        mid-stream loss (idempotent content addressing makes the re-request
        safe), give up on the remaining suffix after STREAM_MAX_RESUMES
        consecutive progress-less attempts — the engine then recomputes
        only the lost blocks."""
        import asyncio

        from ..transfer import native_available

        n = len(want)
        imported = 0
        resumes = 0
        while imported < n:
            req: Dict[str, Any] = {
                "hashes": [int(h) for h in want[imported:]],
                "stream": True,
                "window": STREAM_WINDOW_BLOCKS,
                "wait_s": STREAM_WAIT_S,
                "native_ok": native_available(),
            }
            if traceparent:
                req["traceparent"] = traceparent
            eof = False
            progressed = False
            try:
                await FAULTS.ainject("transfer.pull")
                stream = await self._tcp.call(address, req)
                t_prev = time.monotonic()
                async for item in stream:
                    if item.get("eof"):
                        eof = True
                        break
                    # chaos hook: an armed mid-stream window fault drops the
                    # stream through the real resume path (no-op unarmed)
                    await FAULTS.ainject("transfer.stream_window")
                    m = int(item.get("matched", 0))
                    if m <= 0:
                        continue
                    w_hashes = list(want[imported : imported + m])
                    if "native" in item:
                        block_major = await self._native_fetch(address, item, m)
                        if block_major is None:
                            raise ConnectionError(
                                "native window fetch failed mid-stream"
                            )
                        wire = "native"
                        nbytes = m * int(item.get("block_bytes", 0))
                    else:
                        dtype = _dtype_from_name(item.get("dtype", "float32"))
                        arr = np.frombuffer(
                            item.get("data", b""), dtype
                        ).reshape(item.get("shape", []))
                        nbytes = len(item.get("data", b"")) + len(
                            item.get("scales", b"")
                        )
                        if "scales" in item:
                            L = arr.shape[0]
                            scales = np.frombuffer(
                                item["scales"], SCALE_DTYPE
                            ).reshape(L, 2, m, arr.shape[4])
                            block_major = (
                                np.ascontiguousarray(np.moveaxis(arr, 2, 0)),
                                np.ascontiguousarray(np.moveaxis(scales, 2, 0)),
                            )
                        else:
                            block_major = np.ascontiguousarray(
                                np.moveaxis(arr, 2, 0)
                            )
                        wire = "inline"
                    # wire-active seconds: inter-item latency minus the
                    # server-side commit wait it reported for this window
                    leg = max(
                        time.monotonic() - t_prev
                        - float(item.get("wait_s", 0.0)),
                        1e-9,
                    )
                    got = await self.engine.import_blocks(w_hashes, block_major)
                    info["wire"] = wire
                    info["bytes"] += nbytes
                    info["xfer_s"] += leg
                    imported += got
                    progressed = progressed or got > 0
                    if got < m:
                        # local allocator full: stop pulling, serve with what
                        # landed (admission recomputes the rest)
                        info["blocks"] = imported
                        return imported
                    t_prev = time.monotonic()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning(
                    "kv stream from %s lost after %d/%d blocks (%r); "
                    "resuming from the first missing block",
                    address, imported, n, e,
                )
            if eof:
                break
            if progressed:
                resumes = 0
            else:
                resumes += 1
                if resumes > STREAM_MAX_RESUMES:
                    log.warning(
                        "kv stream from %s exhausted %d resume attempts at "
                        "%d/%d blocks; recomputing the remaining suffix",
                        address, STREAM_MAX_RESUMES, imported, n,
                    )
                    break
                await asyncio.sleep(min(0.05 * resumes, 0.5))
        info["blocks"] = imported
        return imported

    async def _device_pull(
        self, address: str, item: Dict[str, Any], hashes: List[SequenceHash]
    ) -> Optional[int]:
        """Pull offered pages device-to-device and scatter them in. Returns
        blocks imported, or None on failure (caller retries over the wire)."""
        import asyncio

        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        dev = item["device"]
        eng = self.engine
        shards = int(dev["shards"])
        # prefer the engine's own devices for the landing buffers; top up
        # from other local devices if the pull layout is wider than its mesh
        eng_devs = list(eng.mesh.devices.flat)
        pool = eng_devs + [d for d in jax.local_devices() if d not in eng_devs]
        if len(pool) < shards:
            log.warning(
                "device pull wants %d shards; only %d local devices",
                shards, len(pool),
            )
            return None
        pull_sh = NamedSharding(
            Mesh(np.array(pool[:shards]), ("x",)), P(None, None, None, "x", None)
        )
        dtype = _dtype_from_name(dev["dtype"])
        spec = jax.ShapeDtypeStruct(tuple(dev["shape"]), dtype, sharding=pull_sh)
        loop = asyncio.get_event_loop()

        def dial_and_pull():
            # the dial (and the lazy local server start) blocks: keep it off
            # the event loop that drives engine scheduling
            conn = _xfer_connect(dev["address"])
            return conn.pull(int(dev["uuid"]), [spec, spec])

        try:
            kp, vp = await loop.run_in_executor(None, dial_and_pull)
        except Exception:
            log.exception("device pull failed; retrying over the wire")
            # drop the cached connection: a broken one would otherwise
            # permanently disable the fast path to this address. Do NOT
            # free_pull here — the server's expiry must count this offer
            # toward its leak budget (freeing would hide every real leak).
            _proc_xfer_conns.pop(dev["address"], None)
            return None
        # pull landed: release the server's refs
        try:
            stream = await self._tcp.call(address, {"free_pull": int(dev["uuid"])})
            async for _ in stream:
                pass
        except Exception:
            pass  # server-side expiry reclaims the offer
        return await import_pages_device(eng, hashes, kp, vp)

    async def _native_fetch(
        self, address: str, item: Dict[str, Any], matched: int
    ):
        """Bulk-fetch leased slots over the C++ agent; returns block-major
        pages [n, L, 2, bs, kvh, d] in the server's wire dtype — or, for an
        int8 server, the decoded (payload, scales) pair — or None on failure
        (caller recomputes)."""
        import asyncio

        from ..transfer import native_fetch

        nat = item["native"]
        block_shape = item["block_shape"]
        dtype = _dtype_from_name(item.get("dtype", "float32"))
        quantized = item.get("kv_dtype") == "int8"
        block_bytes = int(
            item.get("block_bytes", int(np.prod(block_shape)) * dtype.itemsize)
        )
        loop = asyncio.get_event_loop()
        try:
            raw = await loop.run_in_executor(
                None, native_fetch,
                nat["host"], nat["port"], nat["region"], nat["slots"], block_bytes,
            )
        except Exception:
            log.exception("native kv fetch failed; recomputing prefill locally")
            return None
        finally:
            try:
                stream = await self._tcp.call(
                    address,
                    {"free_slots": nat["slots"], "token": nat.get("token")},
                )
                async for _ in stream:
                    pass
            except Exception:
                pass  # lease expiry reclaims the slots
        # integrity check: if our lease expired mid-read and a re-lease
        # overwrote the slots, the bytes are torn — importing them would
        # poison the content-addressed prefix cache with wrong KV under a
        # valid hash. Verify against the server's gather-time checksums.
        expected = nat.get("crc32")
        if expected is not None:
            for i in range(matched):
                if zlib.crc32(raw[i]) != expected[i]:
                    log.warning(
                        "kv transfer checksum mismatch on slot %s (stale "
                        "lease overwrite?); recomputing prefill locally",
                        nat["slots"][i],
                    )
                    return None
        if quantized:
            from ..kvbm.layout import BlockShape, QuantizedBlockCodec

            L, _, bs, kvh, d = block_shape
            codec = QuantizedBlockCodec(BlockShape(
                num_layers=L, block_size=bs, num_kv_heads=kvh, head_dim=d,
                dtype=np.dtype(np.int8),
            ))
            return codec.decode_many(raw[:matched])
        return raw.view(dtype).reshape([matched] + list(block_shape))

    async def close(self) -> None:
        await self._tcp.close()
