"""EngineWatchdog: deregister a worker the moment its engine dies.

Analog of the reference's vLLM engine monitor
(components/src/dynamo/vllm/engine_monitor.py): watches engine health and, on
a step-loop crash, pulls the worker's registration (model card + instance
key) out of discovery BEFORE new requests can be routed to it — in-flight
requests already got their error frames from the crashed loop, and the
frontend's Migration operator replays them elsewhere.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, List, Optional

from ..runtime.component import ServedEndpoint
from ..runtime.health import HealthState
from ..runtime.logging import get_logger

log = get_logger("engine.monitor")


class EngineWatchdog:
    def __init__(
        self,
        engine,                             # anything with .healthy: bool
        served: List[ServedEndpoint],
        state: Optional[HealthState] = None,
        poll_s: float = 0.25,
        on_down: Optional[Callable[[], Awaitable[None]]] = None,
    ):
        self.engine = engine
        self.served = served
        self.state = state or HealthState()
        self.poll_s = poll_s
        self.on_down = on_down
        self.fired = False
        self._task: Optional[asyncio.Task] = None
        self.state.set("engine", True)

    async def _trip(self) -> None:
        if self.fired:
            return
        self.fired = True
        self.state.set("engine", False, "engine loop crashed")
        log.error("engine unhealthy: deregistering %d endpoints", len(self.served))
        for s in self.served:
            try:
                # deletes the instance + model-card keys first, so discovery
                # drops the model before the request server stops answering
                await s.stop(graceful_timeout_s=0.5)
            except Exception:
                log.exception("deregistering %s failed", s.endpoint.path)
        if self.on_down is not None:
            await self.on_down()

    def start(self) -> "EngineWatchdog":
        # push path: the engine invokes on_crash from its crash handler, so
        # deregistration starts immediately; the poll below is the fallback
        # for engines without the hook (and for healthy flipped elsewhere)
        if hasattr(self.engine, "on_crash"):
            async def on_crash(exc: BaseException) -> None:
                await self._trip()

            self.engine.on_crash = on_crash

        async def loop() -> None:
            try:
                while True:
                    if not getattr(self.engine, "healthy", True):
                        await self._trip()
                        return
                    await asyncio.sleep(self.poll_s)
            except asyncio.CancelledError:
                pass

        self._task = asyncio.create_task(loop())
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
