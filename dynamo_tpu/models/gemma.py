"""Gemma 2 / Gemma 3 (text) family.

The reference serves Gemma through its engine adapters; this engine owns
the model, so the family lives here like llama/moe/mla/gptoss. What makes
Gemma not-llama (all verified against the HF reference implementations,
transformers models/gemma2/modeling_gemma2.py and gemma3/modeling_gemma3.py,
and pinned by tests/test_gemma_parity.py):

- RMSNorm computes in float32 and scales by (1 + weight) — the zero-init
  convention (Gemma2RMSNorm.forward).
- embeddings are scaled by sqrt(hidden_size) CAST TO THE MODEL DTYPE first
  (the HF "normalizer" downcast quirk — sqrt(3072) becomes 55.5 in bf16;
  parity requires reproducing it).
- sandwich norms: post_attention_layernorm wraps the attention OUTPUT and
  post_feedforward_layernorm wraps the MLP output, in addition to the
  usual pre-norms.
- attention scale is query_pre_attn_scalar**-0.5, not head_dim**-0.5
  (implemented by pre-scaling q so the attention ops stay unchanged).
- interleaved sliding-window / full attention per layer_types, riding the
  same paged ``window`` machinery as gpt-oss (ops/attention.py).
- Gemma 2: attention-logit softcapping (tanh) inside the score matrix
  (ops/attention.py ``softcap``) and final-logit softcapping in lm_logits.
- Gemma 3: per-head q/k RMSNorm (Gemma convention), no softcaps, and DUAL
  rope — sliding layers use rope_local_base_freq, full layers use
  rope_theta with an optional linear position scale (factor 8 on the
  released checkpoints).
- GeGLU MLP: gelu_tanh(gate) * up.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from .llama import AttendFn, LlamaConfig, Params


@dataclasses.dataclass(frozen=True)
class GemmaConfig(LlamaConfig):
    query_pre_attn_scalar: float = 256.0
    sliding_window: int = 4096
    # per-layer kinds: "sliding" | "full"; () = derive from sliding_pattern
    layer_types: Tuple[str, ...] = ()
    # every Nth layer is full attention (gemma2: 2 -> alternate, full on
    # odd; gemma3: 6 -> five sliding then one full)
    sliding_pattern: int = 2
    attn_logit_softcap: Optional[float] = None   # gemma2: 50.0
    final_logit_softcap: Optional[float] = None  # gemma2: 30.0
    # gemma3 dual rope: sliding layers use the local theta (no scaling),
    # full layers use rope_theta / linear factor
    rope_local_theta: Optional[float] = None
    rope_scaling_factor: float = 1.0

    def kind_for_layer(self, layer_idx: int) -> str:
        if self.layer_types:
            return self.layer_types[layer_idx]
        # HF convention for both families: layer_idx+1 % pattern == 0 ->
        # full ("sliding_attention" otherwise)
        return "full" if (layer_idx + 1) % self.sliding_pattern == 0 else "sliding"

    def window_for_layer(self, layer_idx: int) -> Optional[int]:
        return self.sliding_window if self.kind_for_layer(layer_idx) == "sliding" else None

    @classmethod
    def tiny_gemma2(cls, **kw) -> "GemmaConfig":
        defaults = dict(
            vocab_size=512, hidden_size=64, num_layers=4, num_heads=4,
            num_kv_heads=2, head_dim=16, intermediate_size=128,
            query_pre_attn_scalar=16.0, sliding_window=16,
            attn_logit_softcap=50.0, final_logit_softcap=30.0,
            tie_embeddings=True, dtype=jnp.float32,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def tiny_gemma3(cls, **kw) -> "GemmaConfig":
        defaults = dict(
            vocab_size=512, hidden_size=64, num_layers=6, num_heads=4,
            num_kv_heads=2, head_dim=16, intermediate_size=128,
            query_pre_attn_scalar=16.0, sliding_window=16,
            sliding_pattern=3, qk_norm=True, rope_theta=1_000_000.0,
            rope_local_theta=10_000.0, rope_scaling_factor=8.0,
            tie_embeddings=True, dtype=jnp.float32,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def gemma2_2b(cls, vocab_size: int = 256000) -> "GemmaConfig":
        return cls(
            vocab_size=vocab_size, hidden_size=2304, num_layers=26,
            num_heads=8, num_kv_heads=4, head_dim=256,
            intermediate_size=9216, query_pre_attn_scalar=256.0,
            sliding_window=4096, attn_logit_softcap=50.0,
            final_logit_softcap=30.0, tie_embeddings=True,
            max_position=8192,
        )

    @classmethod
    def gemma3_4b(cls, vocab_size: int = 262208) -> "GemmaConfig":
        return cls(
            vocab_size=vocab_size, hidden_size=2560, num_layers=34,
            num_heads=8, num_kv_heads=4, head_dim=256,
            intermediate_size=10240, query_pre_attn_scalar=256.0,
            sliding_window=1024, sliding_pattern=6, qk_norm=True,
            rope_theta=1_000_000.0, rope_local_theta=10_000.0,
            rope_scaling_factor=8.0, tie_embeddings=True,
            max_position=131072,
        )


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------


def gemma_rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    """Gemma convention: float32 math, scale by (1 + weight) BEFORE the
    downcast ((x*w).to(dtype), not x.to(dtype)*w — HF PR #29402)."""
    xf = x.astype(jnp.float32)
    normed = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (normed * (1.0 + w.astype(jnp.float32))).astype(x.dtype)


# rope: the HF rotate-half layout is exactly llama's — reuse those helpers.
# Gemma3's linear position scale on full-attention layers folds into the
# positions BEFORE the table build (positions / factor).
from .llama import apply_rope, rope_cos_sin  # noqa: E402


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_layer_params(rng: jax.Array, cfg: GemmaConfig) -> Params:
    k = jax.random.split(rng, 7)
    h, qd, kvd = cfg.hidden_size, cfg.q_size, cfg.kv_size
    inter = cfg.intermediate_size
    scale = 1.0 / math.sqrt(h)
    p: Params = {
        "attn_norm": jnp.zeros((h,), cfg.dtype),
        "post_attn_norm": jnp.zeros((h,), cfg.dtype),
        "pre_mlp_norm": jnp.zeros((h,), cfg.dtype),
        "post_mlp_norm": jnp.zeros((h,), cfg.dtype),
        "wq": (jax.random.normal(k[0], (h, qd)) * scale).astype(cfg.dtype),
        "wk": (jax.random.normal(k[1], (h, kvd)) * scale).astype(cfg.dtype),
        "wv": (jax.random.normal(k[2], (h, kvd)) * scale).astype(cfg.dtype),
        "wo": (jax.random.normal(k[3], (qd, h)) * scale).astype(cfg.dtype),
        "w_gate": (jax.random.normal(k[4], (h, inter)) * scale).astype(cfg.dtype),
        "w_up": (jax.random.normal(k[5], (h, inter)) * scale).astype(cfg.dtype),
        "w_down": (jax.random.normal(k[6], (inter, h)) * (1.0 / math.sqrt(inter))).astype(cfg.dtype),
    }
    if cfg.qk_norm:
        p["q_norm"] = jnp.zeros((cfg.head_dim,), cfg.dtype)
        p["k_norm"] = jnp.zeros((cfg.head_dim,), cfg.dtype)
    return p


def init_params(rng: jax.Array, cfg: GemmaConfig) -> Params:
    keys = jax.random.split(rng, cfg.num_layers + 2)
    params: Params = {
        "embed": (
            jax.random.normal(keys[0], (cfg.vocab_size, cfg.hidden_size)) * 0.02
        ).astype(cfg.dtype),
        "final_norm": jnp.zeros((cfg.hidden_size,), cfg.dtype),
        "layers": [
            init_layer_params(keys[i + 2], cfg) for i in range(cfg.num_layers)
        ],
    }
    return params


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def layer_forward(
    p: Params,
    cfg: GemmaConfig,
    x: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    attend: AttendFn,
    layer_idx: int,
) -> jax.Array:
    lead = x.shape[:-1]
    h = gemma_rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
    q = (h @ p["wq"]).reshape(*lead, cfg.num_heads, cfg.head_dim)
    k = (h @ p["wk"]).reshape(*lead, cfg.num_kv_heads, cfg.head_dim)
    v = (h @ p["wv"]).reshape(*lead, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:  # gemma3 per-head norms, gemma convention
        q = gemma_rms_norm(q, p["q_norm"], cfg.rms_norm_eps)
        k = gemma_rms_norm(k, p["k_norm"], cfg.rms_norm_eps)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    # the attention ops scale by head_dim**-0.5; gemma wants
    # query_pre_attn_scalar**-0.5 — fold the ratio into q
    q = q * jnp.asarray(
        math.sqrt(cfg.head_dim) / math.sqrt(cfg.query_pre_attn_scalar),
        q.dtype,
    )
    attn = attend(
        q, k, v, layer_idx,
        window=cfg.window_for_layer(layer_idx),
        softcap=cfg.attn_logit_softcap,
    )
    attn = attn.reshape(*lead, cfg.q_size) @ p["wo"]
    x = x + gemma_rms_norm(attn, p["post_attn_norm"], cfg.rms_norm_eps)

    h2 = gemma_rms_norm(x, p["pre_mlp_norm"], cfg.rms_norm_eps)
    gate = jax.nn.gelu(
        (h2 @ p["w_gate"]).astype(jnp.float32), approximate=True
    ).astype(x.dtype)
    mlp = (gate * (h2 @ p["w_up"])) @ p["w_down"]
    return x + gemma_rms_norm(mlp, p["post_mlp_norm"], cfg.rms_norm_eps)


def forward(
    params: Params,
    cfg: GemmaConfig,
    token_ids: jax.Array,
    positions: jax.Array,
    attend: AttendFn,
    lora: Optional[Callable] = None,
    inputs_embeds: Optional[jax.Array] = None,
) -> jax.Array:
    if lora is not None:
        raise NotImplementedError("LoRA is not supported for the gemma family")
    x = params["embed"][token_ids] if inputs_embeds is None else inputs_embeds
    # the HF normalizer downcast quirk is part of the checkpoint contract
    x = x * jnp.asarray(math.sqrt(cfg.hidden_size), x.dtype)
    tables = {}

    def rope_for(layer_idx: int):
        if cfg.rope_local_theta is None:
            key = ("global",)
            theta, scale = cfg.rope_theta, 1.0
        elif cfg.kind_for_layer(layer_idx) == "sliding":
            key = ("local",)
            theta, scale = cfg.rope_local_theta, 1.0
        else:
            key = ("global",)
            theta, scale = cfg.rope_theta, cfg.rope_scaling_factor
        if key not in tables:
            cos, sin = rope_cos_sin(
                positions.astype(jnp.float32) / scale, cfg.head_dim, theta
            )
            tables[key] = (cos[..., None, :], sin[..., None, :])
        return tables[key]

    for i, layer in enumerate(params["layers"]):
        cos, sin = rope_for(i)
        x = layer_forward(layer, cfg, x, cos, sin, attend, i)
    return gemma_rms_norm(x, params["final_norm"], cfg.rms_norm_eps)


def lm_logits(params: Params, cfg: GemmaConfig, hidden: jax.Array) -> jax.Array:
    head = params.get("lm_head")  # untied finetunes; released gemma ties
    logits = (
        hidden @ head if head is not None else hidden @ params["embed"].T
    ).astype(jnp.float32)
    if cfg.final_logit_softcap:
        c = cfg.final_logit_softcap
        logits = jnp.tanh(logits / c) * c
    return logits
