"""gpt-oss family (OpenAI gpt-oss-20b/120b) in functional JAX.

The reference serves gpt-oss through its engine adapters (recipes/
gpt-oss-120b, TRT-LLM/vLLM workers) and parses its harmony dialect
(our parsers/tool_calls.py already speaks it); this module owns the model
itself, like models/llama.py owns the dense family. Architecture:

- GQA attention with **per-head attention sinks**: a learned logit joins
  the softmax as a virtual key whose probability mass is dropped, damping
  every real attention weight (ops/attention.py _sink_softmax).
- **Alternating sliding-window / full attention** layers
  (layer_types, window 128): handled by the paged attention ops'
  ``window`` argument — the engine's paged cache is unchanged, masks do
  the windowing. Head_dim 64 keeps these layers on the pure-JAX attention
  path automatically (the Pallas kernels require 128-aligned heads).
- MoE FFN in every layer: router = top-k over plain logits then softmax
  over the SELECTED logits; experts use a fused, biased gate_up projection
  with interleaved gate/up lanes and the clamped swiglu
  ``(up+1) * gate * sigmoid(alpha*gate)``.
- YaRN rope scaling (the released models run 4k->128k contexts).

Weights load from HF checkpoints (engine/weights.py) with logits parity
pinned against transformers in tests/test_gptoss_parity.py.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from .llama import LlamaConfig, Params, apply_rope, rms_norm


@dataclasses.dataclass(frozen=True)
class GptOssConfig(LlamaConfig):
    num_experts: int = 32
    num_experts_per_tok: int = 4
    sliding_window: int = 128
    # per-layer attention kind; empty = the released pattern (alternating,
    # even layers sliding). Tuple of "sliding_attention" / "full_attention".
    layer_types: Tuple[str, ...] = ()
    swiglu_alpha: float = 1.702
    swiglu_limit: float = 7.0
    # YaRN (factor 0 disables scaling)
    rope_scaling_factor: float = 0.0
    rope_beta_fast: float = 32.0
    rope_beta_slow: float = 1.0
    rope_truncate: bool = False
    rope_original_max_position: int = 4096

    def window_for_layer(self, layer_idx: int) -> Optional[int]:
        if self.layer_types:
            kind = self.layer_types[layer_idx]
        else:
            kind = "sliding_attention" if layer_idx % 2 == 0 else "full_attention"
        return self.sliding_window if kind == "sliding_attention" else None

    @classmethod
    def tiny_gptoss(cls, **kw) -> "GptOssConfig":
        defaults = dict(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, head_dim=16, intermediate_size=64,
            num_experts=4, num_experts_per_tok=2, sliding_window=8,
            qkv_bias=True, tie_embeddings=False, dtype=jnp.float32,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def gpt_oss_20b(cls, vocab_size: int = 201088) -> "GptOssConfig":
        return cls(
            vocab_size=vocab_size, hidden_size=2880, num_layers=24,
            num_heads=64, num_kv_heads=8, head_dim=64,
            intermediate_size=2880, num_experts=32, num_experts_per_tok=4,
            sliding_window=128, rope_theta=150000.0, qkv_bias=True,
            tie_embeddings=False, max_position=131072,
            rope_scaling_factor=32.0, rope_original_max_position=4096,
        )

    @classmethod
    def gpt_oss_120b(cls, vocab_size: int = 201088) -> "GptOssConfig":
        return cls(
            vocab_size=vocab_size, hidden_size=2880, num_layers=36,
            num_heads=64, num_kv_heads=8, head_dim=64,
            intermediate_size=2880, num_experts=128, num_experts_per_tok=4,
            sliding_window=128, rope_theta=150000.0, qkv_bias=True,
            tie_embeddings=False, max_position=131072,
            rope_scaling_factor=32.0, rope_original_max_position=4096,
        )


# ---------------------------------------------------------------------------
# rope (YaRN)
# ---------------------------------------------------------------------------


def yarn_inv_freq(cfg: GptOssConfig) -> Tuple[jax.Array, float]:
    """(inv_freq [d/2], attention_factor) per the YaRN recipe
    (transformers _compute_yarn_parameters semantics: interpolated and
    extrapolated frequencies blended over a linear ramp between the
    beta_fast/beta_slow correction dims; cos/sin scaled by
    0.1*ln(factor)+1)."""
    d, base = cfg.head_dim, cfg.rope_theta
    pos_freqs = base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    inv_extra = 1.0 / pos_freqs
    factor = cfg.rope_scaling_factor
    if factor <= 1.0:
        return inv_extra, 1.0
    inv_interp = 1.0 / (factor * pos_freqs)

    def corr_dim(rot):
        return (d * math.log(cfg.rope_original_max_position / (rot * 2 * math.pi))) / (
            2 * math.log(base)
        )

    low, high = corr_dim(cfg.rope_beta_fast), corr_dim(cfg.rope_beta_slow)
    if cfg.rope_truncate:
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, d - 1)
    if low == high:
        high += 0.001
    ramp = jnp.clip(
        (jnp.arange(d // 2, dtype=jnp.float32) - low) / (high - low), 0, 1
    )
    extra_factor = 1.0 - ramp
    inv_freq = inv_interp * (1 - extra_factor) + inv_extra * extra_factor
    return inv_freq, 0.1 * math.log(factor) + 1.0


def rope_tables(cfg: GptOssConfig, positions: jax.Array):
    inv_freq, att_factor = yarn_inv_freq(cfg)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles) * att_factor, jnp.sin(angles) * att_factor


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_layer_params(rng: jax.Array, cfg: GptOssConfig) -> Params:
    k = jax.random.split(rng, 10)
    h, qd, kvd = cfg.hidden_size, cfg.q_size, cfg.kv_size
    E, inter = cfg.num_experts, cfg.intermediate_size
    scale = 1.0 / math.sqrt(h)
    iscale = 1.0 / math.sqrt(inter)
    return {
        "attn_norm": jnp.ones((h,), cfg.dtype),
        "mlp_norm": jnp.ones((h,), cfg.dtype),
        "wq": (jax.random.normal(k[0], (h, qd)) * scale).astype(cfg.dtype),
        "wk": (jax.random.normal(k[1], (h, kvd)) * scale).astype(cfg.dtype),
        "wv": (jax.random.normal(k[2], (h, kvd)) * scale).astype(cfg.dtype),
        "wo": (jax.random.normal(k[3], (qd, h)) * scale).astype(cfg.dtype),
        "bq": jnp.zeros((qd,), cfg.dtype),
        "bk": jnp.zeros((kvd,), cfg.dtype),
        "bv": jnp.zeros((kvd,), cfg.dtype),
        "bo": jnp.zeros((h,), cfg.dtype),
        "sinks": jnp.zeros((cfg.num_heads,), jnp.float32),
        "w_router": (jax.random.normal(k[4], (h, E)) * scale).astype(cfg.dtype),
        "b_router": jnp.zeros((E,), cfg.dtype),
        # fused per-expert projections, HF layout: gate/up lanes interleaved
        "w_gateup": (
            jax.random.normal(k[5], (E, h, 2 * inter)) * scale
        ).astype(cfg.dtype),
        "b_gateup": jnp.zeros((E, 2 * inter), cfg.dtype),
        "w_edown": (
            jax.random.normal(k[6], (E, inter, h)) * iscale
        ).astype(cfg.dtype),
        "b_edown": jnp.zeros((E, h), cfg.dtype),
    }


def init_params(rng: jax.Array, cfg: GptOssConfig) -> Params:
    keys = jax.random.split(rng, cfg.num_layers + 2)
    params: Params = {
        "embed": (
            jax.random.normal(keys[0], (cfg.vocab_size, cfg.hidden_size)) * 0.02
        ).astype(cfg.dtype),
        "final_norm": jnp.ones((cfg.hidden_size,), cfg.dtype),
        "layers": [init_layer_params(keys[i + 2], cfg) for i in range(cfg.num_layers)],
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = (
            jax.random.normal(keys[1], (cfg.hidden_size, cfg.vocab_size)) * 0.02
        ).astype(cfg.dtype)
    return params


# ---------------------------------------------------------------------------
# router + experts
# ---------------------------------------------------------------------------


def route(p: Params, cfg: GptOssConfig, x: jax.Array):
    """gpt-oss router: top-k over raw logits, softmax over the SELECTED
    logits (not over all experts). x [T, H] -> (weights [T,K] f32, idx)."""
    logits = (
        x.astype(jnp.float32) @ p["w_router"].astype(jnp.float32)
        + p["b_router"].astype(jnp.float32)
    )
    topv, topi = jax.lax.top_k(logits, cfg.num_experts_per_tok)
    return jax.nn.softmax(topv, axis=-1), topi


def _expert_apply(cfg: GptOssConfig, w_gu, b_gu, w_dn, b_dn, x):
    """One selected expert per token: fused clamped-swiglu MLP.
    x [T, H]; w_gu [T, H, 2I]; returns [T, H] (float32 activations)."""
    gu = jnp.einsum("th,thi->ti", x.astype(jnp.float32), w_gu.astype(jnp.float32))
    gu = gu + b_gu.astype(jnp.float32)
    gate, up = gu[..., ::2], gu[..., 1::2]
    gate = jnp.minimum(gate, cfg.swiglu_limit)
    up = jnp.clip(up, -cfg.swiglu_limit, cfg.swiglu_limit)
    glu = gate * jax.nn.sigmoid(gate * cfg.swiglu_alpha)
    act = (up + 1.0) * glu
    out = jnp.einsum("ti,tih->th", act, w_dn.astype(jnp.float32))
    return out + b_dn.astype(jnp.float32)


def experts_gather(p: Params, cfg: GptOssConfig, x: jax.Array, routed) -> jax.Array:
    """Sparse exact path (replicated experts): per-slot weight gathers, K
    static — the same shape as moe.moe_ffn_gather but with gpt-oss's fused
    biased projections and clamped swiglu."""
    topw, topi = routed
    y = jnp.zeros(x.shape, jnp.float32)
    for k in range(cfg.num_experts_per_tok):
        idx = topi[:, k]
        out = _expert_apply(
            cfg, p["w_gateup"][idx], p["b_gateup"][idx],
            p["w_edown"][idx], p["b_edown"][idx], x,
        )
        y = y + topw[:, k, None] * out
    return y.astype(x.dtype)


def experts_ep_psum(
    p: Params, cfg: GptOssConfig, x: jax.Array, routed, axis_name: str
) -> jax.Array:
    """Inside shard_map: expert stacks sharded on the leading dim, tokens
    and routing replicated. Each shard computes only the selected experts it
    owns (masked gather), one psum combines."""
    topw, topi = routed
    E_loc = p["w_gateup"].shape[0]
    me = jax.lax.axis_index(axis_name)
    local = topi - me * E_loc
    y = jnp.zeros(x.shape, jnp.float32)
    for k in range(cfg.num_experts_per_tok):
        idx = jnp.clip(local[:, k], 0, E_loc - 1)
        mine = (local[:, k] >= 0) & (local[:, k] < E_loc)
        out = _expert_apply(
            cfg, p["w_gateup"][idx], p["b_gateup"][idx],
            p["w_edown"][idx], p["b_edown"][idx], x,
        )
        y = y + jnp.where(mine, topw[:, k], 0.0)[:, None] * out
    return jax.lax.psum(y, axis_name).astype(x.dtype)


def expert_params(p: Params) -> Params:
    return {k: p[k] for k in ("w_gateup", "b_gateup", "w_edown", "b_edown")}


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

# attend(q, k, v, layer_idx, window=..., sinks=...) — the engine's attends
# accept the extra kwargs and thread them into ops/attention.py
AttendFn = Callable[..., jax.Array]


def layer_forward(
    p: Params,
    cfg: GptOssConfig,
    x: jax.Array,                 # [..., S, hidden]
    cos: jax.Array,
    sin: jax.Array,
    attend: AttendFn,
    layer_idx: int,
    expert_fn=None,
) -> jax.Array:
    lead = x.shape[:-1]
    h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
    q = (h @ p["wq"] + p["bq"]).reshape(*lead, cfg.num_heads, cfg.head_dim)
    k = (h @ p["wk"] + p["bk"]).reshape(*lead, cfg.num_kv_heads, cfg.head_dim)
    v = (h @ p["wv"] + p["bv"]).reshape(*lead, cfg.num_kv_heads, cfg.head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn = attend(
        q, k, v, layer_idx,
        window=cfg.window_for_layer(layer_idx), sinks=p["sinks"],
    )
    attn = attn.reshape(*lead, cfg.q_size)
    x = x + (attn @ p["wo"] + p["bo"])
    # MoE FFN in every layer
    hn = rms_norm(x, p["mlp_norm"], cfg.rms_norm_eps)
    flat = hn.reshape(-1, hn.shape[-1])
    routed = route(p, cfg, flat)
    if expert_fn is not None:
        y = expert_fn(expert_params(p), flat, routed)
    else:
        y = experts_gather(p, cfg, flat, routed)
    return x + y.reshape(hn.shape)


def forward(
    params: Params,
    cfg: GptOssConfig,
    token_ids: jax.Array,
    positions: jax.Array,
    attend: AttendFn,
    lora: Optional[Callable] = None,
    inputs_embeds: Optional[jax.Array] = None,
    expert_fn=None,
) -> jax.Array:
    if lora is not None:
        raise NotImplementedError("LoRA is not supported for the gpt-oss family")
    x = params["embed"][token_ids] if inputs_embeds is None else inputs_embeds
    cos, sin = rope_tables(cfg, positions)
    cos, sin = cos[..., None, :], sin[..., None, :]
    for i, layer in enumerate(params["layers"]):
        x = layer_forward(layer, cfg, x, cos, sin, attend, i, expert_fn=expert_fn)
    return rms_norm(x, params["final_norm"], cfg.rms_norm_eps)


def lm_logits(params: Params, cfg: GptOssConfig, hidden: jax.Array) -> jax.Array:
    if cfg.tie_embeddings:
        return (hidden @ params["embed"].T).astype(jnp.float32)
    return (hidden @ params["lm_head"]).astype(jnp.float32)
