"""Model-family registry: one place that maps a model config to its
(init, forward, lm_logits, partition-spec) functions so the engine stays
family-agnostic (reference analog: engine selection by ModelDeploymentCard
rather than hard-coded architectures).
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ..parallel.mesh import AXIS_TP
from . import llama, moe


def is_moe(cfg) -> bool:
    return isinstance(cfg, moe.MoeConfig)


def init_params(rng, cfg):
    return (moe if is_moe(cfg) else llama).init_params(rng, cfg)


def forward_fn(cfg):
    return (moe if is_moe(cfg) else llama).forward


def lm_logits_fn(cfg):
    return (moe if is_moe(cfg) else llama).lm_logits


def param_specs(cfg) -> dict:
    """name -> PartitionSpec for top-level and per-layer params.

    Dense family: megatron TP (parallel/mesh.param_specs_llama). MoE: the
    expert-stacked FFN weights shard on the EXPERT dim over the tp axis
    (EP rides the same devices as attention TP); GSPMD inserts the psum at
    the expert-contraction einsum. The router is tiny and replicated.
    """
    top = {
        "embed": P(None, AXIS_TP),
        "final_norm": P(None),
        "lm_head": P(None, AXIS_TP),
    }
    layer = {
        "wq": P(None, AXIS_TP),
        "wk": P(None, AXIS_TP),
        "wv": P(None, AXIS_TP),
        "wo": P(AXIS_TP, None),
        "bq": P(AXIS_TP),
        "bk": P(AXIS_TP),
        "bv": P(AXIS_TP),
    }
    if is_moe(cfg):
        layer.update({
            "w_router": P(None, None),
            "w_gate": P(AXIS_TP, None, None),
            "w_up": P(AXIS_TP, None, None),
            "w_down": P(AXIS_TP, None, None),
        })
    else:
        layer.update({
            "w_gate": P(None, AXIS_TP),
            "w_up": P(None, AXIS_TP),
            "w_down": P(AXIS_TP, None),
        })
    return {"top": top, "layer": layer, "default": P()}
