"""Model-family registry: one place that maps a model config to its
(init, forward, lm_logits, partition-spec) functions so the engine stays
family-agnostic (reference analog: engine selection by ModelDeploymentCard
rather than hard-coded architectures).
"""

from __future__ import annotations

from functools import partial

from jax.sharding import PartitionSpec as P

from ..parallel.mesh import AXIS_TP, shard_map
from . import gemma, gptoss, llama, mla, moe


def is_moe(cfg) -> bool:
    return isinstance(cfg, moe.MoeConfig)


def is_mla(cfg) -> bool:
    return isinstance(cfg, mla.MlaConfig)


def is_gptoss(cfg) -> bool:
    return isinstance(cfg, gptoss.GptOssConfig)


def is_gemma(cfg) -> bool:
    return isinstance(cfg, gemma.GemmaConfig)


def supports_pp(cfg) -> bool:
    """Pipeline-parallel serving covers the dense llama family only: the
    stage placement stacks per-layer params homogeneously, which MoE expert
    stacks, MLA latent projections, and gpt-oss/gemma windowed-attention
    extras do not fit (parallel/pp_serving.py)."""
    return not (is_moe(cfg) or is_mla(cfg) or is_gptoss(cfg) or is_gemma(cfg))


def check_pp_supported(cfg) -> None:
    """One gate, one message: raised both at TpuEngine construction and at
    the pp_serving program builders, so a MoE/MLA/gpt-oss/gemma preset
    configured with pp>1 fails at the door with the fix spelled out instead
    of a KeyError deep in stacked-param placement."""
    if not supports_pp(cfg):
        raise ValueError(
            f"pp serving supports dense llama-family models only; "
            f"{type(cfg).__name__} (MoE/MLA/gpt-oss/gemma) is not stacked "
            f"for pipeline stages — configure this preset with pp=1 "
            f"(use tp/sp/dp instead)"
        )


def family(cfg):
    if is_mla(cfg):
        return mla
    if is_gptoss(cfg):
        return gptoss
    if is_gemma(cfg):
        return gemma
    return moe if is_moe(cfg) else llama


def init_params(rng, cfg):
    return family(cfg).init_params(rng, cfg)


def _ep_psum_shard_map(mesh, weight_specs, kernel, n_extra_args):
    """THE shard_map construction site for every family's EP path:
    expert-stacked weights sharded per ``weight_specs``, tokens (and any
    precomputed routing) replicated, ``kernel`` per shard with a psum
    combine inside. One site = the collective shape cannot drift between
    the MoeConfig, MLA, and gpt-oss families. ``n_extra_args``: 0 for
    kernel(shard_params, x), 1 for kernel(shard_params, x, routed)."""
    extra = ((P(), P()),) * n_extra_args
    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(weight_specs, P(), *extra),
        out_specs=P(),
        check_vma=False,
    )


def forward_fn(cfg, mesh=None):
    """Forward pass for the family. For MoE the FFN strategy is picked here
    so serving never pays dense all-expert FLOPs (ADVICE r2):

    - experts replicated (no mesh / tp==1): exact per-token gather
      (moe_ffn_gather, T*K expert applications instead of T*E)
    - experts sharded over tp (EP rides the TP axis): shard_map'd
      moe_ffn_ep_psum — each shard computes only its local experts, one
      psum combines (same collective as a TP row matmul)
    """
    if is_gptoss(cfg):
        if mesh is None or mesh.shape.get(AXIS_TP, 1) == 1:
            return gptoss.forward

        # EP: gpt-oss's own expert kernel (fused biased gate_up, clamped
        # swiglu) sharded on the expert dim; router replicated outside
        gu_specs = {
            "w_gateup": P(AXIS_TP, None, None),
            "b_gateup": P(AXIS_TP, None),
            "w_edown": P(AXIS_TP, None, None),
            "b_edown": P(AXIS_TP, None),
        }

        def gptoss_expert_fn(ep, x, routed):
            fn = _ep_psum_shard_map(
                mesh, gu_specs,
                lambda sp, sx, srouted: gptoss.experts_ep_psum(
                    sp, cfg, sx, srouted, AXIS_TP
                ),
                1,
            )
            return fn(ep, x, routed)

        return partial(gptoss.forward, expert_fn=gptoss_expert_fn)
    if is_mla(cfg):
        if cfg.num_experts == 0 or mesh is None or mesh.shape.get(AXIS_TP, 1) == 1:
            # per-token gather kernel (exact, sparse) on replicated experts
            return mla.forward

        # EP: expert stacks shard on the expert dim over the tp axis (same
        # devices as attention TP); the DeepSeek router runs OUTSIDE the
        # shard_map (it is replicated), each shard computes its local
        # experts' contribution, one psum combines — identical collective
        # shape to the MoeConfig path. Specs come from param_specs (one
        # source of truth with how the engine placed the weights), remapped
        # to the kernel's w_gate/w_up/w_down names (mla.expert_params).
        layer_specs = param_specs(cfg)["layer"]
        weight_specs = {
            "w_gate": layer_specs["w_egate"],
            "w_up": layer_specs["w_eup"],
            "w_down": layer_specs["w_edown"],
        }

        def mla_expert_fn(ep, x, routed):
            fn = _ep_psum_shard_map(
                mesh, weight_specs,
                lambda sp, sx, srouted: moe.moe_ffn_ep_psum(
                    sp, cfg, sx, AXIS_TP, routed=srouted
                ),
                1,
            )
            return fn(ep, x, routed)

        return partial(mla.forward, expert_fn=mla_expert_fn)
    if is_gemma(cfg):
        # dense family: megatron TP rides GSPMD like llama; sliding-window
        # layers use the same paged ``window`` path as gpt-oss
        return gemma.forward
    if not is_moe(cfg):
        return llama.forward
    # the gather path materializes [T, H, I] per-token weight copies: a win
    # at decode widths, an OOM at prefill widths — pick per program off the
    # static token count (each prefill bucket compiles its own program)
    GATHER_MAX_TOKENS = 32
    if mesh is None or mesh.shape.get(AXIS_TP, 1) == 1:
        def ffn_local(p, _cfg, x):
            if x.shape[0] <= GATHER_MAX_TOKENS:
                return moe.moe_ffn_gather(p, _cfg, x)
            return moe.moe_ffn(p, _cfg, x)

        return partial(moe.forward, ffn_fn=ffn_local)

    # one source of truth for the expert layout: the same specs the engine
    # places the params with (below)
    layer_specs = param_specs(cfg)["layer"]
    ep_keys = ("w_router", "w_gate", "w_up", "w_down")
    if getattr(cfg, "redundant_experts", 0) > 0:
        # EPLB remap tables ride into the shard_map replicated (every shard
        # must compute the same logical->physical assignment)
        ep_keys = ep_keys + ("eplb_slots", "eplb_nrep")
    ep_specs = (
        {k: layer_specs.get(k, P()) for k in ep_keys}, P()
    )

    def ffn(p, _cfg, x):
        sub = {k: p[k] for k in ep_keys}
        fn = _ep_psum_shard_map(
            mesh, ep_specs[0],
            lambda sp, sx: moe.moe_ffn_ep_psum(sp, _cfg, sx, AXIS_TP),
            0,
        )
        return fn(sub, x)

    return partial(moe.forward, ffn_fn=ffn)


def lm_logits_fn(cfg):
    return family(cfg).lm_logits


def param_specs(cfg) -> dict:
    """name -> PartitionSpec for top-level and per-layer params.

    Dense family: megatron TP (parallel/mesh.param_specs_llama). MoE: the
    expert-stacked FFN weights shard on the EXPERT dim over the tp axis
    (EP rides the same devices as attention TP); GSPMD inserts the psum at
    the expert-contraction einsum. The router is tiny and replicated.
    """
    top = {
        "embed": P(None, AXIS_TP),
        "final_norm": P(None),
        "lm_head": P(None, AXIS_TP),
    }
    layer = {
        "wq": P(None, AXIS_TP),
        "wk": P(None, AXIS_TP),
        "wv": P(None, AXIS_TP),
        "wo": P(AXIS_TP, None),
        "bq": P(AXIS_TP),
        "bk": P(AXIS_TP),
        "bv": P(AXIS_TP),
    }
    if is_gptoss(cfg):
        layer.update({
            "bo": P(None),
            "sinks": P(None),
            "w_router": P(),
            "b_router": P(),
            "w_gateup": P(AXIS_TP, None, None),
            "b_gateup": P(AXIS_TP, None),
            "w_edown": P(AXIS_TP, None, None),
            "b_edown": P(AXIS_TP, None),
        })
        return {"top": top, "layer": layer, "default": P()}
    if is_mla(cfg):
        # q heads shard over TP (head-stacked w_uk/w_uv, column-parallel
        # w_uq/wq, row-parallel wo); the shared latent projections and the
        # 1-head latent KV stay replicated.
        layer.update({
            "wq": P(None, AXIS_TP),
            "w_uq": P(None, AXIS_TP),
            "w_dq": P(),
            "w_dkv": P(),
            "w_uk": P(AXIS_TP, None, None),
            "w_uv": P(AXIS_TP, None, None),
            "wo": P(AXIS_TP, None),
            "w_router": P(),
            "w_shared_gate": P(None, AXIS_TP),
            "w_shared_up": P(None, AXIS_TP),
            "w_shared_down": P(AXIS_TP, None),
        })
        # dense-layer FFN (and first_dense_layers of MoE models) keep the
        # megatron column/row specs; expert stacks live under their own
        # names (w_e*) and shard on the EXPERT dim over tp
        layer.update({
            "w_gate": P(None, AXIS_TP),
            "w_up": P(None, AXIS_TP),
            "w_down": P(AXIS_TP, None),
            "w_egate": P(AXIS_TP, None, None),
            "w_eup": P(AXIS_TP, None, None),
            "w_edown": P(AXIS_TP, None, None),
        })
    elif is_moe(cfg):
        layer.update({
            "w_router": P(None, None),
            "w_gate": P(AXIS_TP, None, None),
            "w_up": P(AXIS_TP, None, None),
            "w_down": P(AXIS_TP, None, None),
        })
    else:
        layer.update({
            "w_gate": P(None, AXIS_TP),
            "w_up": P(None, AXIS_TP),
            "w_down": P(AXIS_TP, None),
        })
    return {"top": top, "layer": layer, "default": P()}


def kv_cache_spec(cfg, tp: int = 1) -> P:
    """Paged-KV sharding for the family. Caches shard kv_heads over TP when
    they divide evenly; otherwise (MQA / MLA-latent 1-head caches, or GQA
    with fewer kv heads than TP shards) the cache replicates — the layout
    real MLA deployments use, and the same condition the engine's Pallas
    eligibility check uses."""
    from ..parallel import mesh as meshlib

    kvh = getattr(cfg, "num_kv_heads", 0)
    if kvh == 1 or (tp > 1 and kvh % tp != 0):
        return P(None, None, None, None)
    return meshlib.kv_cache_spec()


def kv_scale_spec(cfg, tp: int = 1) -> P:
    """Sharding for the int8 cache's per-block-per-kv-head scale rows
    ([num_blocks, kv_heads] f32): the kv-head dim follows the cache payload
    — sharded over TP exactly when kv_cache_spec shards kv_heads, replicated
    otherwise (MQA / MLA-latent / non-dividing GQA). One condition, two
    specs, so payload and scales can never shard apart."""
    if kv_cache_spec(cfg, tp) == P(None, None, None, None):
        return P(None, None)
    from ..parallel.mesh import AXIS_TP as _tp_axis

    return P(None, _tp_axis)
