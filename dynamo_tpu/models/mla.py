"""Multi-head Latent Attention (MLA) family: DeepSeek V2/V3/R1-style models
in functional JAX.

What the reference serves through engine adapters (recipes/deepseek-r1/,
trtllm/sglang workers), this framework owns as first-class model code, the
same way models/llama.py owns the dense family.

TPU-first design — the KV cache holds the COMPRESSED latent:

MLA projects hidden states down to a small shared latent ``c`` (kv_lora_rank
floats) plus one decoupled RoPE key ``k_pe`` (qk_rope_head_dim floats) per
token; per-head K/V are up-projections of ``c``. The serving win is the
"weight absorption" identity: folding the K up-projection into the query and
the V up-projection past the softmax turns attention into **MQA over the
latent**, so the cache per token is ``kv_lora_rank + qk_rope_head_dim``
floats instead of ``2 * heads * head_dim`` (DeepSeek V3: 576 vs 32768 — a
57x smaller cache, and decode on TPU is HBM-bandwidth-bound on exactly that
gather traffic):

    score_h(i) = q_nope_h . (W_uk_h c_i) + q_pe_h . k_pe_i
               = concat(W_uk_h^T q_nope_h, q_pe_h) . concat(c_i, k_pe_i)
    out_h      = W_uv_h (sum_i p_i c_i)

This maps onto the engine's existing attend contract with no engine changes:
``num_kv_heads = 1`` and ``head_dim = kv_lora_rank + qk_rope_head_dim``; the
cached "k" is ``concat(c, k_pe)``, the cached "v" is ``c`` zero-padded to
the same width, and the model applies ``W_uv`` to the attend output's first
``kv_lora_rank`` lanes. All paged/chunked/ring attention paths work
unchanged. Two subtleties:

- softmax scale: the engine's attention ops scale by 1/sqrt(q.shape[-1]);
  MLA wants 1/sqrt(qk_nope_head_dim + qk_rope_head_dim). The query is
  pre-multiplied by the ratio so the net scale is correct.
- TP: q heads (w_uq/w_uk/w_uv/wo) shard over the tp axis; the latent
  projections and the 1-head latent cache are replicated (an MQA cache
  cannot shard on heads — same layout real MLA deployments use).

FFN is the dense SwiGLU for ``num_experts == 0``, otherwise DeepSeek-MoE
style: ``first_dense_layers`` leading dense layers, sigmoid-or-softmax
top-k routing with ``routed_scaling_factor``, optional always-on shared
experts, reusing models/moe.py's expert kernels (gather / dense / EP-psum).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from . import moe as moelib
from .llama import (
    AttendFn,
    LlamaConfig,
    Params,
    apply_rope,
    rms_norm,
    rope_cos_sin,
)


@dataclasses.dataclass(frozen=True)
class MlaConfig(LlamaConfig):
    # attention (latent) dims
    q_lora_rank: int = 0            # 0 = full-rank q projection (V2-Lite)
    kv_lora_rank: int = 64
    qk_nope_head_dim: int = 32
    qk_rope_head_dim: int = 16
    v_head_dim: int = 32
    # MoE FFN (num_experts == 0 -> dense SwiGLU everywhere)
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_intermediate_size: int = 0
    norm_topk_prob: bool = True
    moe_scoring: str = "softmax"    # "sigmoid" = DeepSeek-V3 style
    routed_scaling_factor: float = 1.0
    num_shared_experts: int = 0
    first_dense_layers: int = 0
    # group-limited routing (V3 noaux_tc): experts partitioned into n_group
    # groups; selection first keeps the topk_group best groups (scored by
    # their top-2 expert sum), then top-k within the survivors
    n_group: int = 1
    topk_group: int = 1
    # checkpoint rope layout: True = interleaved pairs (HF rope_interleave,
    # the DeepSeek default) — the loader de-interleaves to rotate-half
    rope_interleave: bool = True

    def __post_init__(self):
        # the engine reads num_kv_heads/head_dim as the KV-cache layout;
        # for MLA that layout IS the latent — pin it so presets can't drift
        object.__setattr__(self, "num_kv_heads", 1)
        object.__setattr__(
            self, "head_dim", self.kv_lora_rank + self.qk_rope_head_dim
        )

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    @property
    def q_size(self) -> int:  # true q projection width (lora sizing etc.)
        return self.num_heads * self.qk_head_dim

    @classmethod
    def tiny_mla(cls, **kw) -> "MlaConfig":
        defaults = dict(
            vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
            kv_lora_rank=64, qk_nope_head_dim=32, qk_rope_head_dim=16,
            v_head_dim=32, intermediate_size=256, dtype=jnp.float32,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def tiny_mla_moe(cls, **kw) -> "MlaConfig":
        defaults = dict(
            vocab_size=512, hidden_size=128, num_layers=3, num_heads=4,
            kv_lora_rank=64, qk_nope_head_dim=32, qk_rope_head_dim=16,
            v_head_dim=32, intermediate_size=256, q_lora_rank=96,
            num_experts=4, num_experts_per_tok=2, moe_intermediate_size=64,
            moe_scoring="sigmoid", routed_scaling_factor=2.0,
            num_shared_experts=1, first_dense_layers=1, dtype=jnp.float32,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def deepseek_v2_lite(cls, vocab_size: int = 102400) -> "MlaConfig":
        """DeepSeek-V2-Lite (15.7B total / 2.4B active)."""
        return cls(
            vocab_size=vocab_size, hidden_size=2048, num_layers=27,
            num_heads=16, q_lora_rank=0, kv_lora_rank=512,
            qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
            intermediate_size=10944, num_experts=64, num_experts_per_tok=6,
            moe_intermediate_size=1408, num_shared_experts=2,
            norm_topk_prob=False,  # V2-Lite uses unnormalized top-k weights
            first_dense_layers=1, rope_theta=10000.0, tie_embeddings=False,
        )

    @classmethod
    def deepseek_v3(cls, vocab_size: int = 129280) -> "MlaConfig":
        """DeepSeek-V3 / R1 (671B total / 37B active). head_dim = 576 is not
        128-aligned, so attention runs the pure-JAX paged path (the Pallas
        eligibility guard falls back automatically)."""
        return cls(
            vocab_size=vocab_size, hidden_size=7168, num_layers=61,
            num_heads=128, q_lora_rank=1536, kv_lora_rank=512,
            qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
            intermediate_size=18432, num_experts=256, num_experts_per_tok=8,
            moe_intermediate_size=2048, moe_scoring="sigmoid",
            routed_scaling_factor=2.5, norm_topk_prob=True,
            num_shared_experts=1, first_dense_layers=3,
            n_group=8, topk_group=4,
            rope_theta=10000.0, tie_embeddings=False,
        )


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def _is_moe_layer(cfg: MlaConfig, layer_idx: int) -> bool:
    return cfg.num_experts > 0 and layer_idx >= cfg.first_dense_layers


def init_layer_params(rng: jax.Array, cfg: MlaConfig, layer_idx: int) -> Params:
    k = jax.random.split(rng, 16)
    h = cfg.hidden_size
    nh, rank = cfg.num_heads, cfg.kv_lora_rank
    nope, rope, vd = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
    scale = 1.0 / math.sqrt(h)
    p: Params = {
        "attn_norm": jnp.ones((h,), cfg.dtype),
        "mlp_norm": jnp.ones((h,), cfg.dtype),
        # KV latent: one down-projection emitting [c (rank) | k_pe (rope)]
        "w_dkv": (jax.random.normal(k[0], (h, rank + rope)) * scale).astype(cfg.dtype),
        "kv_norm": jnp.ones((rank,), cfg.dtype),
        # per-head up-projections, head-stacked so TP shards the head dim
        "w_uk": (
            jax.random.normal(k[1], (nh, nope, rank)) / math.sqrt(rank)
        ).astype(cfg.dtype),
        "w_uv": (
            jax.random.normal(k[2], (nh, rank, vd)) / math.sqrt(rank)
        ).astype(cfg.dtype),
        "wo": (jax.random.normal(k[3], (nh * vd, h)) * scale).astype(cfg.dtype),
    }
    if cfg.q_lora_rank > 0:
        p["w_dq"] = (
            jax.random.normal(k[4], (h, cfg.q_lora_rank)) * scale
        ).astype(cfg.dtype)
        p["q_norm"] = jnp.ones((cfg.q_lora_rank,), cfg.dtype)
        p["w_uq"] = (
            jax.random.normal(k[5], (cfg.q_lora_rank, nh * (nope + rope)))
            / math.sqrt(cfg.q_lora_rank)
        ).astype(cfg.dtype)
    else:
        p["wq"] = (
            jax.random.normal(k[5], (h, nh * (nope + rope))) * scale
        ).astype(cfg.dtype)
    if _is_moe_layer(cfg, layer_idx):
        E, inter = cfg.num_experts, cfg.moe_intermediate_size
        iscale = 1.0 / math.sqrt(inter)
        p["w_router"] = (jax.random.normal(k[6], (h, E)) * scale).astype(cfg.dtype)
        if cfg.moe_scoring == "sigmoid":
            # aux-free load-balancing bias (updated out-of-band in training;
            # inference just reads it — HF e_score_correction_bias)
            p["router_bias"] = jnp.zeros((E,), jnp.float32)
        # expert stacks use their own names (w_e*) so the TP partition spec
        # can shard the expert dim without colliding with the 2-D dense-layer
        # w_gate/w_up/w_down sharing the per-layer spec table
        p["w_egate"] = (jax.random.normal(k[7], (E, h, inter)) * scale).astype(cfg.dtype)
        p["w_eup"] = (jax.random.normal(k[8], (E, h, inter)) * scale).astype(cfg.dtype)
        p["w_edown"] = (jax.random.normal(k[9], (E, inter, h)) * iscale).astype(cfg.dtype)
        if cfg.num_shared_experts > 0:
            si = inter * cfg.num_shared_experts
            p["w_shared_gate"] = (
                jax.random.normal(k[10], (h, si)) * scale
            ).astype(cfg.dtype)
            p["w_shared_up"] = (
                jax.random.normal(k[11], (h, si)) * scale
            ).astype(cfg.dtype)
            p["w_shared_down"] = (
                jax.random.normal(k[12], (si, h)) / math.sqrt(si)
            ).astype(cfg.dtype)
    else:
        inter = cfg.intermediate_size
        iscale = 1.0 / math.sqrt(inter)
        p["w_gate"] = (jax.random.normal(k[7], (h, inter)) * scale).astype(cfg.dtype)
        p["w_up"] = (jax.random.normal(k[8], (h, inter)) * scale).astype(cfg.dtype)
        p["w_down"] = (jax.random.normal(k[9], (inter, h)) * iscale).astype(cfg.dtype)
    return p


def init_params(rng: jax.Array, cfg: MlaConfig) -> Params:
    keys = jax.random.split(rng, cfg.num_layers + 2)
    params: Params = {
        "embed": (
            jax.random.normal(keys[0], (cfg.vocab_size, cfg.hidden_size)) * 0.02
        ).astype(cfg.dtype),
        "final_norm": jnp.ones((cfg.hidden_size,), cfg.dtype),
        "layers": [
            init_layer_params(keys[i + 2], cfg, i) for i in range(cfg.num_layers)
        ],
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = (
            jax.random.normal(keys[1], (cfg.hidden_size, cfg.vocab_size)) * 0.02
        ).astype(cfg.dtype)
    return params


# ---------------------------------------------------------------------------
# routing (DeepSeek flavors) + FFN
# ---------------------------------------------------------------------------


def route(p: Params, cfg: MlaConfig, x: jax.Array):
    """Top-k router matching HF DeepseekV3TopkRouter semantics: sigmoid (V3)
    or softmax (V2) scores; SELECTION uses scores + the aux-free balancing
    bias (e_score_correction_bias) and optional group-limited top-k, while
    the combine WEIGHTS are the unbiased scores gathered at the selected
    indices, normalized then scaled. x [T, H] -> (weights [T,K] f32,
    idx [T,K])."""
    logits = (x.astype(jnp.float32) @ p["w_router"].astype(jnp.float32))
    if cfg.moe_scoring == "sigmoid":
        scores = jax.nn.sigmoid(logits)
    else:
        scores = jax.nn.softmax(logits, axis=-1)
    sel = scores
    bias = p.get("router_bias")
    if bias is not None:
        sel = sel + bias.astype(jnp.float32)
    if cfg.n_group > 1:
        T = sel.shape[0]
        G, Eg = cfg.n_group, cfg.num_experts // cfg.n_group
        group_scores = jax.lax.top_k(sel.reshape(T, G, Eg), 2)[0].sum(-1)
        _, gidx = jax.lax.top_k(group_scores, cfg.topk_group)        # [T, tg]
        gmask = jax.nn.one_hot(gidx, G, dtype=jnp.float32).sum(1)    # [T, G]
        emask = jnp.repeat(gmask, Eg, axis=-1)                       # [T, E]
        sel = jnp.where(emask > 0, sel, 0.0)  # HF masked_fill(~mask, 0.0)
    _, topi = jax.lax.top_k(sel, cfg.num_experts_per_tok)
    topw = jnp.take_along_axis(scores, topi, axis=-1)
    if cfg.norm_topk_prob:
        topw = topw / (topw.sum(-1, keepdims=True) + 1e-20)
    return topw * cfg.routed_scaling_factor, topi


def expert_params(p: Params) -> Params:
    """Expert stacks under the names moe.py's kernels expect."""
    return {"w_gate": p["w_egate"], "w_up": p["w_eup"], "w_down": p["w_edown"]}


def _moe_ffn(
    p: Params, cfg: MlaConfig, x: jax.Array, expert_fn=None
) -> jax.Array:
    """Routed experts (moe.py gather kernel fed by this module's DeepSeek
    router, or a mesh-aware ``expert_fn`` injected by the registry for EP)
    + the always-on shared-expert SwiGLU."""
    routed = route(p, cfg, x)
    if expert_fn is not None:
        y = expert_fn(expert_params(p), x, routed)
    else:
        y = moelib.moe_ffn_gather(expert_params(p), cfg, x, routed=routed)
    if cfg.num_shared_experts > 0:
        sg = jax.nn.silu((x @ p["w_shared_gate"]).astype(jnp.float32)).astype(x.dtype)
        y = y + (sg * (x @ p["w_shared_up"])) @ p["w_shared_down"]
    return y


def _dense_ffn(p: Params, cfg: MlaConfig, x: jax.Array) -> jax.Array:
    gate = jax.nn.silu((x @ p["w_gate"]).astype(jnp.float32)).astype(x.dtype)
    return (gate * (x @ p["w_up"])) @ p["w_down"]


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def layer_forward(
    p: Params,
    cfg: MlaConfig,
    x: jax.Array,                 # [..., S, hidden]
    cos: jax.Array,               # [..., S, 1, rope/2]
    sin: jax.Array,
    attend: AttendFn,
    layer_idx: int,
    expert_fn=None,
) -> jax.Array:
    nh, rank = cfg.num_heads, cfg.kv_lora_rank
    nope, rope = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    lead = x.shape[:-1]           # [..., S]

    h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
    # -- queries
    if cfg.q_lora_rank > 0:
        q = rms_norm(h @ p["w_dq"], p["q_norm"], cfg.rms_norm_eps) @ p["w_uq"]
    else:
        q = h @ p["wq"]
    q = q.reshape(*lead, nh, nope + rope)
    q_nope, q_pe = q[..., :nope], q[..., nope:]
    q_pe = apply_rope(q_pe, cos, sin)
    # -- latent KV
    ckv = h @ p["w_dkv"]                                   # [..., rank+rope]
    c = rms_norm(ckv[..., :rank], p["kv_norm"], cfg.rms_norm_eps)
    k_pe = apply_rope(ckv[..., None, rank:], cos, sin)     # [..., 1, rope]
    # -- absorb W_uk into q: MQA over the latent
    q_abs = jnp.einsum("...hn,hnr->...hr", q_nope, p["w_uk"])
    q_prime = jnp.concatenate([q_abs, q_pe], axis=-1)      # [..., nh, rank+rope]
    # attend ops scale by 1/sqrt(rank+rope); MLA wants 1/sqrt(nope+rope)
    q_prime = q_prime * math.sqrt((rank + rope) / (nope + rope))
    k_prime = jnp.concatenate([c[..., None, :], k_pe], axis=-1)
    cl = c[..., None, :]                                   # [..., 1, rank]
    v_prime = jnp.pad(
        cl, [(0, 0)] * (cl.ndim - 1) + [(0, rope)]
    )
    o = attend(
        q_prime.astype(cfg.dtype), k_prime.astype(cfg.dtype),
        v_prime.astype(cfg.dtype), layer_idx,
    )                                                      # [..., nh, rank+rope]
    # -- un-absorb W_uv past the softmax
    attn = jnp.einsum("...hr,hrv->...hv", o[..., :rank], p["w_uv"])
    x = x + attn.reshape(*lead, nh * cfg.v_head_dim) @ p["wo"]
    # -- FFN
    h = rms_norm(x, p["mlp_norm"], cfg.rms_norm_eps)
    if _is_moe_layer(cfg, layer_idx):
        # routing indexes per token: flatten leading dims to [T, H]
        flat = h.reshape(-1, h.shape[-1])
        return x + _moe_ffn(p, cfg, flat, expert_fn=expert_fn).reshape(h.shape)
    return x + _dense_ffn(p, cfg, h)


def forward(
    params: Params,
    cfg: MlaConfig,
    token_ids: jax.Array,        # [S] int32
    positions: jax.Array,        # [S] int32
    attend: AttendFn,
    lora: Optional[Callable] = None,
    inputs_embeds: Optional[jax.Array] = None,
    expert_fn=None,
) -> jax.Array:
    if lora is not None:
        raise NotImplementedError("LoRA is not supported for the MLA family")
    x = params["embed"][token_ids] if inputs_embeds is None else inputs_embeds
    cos, sin = rope_cos_sin(positions, cfg.qk_rope_head_dim, cfg.rope_theta)
    cos, sin = cos[..., None, :], sin[..., None, :]
    for i, layer in enumerate(params["layers"]):
        x = layer_forward(layer, cfg, x, cos, sin, attend, i, expert_fn=expert_fn)
    return rms_norm(x, params["final_norm"], cfg.rms_norm_eps)


def lm_logits(params: Params, cfg: MlaConfig, hidden: jax.Array) -> jax.Array:
    if cfg.tie_embeddings:
        return (hidden @ params["embed"].T).astype(jnp.float32)
    return (hidden @ params["lm_head"]).astype(jnp.float32)


# ---------------------------------------------------------------------------
# reference (uncompressed) attention — test oracle
# ---------------------------------------------------------------------------


def reference_attention(
    p: Params, cfg: MlaConfig, h_normed: jax.Array, positions: jax.Array
) -> jax.Array:
    """Causal MLA attention with K/V fully materialized per head (no
    absorption, no latent cache) — the semantics the absorbed/MQA serving
    path must reproduce. Returns the post-``wo`` projection delta [S, H]."""
    nh, rank = cfg.num_heads, cfg.kv_lora_rank
    nope, rope = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    S = h_normed.shape[0]
    cos, sin = rope_cos_sin(positions, rope, cfg.rope_theta)
    cos, sin = cos[..., None, :], sin[..., None, :]
    if cfg.q_lora_rank > 0:
        q = rms_norm(h_normed @ p["w_dq"], p["q_norm"], cfg.rms_norm_eps) @ p["w_uq"]
    else:
        q = h_normed @ p["wq"]
    q = q.reshape(S, nh, nope + rope)
    q_nope, q_pe = q[..., :nope], q[..., nope:]
    q_pe = apply_rope(q_pe, cos, sin)
    ckv = h_normed @ p["w_dkv"]
    c = rms_norm(ckv[..., :rank], p["kv_norm"], cfg.rms_norm_eps)
    k_pe = apply_rope(ckv[..., None, rank:], cos, sin)[:, 0]   # [S, rope]
    # materialize per-head K (nope part) and V from the latent
    k_nope = jnp.einsum("sr,hnr->shn", c, p["w_uk"])           # [S, nh, nope]
    v = jnp.einsum("sr,hrv->shv", c, p["w_uv"])                # [S, nh, vd]
    k = jnp.concatenate(
        [k_nope, jnp.broadcast_to(k_pe[:, None, :], (S, nh, rope))], axis=-1
    )
    qf = jnp.concatenate([q_nope, q_pe], axis=-1).astype(jnp.float32)
    s = jnp.einsum("shd,thd->hst", qf, k.astype(jnp.float32))
    s = s / math.sqrt(nope + rope)
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask[None], s, -1e30)
    pattn = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("hst,thv->shv", pattn, v.astype(jnp.float32))
    return (
        o.astype(cfg.dtype).reshape(S, nh * cfg.v_head_dim) @ p["wo"]
    )
