"""EPLB — expert-parallelism load balancing via redundant experts.

Reference: SGLang's EPLB (docs/backends/sglang/expert-distribution-eplb.md
— redundant experts, hierarchical/global rebalancing from periodically
collected token counts); TRT-LLM's moe_cluster/expert parallel knobs
(components/src/dynamo/trtllm/engine.py:120-122). The reference deploys
engines that own this; here the engine is native, so EPLB is built in.

TPU-native shape (models/moe.py holds the hot-path pieces):

- the expert stacks carry R extra PHYSICAL slots ([E+R, ...], STATIC — no
  recompiles, the expert dim keeps sharding over the tp/ep axis);
- per-layer remap tables (``eplb_slots`` [E, R+1], ``eplb_nrep`` [E]) live
  in the params pytree, so a rebalance is an in-place table + weight-slot
  update, exactly like LoRA hot-load;
- tokens spread round-robin across a logical expert's replicas inside the
  EP kernels (``moe.eplb_remap``), so a hot expert's load divides across
  the shards that hold its replicas.

This module is the COLD path: measuring loads, planning the replica set,
and applying a plan to live params. ``TpuEngine.eplb_rebalance`` drives
it at runtime.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import moe


@dataclasses.dataclass
class EplbPlan:
    """A replica layout: phys_src[s] = logical expert served by physical
    slot s (identity for the E primaries); slots/nrep are the routing
    tables (moe.eplb_remap)."""

    phys_src: np.ndarray   # [E+R] int32
    slots: np.ndarray      # [E, R+1] int32
    nrep: np.ndarray       # [E] int32

    def max_shard_load(self, counts: np.ndarray, ep: int) -> float:
        """Expected max per-shard token load under this plan (the quantity
        EPLB minimizes): each expert's count divides evenly across its
        replicas; a slot's load lands on the shard that owns it."""
        E_phys = len(self.phys_src)
        per = E_phys // ep
        shard = np.zeros(ep)
        for s, e in enumerate(self.phys_src):
            shard[s // per] += counts[e] / self.nrep[e]
        return float(shard.max())


def plan(counts: np.ndarray, E: int, R: int, ep: int = 1) -> EplbPlan:
    """Greedy water-filling: repeatedly grant a replica to the expert with
    the highest per-replica load, then place each replica in a redundant
    slot preferring shards that (a) don't already serve that expert and
    (b) carry the least planned load — the same objective as the
    reference's rebalancing (minimize the hottest rank)."""
    counts = np.asarray(counts, np.float64).clip(min=0)
    E_phys = E + R
    if ep > 0 and E_phys % ep:
        raise ValueError(f"E+R={E_phys} must divide over ep={ep} shards")
    reps = np.ones(E, np.int64)
    for _ in range(R):
        e = int(np.argmax(counts / reps))
        reps[e] += 1

    per = E_phys // max(ep, 1)
    shard_load = np.zeros(max(ep, 1))
    shard_of = lambda s: s // per  # noqa: E731
    # primaries' share lands first
    for e in range(E):
        shard_load[shard_of(e)] += counts[e] / reps[e]

    phys_src = np.concatenate(
        [np.arange(E, dtype=np.int32), np.zeros(R, np.int32)]
    )
    slots, nrep = _identity_tables(E, R)
    free = list(range(E, E_phys))
    # place the hottest experts' replicas first
    order = sorted(range(E), key=lambda e: -counts[e])
    for e in order:
        for _ in range(int(reps[e]) - 1):
            taken = {shard_of(s) for s in slots[e][: nrep[e]]}
            # prefer a fresh shard with the least planned load
            best = min(
                free,
                key=lambda s: (shard_of(s) in taken,
                               shard_load[shard_of(s)]),
            )
            free.remove(best)
            phys_src[best] = e
            slots[e][nrep[e]] = best
            nrep[e] += 1
            shard_load[shard_of(best)] += counts[e] / reps[e]
    # pad unused table columns with the primary (any pick stays valid)
    for e in range(E):
        slots[e][nrep[e]:] = slots[e][0]
    return EplbPlan(
        phys_src=phys_src.astype(np.int32),
        slots=slots.astype(np.int32),
        nrep=nrep.astype(np.int32),
    )


def _identity_tables(E: int, R: int) -> Tuple[np.ndarray, np.ndarray]:
    slots = np.tile(np.arange(E, dtype=np.int64)[:, None], (1, R + 1))
    return slots, np.ones(E, np.int64)


def apply_plan(layer: Dict, p: EplbPlan) -> Dict:
    """New layer params under ``p``: replica slots gather their logical
    expert's weights FROM THE PRIMARIES (slots 0..E-1 always hold the
    logical weights, so plans compose without drift), tables swap in. Pure
    function of the old layer — callers assign the result; shardings are
    preserved (gather along the sharded expert dim keeps the spec;
    replicated tables stay replicated)."""
    from jax.sharding import NamedSharding

    out = dict(layer)
    src = jnp.asarray(p.phys_src)
    for k in ("w_gate", "w_up", "w_down"):
        gathered = layer[k][src]
        shd = getattr(layer[k], "sharding", None)
        if isinstance(shd, NamedSharding):
            # an indexed gather drops the expert-dim sharding (the output
            # comes back replicated): re-place on the ORIGINAL spec, or one
            # rebalance silently multiplies expert HBM use by the EP degree.
            # Uncommitted (mesh-less) arrays stay uncommitted — an explicit
            # put would pin them to one device and break later mesh use.
            gathered = jax.device_put(gathered, shd)
        out[k] = gathered
    out["eplb_slots"] = jnp.asarray(p.slots)
    out["eplb_nrep"] = jnp.asarray(p.nrep)
    return out


def probe_expert_load(params, cfg: moe.MoeConfig, token_ids, positions):
    """[num_layers, E] tokens-per-logical-expert for one batch: a dense
    causal forward with the router observed at every MoE layer. The
    reference collects the same statistic from its engines periodically;
    this is the jittable probe the engine's measure path uses (offline —
    never on the serving hot path)."""
    from ..ops import attention as att

    counts: List[jax.Array] = []

    def probing_ffn(p, _cfg, x):
        topw, topi = moe.route(p, _cfg, x)
        counts.append(moe.expert_load(_cfg, topi))
        return moe.moe_ffn(p, _cfg, x)

    def attend(q, k_new, v_new, layer_idx, **extra):
        return att.causal_attention(q, k_new, v_new, **extra)

    moe.forward(params, cfg, token_ids, positions, attend,
                ffn_fn=probing_ffn)
    return jnp.stack(counts)
