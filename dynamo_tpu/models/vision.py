"""Vision encoder for multimodal serving: ViT + projector, functional JAX.

The reference serves vision-language models through its engines' multimodal
paths (components/src/dynamo/vllm/main.py:887-1119 multimodal/encode inits,
sglang/main.py:539-706); this framework owns the model, so the encoder is
framework code: a standard ViT (patchify -> transformer -> per-patch
features) plus a 2-layer MLP projector into the language model's hidden
space — the LLaVA-style recipe. TPU notes: patchify is one reshape+matmul
(MXU-friendly, no conv needed for square patches), everything bfloat16,
static image size (resized host-side in the preprocessor).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp

from .llama import rms_norm

Params = Dict[str, Any]

# the placeholder token id marking image spans in prompts — one shared
# sentinel well above any real vocab (engine config and model cards both
# default to it)
IMAGE_TOKEN_ID = 0x7F_FF_F0


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 256          # encoder width
    num_layers: int = 6
    num_heads: int = 4
    intermediate_size: int = 688
    out_hidden_size: int = 256      # language model hidden (projector out)
    rms_norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return 3 * self.patch_size * self.patch_size

    @classmethod
    def tiny(cls, out_hidden_size: int = 256) -> "VisionConfig":
        return cls(
            image_size=28, patch_size=14, hidden_size=64, num_layers=2,
            num_heads=2, intermediate_size=96, out_hidden_size=out_hidden_size,
        )


def init_params(rng: jax.Array, cfg: VisionConfig) -> Params:
    ks = jax.random.split(rng, cfg.num_layers + 4)
    h, inter = cfg.hidden_size, cfg.intermediate_size
    s = 1.0 / math.sqrt(h)

    def layer(k):
        kk = jax.random.split(k, 4)
        return {
            "attn_norm": jnp.ones((h,), cfg.dtype),
            "mlp_norm": jnp.ones((h,), cfg.dtype),
            "wqkv": (jax.random.normal(kk[0], (h, 3 * h)) * s).astype(cfg.dtype),
            "wo": (jax.random.normal(kk[1], (h, h)) * s).astype(cfg.dtype),
            "w_up": (jax.random.normal(kk[2], (h, inter)) * s).astype(cfg.dtype),
            "w_down": (
                jax.random.normal(kk[3], (inter, h)) / math.sqrt(inter)
            ).astype(cfg.dtype),
        }

    return {
        "patch_embed": (
            jax.random.normal(ks[0], (cfg.patch_dim, h)) / math.sqrt(cfg.patch_dim)
        ).astype(cfg.dtype),
        "pos_embed": (
            jax.random.normal(ks[1], (cfg.num_patches, h)) * 0.02
        ).astype(cfg.dtype),
        "final_norm": jnp.ones((h,), cfg.dtype),
        "proj_up": (
            jax.random.normal(ks[2], (h, cfg.out_hidden_size)) * s
        ).astype(cfg.dtype),
        "proj_down": (
            jax.random.normal(ks[3], (cfg.out_hidden_size, cfg.out_hidden_size))
            / math.sqrt(cfg.out_hidden_size)
        ).astype(cfg.dtype),
        "layers": [layer(ks[4 + i]) for i in range(cfg.num_layers)],
    }


def patchify(cfg: VisionConfig, image: jax.Array) -> jax.Array:
    """[H, W, 3] float in [0,1] -> [num_patches, patch_dim]."""
    p = cfg.patch_size
    n = cfg.image_size // p
    x = image.reshape(n, p, n, p, 3)
    return x.transpose(0, 2, 1, 3, 4).reshape(n * n, 3 * p * p)


def _attn(lp: Params, cfg: VisionConfig, x: jax.Array) -> jax.Array:
    S = x.shape[0]
    hd = cfg.hidden_size // cfg.num_heads
    qkv = (x @ lp["wqkv"]).reshape(S, 3, cfg.num_heads, hd)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    scores = jnp.einsum("shd,thd->hst", q, k).astype(jnp.float32) / math.sqrt(hd)
    w = jax.nn.softmax(scores, axis=-1)  # bidirectional: no causal mask
    out = jnp.einsum("hst,thd->shd", w, v.astype(jnp.float32))
    return (out.reshape(S, cfg.hidden_size).astype(x.dtype)) @ lp["wo"]


def encode(params: Params, cfg: VisionConfig, image: jax.Array) -> jax.Array:
    """[image_size, image_size, 3] -> projected patch features
    [num_patches, out_hidden_size] (the language model's soft tokens)."""
    x = patchify(cfg, image).astype(cfg.dtype) @ params["patch_embed"]
    x = x + params["pos_embed"]
    for lp in params["layers"]:
        x = x + _attn(lp, cfg, rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps))
        hmid = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + jax.nn.gelu((hmid @ lp["w_up"]).astype(jnp.float32)).astype(
            x.dtype
        ) @ lp["w_down"]
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    h = jax.nn.gelu((x @ params["proj_up"]).astype(jnp.float32)).astype(cfg.dtype)
    return h @ params["proj_down"]
