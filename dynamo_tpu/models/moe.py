"""Sparse MoE model family (Qwen3-MoE / Mixtral style) with TPU-native
expert parallelism.

The reference only passes EP knobs through to engine-internal all-to-all
(moe_expert_parallel_size etc., components/src/dynamo/trtllm/engine.py:
120-122; SGLang EPLB docs) — this framework owns the model, so EP is
implemented directly over the mesh:

* ``moe_ffn``            — dense reference (single device / replicated).
* ``moe_ffn_ep_psum``    — experts sharded over an axis, tokens REPLICATED
  on it (the engine's decode layout: EP rides the tp axis); each shard
  computes its local experts' contribution, one psum combines. Same
  collective cost as a TP row-parallel matmul.
* ``moe_ffn_ep_a2a``     — tokens SHARDED over the ep axis (GShard/Switch
  style): capacity-bounded dispatch, all-to-all to the expert owners over
  ICI, expert compute, all-to-all back, weighted combine. This is the
  scale path for large-batch prefill.

Routing is softmax-then-top-k with optional top-k renormalization
(Qwen3-MoE convention). Expert-load counts are returned for an
EPLB-style rebalancing feed (reference: docs/backends/sglang/
expert-distribution-eplb.md — pattern only).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple

import jax
import jax.numpy as jnp

from . import llama
from .llama import (
    AttendFn,
    Params,
    apply_rope,
    rms_norm,
    rope_cos_sin,
)


@dataclasses.dataclass(frozen=True)
class MoeConfig(llama.LlamaConfig):
    num_experts: int = 8
    num_experts_per_tok: int = 2
    moe_intermediate_size: int = 128
    norm_topk_prob: bool = True
    # a2a dispatch capacity per (source shard, expert) = ceil(T*K/E * factor)
    capacity_factor: float = 2.0
    # EPLB (expert parallelism load balancing; reference: SGLang EPLB,
    # docs/backends/sglang/expert-distribution-eplb.md — redundant experts
    # rebalanced from observed load). R extra PHYSICAL expert slots hold
    # replicas of hot experts: the expert stacks are [E+R, ...] (static, so
    # zero recompiles), per-layer remap tables (eplb_slots/eplb_nrep, part
    # of the params pytree like LoRA tables) spread each logical expert's
    # tokens across its replicas, and TpuEngine.eplb_rebalance() re-plans
    # the replica set from measured counts at runtime. 0 disables.
    redundant_experts: int = 0

    @property
    def num_physical_experts(self) -> int:
        return self.num_experts + self.redundant_experts

    @classmethod
    def tiny_moe(cls, **kw) -> "MoeConfig":
        defaults = dict(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, head_dim=16, intermediate_size=128,
            num_experts=4, num_experts_per_tok=2, moe_intermediate_size=64,
            dtype=jnp.float32,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def qwen3_30b_a3b(cls, vocab_size: int = 151936) -> "MoeConfig":
        return cls(
            vocab_size=vocab_size, hidden_size=2048, num_layers=48,
            num_heads=32, num_kv_heads=4, head_dim=128,
            intermediate_size=6144,  # unused (all layers sparse)
            num_experts=128, num_experts_per_tok=8,
            moe_intermediate_size=768, rope_theta=1000000.0, qk_norm=True,
            tie_embeddings=False,
        )


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_layer_params(rng: jax.Array, cfg: MoeConfig) -> Params:
    k = jax.random.split(rng, 9)
    h, qd, kvd = cfg.hidden_size, cfg.q_size, cfg.kv_size
    E, inter = cfg.num_experts, cfg.moe_intermediate_size
    scale = 1.0 / math.sqrt(h)
    iscale = 1.0 / math.sqrt(inter)
    p: Params = {
        "attn_norm": jnp.ones((h,), cfg.dtype),
        "mlp_norm": jnp.ones((h,), cfg.dtype),
        "wq": (jax.random.normal(k[0], (h, qd)) * scale).astype(cfg.dtype),
        "wk": (jax.random.normal(k[1], (h, kvd)) * scale).astype(cfg.dtype),
        "wv": (jax.random.normal(k[2], (h, kvd)) * scale).astype(cfg.dtype),
        "wo": (jax.random.normal(k[3], (qd, h)) * scale).astype(cfg.dtype),
        "w_router": (jax.random.normal(k[4], (h, E)) * scale).astype(cfg.dtype),
        # expert-stacked FFN weights: [E, ...] so the expert dim shards
        "w_gate": (jax.random.normal(k[5], (E, h, inter)) * scale).astype(cfg.dtype),
        "w_up": (jax.random.normal(k[6], (E, h, inter)) * scale).astype(cfg.dtype),
        "w_down": (jax.random.normal(k[7], (E, inter, h)) * iscale).astype(cfg.dtype),
    }
    if cfg.qk_norm:
        p["q_norm"] = jnp.ones((cfg.head_dim,), cfg.dtype)
        p["k_norm"] = jnp.ones((cfg.head_dim,), cfg.dtype)
    if cfg.redundant_experts > 0:
        ensure_eplb_layer(p, cfg)
    return p


def init_params(rng: jax.Array, cfg: MoeConfig) -> Params:
    keys = jax.random.split(rng, cfg.num_layers + 2)
    params: Params = {
        "embed": (
            jax.random.normal(keys[0], (cfg.vocab_size, cfg.hidden_size)) * 0.02
        ).astype(cfg.dtype),
        "final_norm": jnp.ones((cfg.hidden_size,), cfg.dtype),
        "layers": [init_layer_params(keys[i + 2], cfg) for i in range(cfg.num_layers)],
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = (
            jax.random.normal(keys[1], (cfg.hidden_size, cfg.vocab_size)) * 0.02
        ).astype(cfg.dtype)
    return params


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------


def route(
    p: Params, cfg: MoeConfig, x: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """softmax-then-top-k router. x [T, H] -> (weights [T, K] f32, idx [T, K])."""
    logits = (x @ p["w_router"]).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    topw, topi = jax.lax.top_k(probs, cfg.num_experts_per_tok)
    if cfg.norm_topk_prob:
        topw = topw / jnp.maximum(topw.sum(-1, keepdims=True), 1e-9)
    return topw, topi


def expert_load(cfg: MoeConfig, topi: jax.Array) -> jax.Array:
    """Tokens-per-expert counts [E] — the EPLB rebalancing feed."""
    oh = jax.nn.one_hot(topi.reshape(-1), cfg.num_experts, dtype=jnp.int32)
    return oh.sum(0)


# ---------------------------------------------------------------------------
# EPLB: redundant physical experts + replica remap tables
# ---------------------------------------------------------------------------


def default_eplb_tables(cfg: MoeConfig):
    """Identity-ish plan: redundant slot E+i replicates logical expert
    i % E (round-robin, so R > E just stacks more replicas per expert)
    until a measured rebalance replaces it. Returns numpy
    (slots [E, R+1], nrep [E], src [R]) — slots padded by repeating the
    primary so any index mod nrep lands on a valid replica; src[i] is the
    logical expert slot E+i serves (the weight-expansion gather)."""
    import numpy as np

    E, R = cfg.num_experts, cfg.redundant_experts
    slots = np.tile(np.arange(E, dtype=np.int32)[:, None], (1, R + 1))
    nrep = np.ones(E, np.int32)
    src = np.arange(R, dtype=np.int32) % E
    for i in range(R):
        e = src[i]
        slots[e, nrep[e]] = E + i
        nrep[e] += 1
    return slots, nrep, src


def ensure_eplb_layer(p: Params, cfg: MoeConfig) -> Params:
    """Expand a layer's logical [E, ...] expert stacks to physical
    [E+R, ...] and seed the remap tables. Idempotent — checkpoint loaders
    produce logical stacks; init and engine admission call this."""
    R = cfg.redundant_experts
    if R <= 0 or "w_gate" not in p:
        return p
    if p["w_gate"].shape[0] == cfg.num_physical_experts:
        return p
    slots, nrep, src = default_eplb_tables(cfg)
    for k in ("w_gate", "w_up", "w_down"):
        # default replicas mirror experts src[i] = i % E (the tables above)
        p[k] = jnp.concatenate([p[k], p[k][src]], axis=0)
    p["eplb_slots"] = jnp.asarray(slots)
    p["eplb_nrep"] = jnp.asarray(nrep)
    return p


def eplb_remap(p: Params, topi: jax.Array) -> jax.Array:
    """Map logical expert ids [T, K] to physical slots, spreading each
    expert's tokens round-robin across its replicas (the token index is the
    salt — deterministic, batch-independent per position)."""
    if "eplb_slots" not in p:
        return topi
    T, K = topi.shape
    nrep = p["eplb_nrep"][topi]                          # [T, K]
    pick = (jnp.arange(T, dtype=jnp.int32)[:, None] + jnp.arange(K)) % nrep
    return jnp.take_along_axis(
        p["eplb_slots"][topi], pick[..., None], axis=-1
    )[..., 0]


def primary_experts(p: Params, cfg: MoeConfig) -> Params:
    """View of the layer with only the logical expert slots (the dense and
    gather paths index logically and must not touch replicas)."""
    if "eplb_slots" not in p:
        return p
    out = dict(p)
    for k in ("w_gate", "w_up", "w_down"):
        out[k] = p[k][: cfg.num_experts]
    return out


def _expert_mlp(w_gate, w_up, w_down, x, out_dtype):
    """x [E, B, H] through per-expert SwiGLU -> [E, B, H]."""
    gate = jnp.einsum("ebh,ehi->ebi", x, w_gate)
    up = jnp.einsum("ebh,ehi->ebi", x, w_up)
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(out_dtype) * up
    return jnp.einsum("ebi,eih->ebh", act, w_down)


# ---------------------------------------------------------------------------
# dense reference
# ---------------------------------------------------------------------------


def moe_ffn(p: Params, cfg: MoeConfig, x: jax.Array) -> jax.Array:
    """Dense reference: every expert computed for every token, masked
    combine. Exact (no capacity drops); O(T*E) compute — fine for tests and
    single-chip small-E serving."""
    T, H = x.shape
    p = primary_experts(p, cfg)  # EPLB replicas are an EP-path concern
    topw, topi = route(p, cfg, x)                        # [T, K]
    out_all = _expert_mlp(
        p["w_gate"], p["w_up"], p["w_down"],
        jnp.broadcast_to(x, (cfg.num_experts, T, H)), x.dtype,
    )                                                    # [E, T, H]
    oh = jax.nn.one_hot(topi, cfg.num_experts, dtype=jnp.float32)  # [T, K, E]
    weights = (topw[..., None] * oh).sum(1)              # [T, E]
    return jnp.einsum("te,eth->th", weights.astype(x.dtype), out_all)


def moe_ffn_gather(
    p: Params, cfg: MoeConfig, x: jax.Array, routed=None
) -> jax.Array:
    """Sparse exact serving path (replicated experts): compute only the K
    routed experts per token via per-slot weight gathers.

    FLOPs are T*K*3HI vs the dense reference's T*E*3HI (16x less for a
    128-expert/top-8 model), and HBM reads touch only the selected experts'
    weights — the decode-step win for high-E/low-K models. K is static and
    small, so the loop unrolls under jit into K gather+einsum chains.

    ``routed`` overrides the router output (topw, topi) — the MLA family
    passes its DeepSeek-style routing through the same kernel."""
    topw, topi = routed if routed is not None else route(p, cfg, x)
    y = jnp.zeros_like(x)
    for k in range(topi.shape[1]):
        idx = topi[:, k]                                 # [T]
        gate = jnp.einsum("th,thi->ti", x, p["w_gate"][idx])
        up = jnp.einsum("th,thi->ti", x, p["w_up"][idx])
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
        contrib = jnp.einsum("ti,tih->th", act, p["w_down"][idx])
        y = y + topw[:, k, None].astype(x.dtype) * contrib
    return y


# ---------------------------------------------------------------------------
# EP strategies
# ---------------------------------------------------------------------------


def moe_ffn_ep_psum(
    p: Params, cfg: MoeConfig, x: jax.Array, axis_name: str, routed=None
) -> jax.Array:
    """Inside shard_map: tokens replicated on ``axis_name``, expert-stacked
    weights sharded on their leading dim. Each shard computes its local
    experts' weighted contribution; psum combines. ``routed`` injects
    precomputed (topw, topi) — used by the MLA family's DeepSeek router,
    whose routing runs outside the shard_map."""
    T, H = x.shape
    E_loc = p["w_gate"].shape[0]
    me = jax.lax.axis_index(axis_name)
    topw, topi = routed if routed is not None else route(p, cfg, x)
    # EPLB: logical -> physical replica slots (tables replicated across
    # shards, so every shard computes the same assignment)
    topi = eplb_remap(p, topi)
    out_all = _expert_mlp(
        p["w_gate"], p["w_up"], p["w_down"],
        jnp.broadcast_to(x, (E_loc, T, H)), x.dtype,
    )                                                    # [E_loc, T, H]
    oh = jax.nn.one_hot(
        topi - me * E_loc, E_loc, dtype=jnp.float32
    )                                                    # [T, K, E_loc] (oob -> 0)
    weights = (topw[..., None] * oh).sum(1)              # [T, E_loc]
    local = jnp.einsum("te,eth->th", weights.astype(x.dtype), out_all)
    return jax.lax.psum(local, axis_name)


def moe_ffn_ep_a2a(
    p: Params, cfg: MoeConfig, x: jax.Array, axis_name: str
) -> jax.Array:
    """Inside shard_map: tokens SHARDED on ``axis_name`` [T_loc, H], experts
    sharded [E_loc, ...]. GShard-style capacity dispatch with two
    all-to-alls over ICI."""
    T, H = x.shape
    K = cfg.num_experts_per_tok
    ep = jax.lax.psum(1, axis_name)
    # E here is PHYSICAL (== logical when EPLB is off): the dispatch works
    # in physical slots; capacity stays a per-logical-expert budget
    E_loc = p["w_gate"].shape[0]
    E = E_loc * ep
    C = max(1, int(math.ceil(T * K / cfg.num_experts * cfg.capacity_factor)))

    topw, topi = route(p, cfg, x)                        # [T, K]
    topi = eplb_remap(p, topi)
    flat_i = topi.reshape(T * K)                         # expert per slot
    flat_w = topw.reshape(T * K)
    oh = jax.nn.one_hot(flat_i, E, dtype=jnp.float32)    # [T*K, E]
    pos = jnp.cumsum(oh, axis=0) - oh                    # queue position
    pos_sel = (pos * oh).sum(-1).astype(jnp.int32)       # [T*K]
    keep = pos_sel < C
    disp = oh * keep[:, None]                            # drop overflow
    slot_oh = jax.nn.one_hot(pos_sel, C, dtype=jnp.float32)
    # combine[t*k, e, c]: 1 where slot lands at (e, c)
    combine = disp[:, :, None] * slot_oh[:, None, :]     # [T*K, E, C]

    x_rep = jnp.repeat(x, K, axis=0)                     # [T*K, H] (slot-major)
    x_disp = jnp.einsum(
        "sec,sh->ech", combine.astype(x.dtype), x_rep
    )                                                    # [E, C, H]

    # ship each expert's buffer to its owner: tiled a2a keeps [E, C, H],
    # rows regrouped as (src_shard, local_expert)
    x_recv = jax.lax.all_to_all(
        x_disp, axis_name, split_axis=0, concat_axis=0, tiled=True
    )
    x_exp = (
        x_recv.reshape(ep, E_loc, C, H)
        .transpose(1, 0, 2, 3)
        .reshape(E_loc, ep * C, H)
    )
    y_exp = _expert_mlp(p["w_gate"], p["w_up"], p["w_down"], x_exp, x.dtype)
    y_send = (
        y_exp.reshape(E_loc, ep, C, H)
        .transpose(1, 0, 2, 3)
        .reshape(E, C, H)
    )
    y_recv = jax.lax.all_to_all(
        y_send, axis_name, split_axis=0, concat_axis=0, tiled=True
    )
    weighted = combine * flat_w[:, None, None]           # [T*K, E, C]
    y = jnp.einsum("sec,ech->sh", weighted.astype(x.dtype), y_recv)
    return y.reshape(T, K, H).sum(1)


# ---------------------------------------------------------------------------
# full model
# ---------------------------------------------------------------------------


def layer_forward(
    p: Params,
    cfg: MoeConfig,
    x: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    attend: AttendFn,
    layer_idx: int,
    ffn_fn=None,
) -> jax.Array:
    """Same attention block as llama.layer_forward (cited there); the MLP is
    the sparse MoE. ``ffn_fn(p, cfg, x2d)`` overrides the FFN strategy."""
    h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
    q = h @ p["wq"]
    k = h @ p["wk"]
    v = h @ p["wv"]
    new_shape = h.shape[:-1]
    q = q.reshape(*new_shape, cfg.num_heads, cfg.head_dim)
    k = k.reshape(*new_shape, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(*new_shape, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = rms_norm(q, p["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, p["k_norm"], cfg.rms_norm_eps)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn_out = attend(q, k, v, layer_idx)
    attn_out = attn_out.reshape(*new_shape, cfg.q_size)
    x = x + attn_out @ p["wo"]

    h = rms_norm(x, p["mlp_norm"], cfg.rms_norm_eps)
    lead = h.shape[:-1]
    h2d = h.reshape(-1, cfg.hidden_size)
    fn = ffn_fn if ffn_fn is not None else moe_ffn
    y = fn(p, cfg, h2d).reshape(*lead, cfg.hidden_size)
    return x + y


def forward(
    params: Params,
    cfg: MoeConfig,
    token_ids: jax.Array,
    positions: jax.Array,
    attend: AttendFn,
    ffn_fn=None,
) -> jax.Array:
    x = params["embed"][token_ids]
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)
    cos, sin = cos[..., None, :], sin[..., None, :]
    for i, layer in enumerate(params["layers"]):
        x = layer_forward(layer, cfg, x, cos, sin, attend, i, ffn_fn=ffn_fn)
    return rms_norm(x, params["final_norm"], cfg.rms_norm_eps)


lm_logits = llama.lm_logits
