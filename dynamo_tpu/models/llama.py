"""Llama-family transformer in functional JAX (covers Llama 2/3, Qwen 2/3,
Mistral, DeepSeek-distill dense layouts via config switches).

Design notes (TPU-first):
- Pure param-pytree + functions: shardings are NamedSharding annotations on
  the pytree, jit handles the rest (psum inserted by XLA for row-parallel
  matmuls when inputs/outputs are sharded per parallel/mesh.py specs).
- Weights in bfloat16 (MXU native); attention logits and softmax in float32.
- Layers are a Python-level loop (unrolled under jit): no data-dependent
  control flow, static shapes everywhere.
- Attention is pluggable: callers pass an ``attend`` function so the same
  block stack serves contiguous prefill, paged decode, and ring/SP variants
  (see ops/attention.py).

The reference treats models as engine-internal (vLLM/SGLang own them); here
the model is first-class framework code.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 512
    hidden_size: int = 256
    num_layers: int = 4
    num_heads: int = 4
    num_kv_heads: int = 2
    head_dim: int = 64
    intermediate_size: int = 688
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    max_position: int = 8192
    qkv_bias: bool = False          # Qwen2-style
    qk_norm: bool = False           # Qwen3-style per-head q/k RMSNorm
    tie_embeddings: bool = True
    dtype: Any = jnp.bfloat16

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test-scale config (byte tokenizer vocab)."""
        return cls(**kw)

    @classmethod
    def llama3_8b(cls, vocab_size: int = 128256) -> "LlamaConfig":
        return cls(
            vocab_size=vocab_size, hidden_size=4096, num_layers=32, num_heads=32,
            num_kv_heads=8, head_dim=128, intermediate_size=14336,
            rope_theta=500000.0, max_position=8192, tie_embeddings=False,
        )

    @classmethod
    def llama3_70b(cls, vocab_size: int = 128256) -> "LlamaConfig":
        return cls(
            vocab_size=vocab_size, hidden_size=8192, num_layers=80, num_heads=64,
            num_kv_heads=8, head_dim=128, intermediate_size=28672,
            rope_theta=500000.0, max_position=8192, tie_embeddings=False,
        )

    @classmethod
    def qwen3_0_6b(cls, vocab_size: int = 151936) -> "LlamaConfig":
        return cls(
            vocab_size=vocab_size, hidden_size=1024, num_layers=28, num_heads=16,
            num_kv_heads=8, head_dim=128, intermediate_size=3072,
            rope_theta=1000000.0, qk_norm=True, tie_embeddings=True,
        )


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_layer_params(rng: jax.Array, cfg: LlamaConfig) -> Params:
    k = jax.random.split(rng, 8)
    h, qd, kvd, inter = cfg.hidden_size, cfg.q_size, cfg.kv_size, cfg.intermediate_size
    scale = 1.0 / math.sqrt(h)
    iscale = 1.0 / math.sqrt(inter)
    p: Params = {
        "attn_norm": jnp.ones((h,), cfg.dtype),
        "mlp_norm": jnp.ones((h,), cfg.dtype),
        "wq": (jax.random.normal(k[0], (h, qd)) * scale).astype(cfg.dtype),
        "wk": (jax.random.normal(k[1], (h, kvd)) * scale).astype(cfg.dtype),
        "wv": (jax.random.normal(k[2], (h, kvd)) * scale).astype(cfg.dtype),
        "wo": (jax.random.normal(k[3], (qd, h)) * scale).astype(cfg.dtype),
        "w_gate": (jax.random.normal(k[4], (h, inter)) * scale).astype(cfg.dtype),
        "w_up": (jax.random.normal(k[5], (h, inter)) * scale).astype(cfg.dtype),
        "w_down": (jax.random.normal(k[6], (inter, h)) * iscale).astype(cfg.dtype),
    }
    if cfg.qkv_bias:
        p["bq"] = jnp.zeros((qd,), cfg.dtype)
        p["bk"] = jnp.zeros((kvd,), cfg.dtype)
        p["bv"] = jnp.zeros((kvd,), cfg.dtype)
    if cfg.qk_norm:
        p["q_norm"] = jnp.ones((cfg.head_dim,), cfg.dtype)
        p["k_norm"] = jnp.ones((cfg.head_dim,), cfg.dtype)
    return p


def init_params(rng: jax.Array, cfg: LlamaConfig) -> Params:
    keys = jax.random.split(rng, cfg.num_layers + 2)
    params: Params = {
        "embed": (
            jax.random.normal(keys[0], (cfg.vocab_size, cfg.hidden_size)) * 0.02
        ).astype(cfg.dtype),
        "final_norm": jnp.ones((cfg.hidden_size,), cfg.dtype),
        "layers": [init_layer_params(keys[i + 2], cfg) for i in range(cfg.num_layers)],
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = (
            jax.random.normal(keys[1], (cfg.hidden_size, cfg.vocab_size)) * 0.02
        ).astype(cfg.dtype)
    return params


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(dtype) * weight


def rope_cos_sin(
    positions: jax.Array, head_dim: int, theta: float
) -> Tuple[jax.Array, jax.Array]:
    """positions [...,] -> cos/sin [..., head_dim//2] (float32)."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., half]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x [..., n_heads, head_dim], cos/sin broadcastable [..., 1, head_dim//2].

    Uses the "rotate-half" layout matching HF Llama (first/second half pairs),
    so HF checkpoints load without permutation."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out1 = x1f * cos - x2f * sin
    out2 = x2f * cos + x1f * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


# attend(q, k_new, v_new, layer_idx) -> attention output [..., n_heads, head_dim]
AttendFn = Callable[[jax.Array, jax.Array, jax.Array, int], jax.Array]


def layer_forward(
    p: Params,
    cfg: LlamaConfig,
    x: jax.Array,                 # [..., S, hidden]
    cos: jax.Array,
    sin: jax.Array,
    attend: AttendFn,
    layer_idx: int,
    lora: Optional[Callable] = None,
) -> jax.Array:
    # optional batched LoRA (lora/adapters.py make_lora_fn): delta added to
    # a projection's output; returns None for targets without adapters
    def _lora(name: str, inp: jax.Array, out: jax.Array) -> jax.Array:
        if lora is None:
            return out
        delta = lora(name, layer_idx, inp)
        return out if delta is None else out + delta

    # attention
    h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
    q = _lora("wq", h, h @ p["wq"])
    k = _lora("wk", h, h @ p["wk"])
    v = _lora("wv", h, h @ p["wv"])
    if cfg.qkv_bias:
        q, k, v = q + p["bq"], k + p["bk"], v + p["bv"]
    new_shape = h.shape[:-1]
    q = q.reshape(*new_shape, cfg.num_heads, cfg.head_dim)
    k = k.reshape(*new_shape, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(*new_shape, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = rms_norm(q, p["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, p["k_norm"], cfg.rms_norm_eps)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn_out = attend(q, k, v, layer_idx)
    attn_out = attn_out.reshape(*new_shape, cfg.q_size)
    x = x + _lora("wo", attn_out, attn_out @ p["wo"])
    # mlp
    h = rms_norm(x, p["mlp_norm"], cfg.rms_norm_eps)
    gate = jax.nn.silu(
        (_lora("w_gate", h, h @ p["w_gate"])).astype(jnp.float32)
    ).astype(x.dtype)
    up = _lora("w_up", h, h @ p["w_up"])
    gu = gate * up
    x = x + _lora("w_down", gu, gu @ p["w_down"])
    return x


def forward(
    params: Params,
    cfg: LlamaConfig,
    token_ids: jax.Array,        # [..., S] int32
    positions: jax.Array,        # [..., S] int32
    attend: AttendFn,
    lora: Optional[Callable] = None,
    inputs_embeds: Optional[jax.Array] = None,  # [..., S, hidden]
) -> jax.Array:
    """Full stack -> final hidden states [..., S, hidden] (pre-lm_head).

    ``inputs_embeds`` replaces the embedding gather when given — the
    multimodal path splices vision soft tokens in (models/vision.py)."""
    x = params["embed"][token_ids] if inputs_embeds is None else inputs_embeds
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)
    cos, sin = cos[..., None, :], sin[..., None, :]  # broadcast over heads
    for i, layer in enumerate(params["layers"]):
        x = layer_forward(layer, cfg, x, cos, sin, attend, i, lora=lora)
    return rms_norm(x, params["final_norm"], cfg.rms_norm_eps)


def lm_logits(params: Params, cfg: LlamaConfig, hidden: jax.Array) -> jax.Array:
    if cfg.tie_embeddings:
        return (hidden @ params["embed"].T).astype(jnp.float32)
    return (hidden @ params["lm_head"]).astype(jnp.float32)
