"""Frontend preprocessor: OpenAI request -> PreprocessedRequest (tokens).

Analog of the reference's OpenAIPreprocessor (lib/llm/src/preprocessor.rs):
applies the chat template, tokenizes, folds sampling + stop options into the
internal request, and stamps metric annotations (input token count, cached
tokens once routing decides).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from ..runtime.errors import (
    ContextLengthError,
    GuidedRejectedError,
    InvalidRequestError,
)
from ..runtime.logging import get_logger
from .model_card import ModelDeploymentCard
from .protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
from .protocols.openai import ChatCompletionRequest, CompletionRequest, new_request_id
from .tokenizer import Tokenizer, load_tokenizer

log = get_logger("llm.preprocessor")

ANNOTATION_INPUT_TOKENS = "input_tokens"
ANNOTATION_CACHED_TOKENS = "cached_tokens"
ANNOTATION_FORMATTED_PROMPT = "formatted_prompt"
ANNOTATION_WORKER_ID = "worker_id"
ANNOTATION_PREFILL_WORKER_ID = "prefill_worker_id"


class OpenAIPreprocessor:
    def __init__(self, card: ModelDeploymentCard, tokenizer: Tokenizer | None = None):
        self.card = card
        self.tokenizer = tokenizer or load_tokenizer(card.tokenizer)

    # -- tokenization --------------------------------------------------------
    def tokenize_chat(self, request: ChatCompletionRequest) -> List[int]:
        messages = [m.model_dump(exclude_none=True) for m in request.messages]
        encode_chat = getattr(self.tokenizer, "encode_chat", None)
        if encode_chat is not None:
            return encode_chat(messages)
        prompt = self.tokenizer.apply_chat_template(messages, add_generation_prompt=True)
        return self.tokenizer.encode(prompt)

    def _has_images(self, request: ChatCompletionRequest) -> bool:
        has = any(
            isinstance(m.content, list)
            and any(p.get("type") == "image_url" for p in m.content)
            for m in request.messages
        )
        if has and self.card.image_tokens <= 0:
            # silently dropping the image would produce a confident answer
            # about content the model never saw
            raise InvalidRequestError(
                f"model {self.card.name!r} does not accept image input"
            )
        return has

    def _check_audio(self, request: ChatCompletionRequest) -> None:
        """Audio requests against a non-audio model fail loudly (reference
        async-openai carries the types; serving needs a capable model)."""
        if self.card.audio:
            return
        wants_audio = "audio" in (request.modalities or [])
        has_audio_part = any(
            isinstance(m.content, list)
            and any(p.get("type") in ("input_audio", "audio") for p in m.content)
            for m in request.messages
        )
        if wants_audio or has_audio_part:
            raise InvalidRequestError(
                f"model {self.card.name!r} does not support audio input/output"
            )

    def tokenize_chat_multimodal(self, request: ChatCompletionRequest):
        """Chat messages with image parts -> (token_ids with placeholder
        runs, decoded images). Multimodal prompts use plain role framing
        (templates are text functions; image spans must stay byte-exact),
        like the reference's media preprocessor path
        (lib/llm/src/preprocessor/media/). Each image becomes
        ``card.image_tokens`` placeholder ids; the engine splices the vision
        tower's patch embeddings over them."""
        from .media import decode_image

        tokens: List[int] = []
        images: List[dict] = []
        for m in request.messages:
            tokens.extend(self.tokenizer.encode(f"<|{m.role}|>\n"))
            parts = m.content if isinstance(m.content, list) else [
                {"type": "text", "text": m.content or ""}
            ]
            for part in parts:
                if part.get("type") == "image_url":
                    url = (part.get("image_url") or {}).get("url", "")
                    arr = decode_image(url, self.card.image_size)
                    images.append({
                        "data": arr.tobytes(),
                        "shape": list(arr.shape),
                    })
                    tokens.extend(
                        [self.card.image_token_id] * self.card.image_tokens
                    )
                    # separator: adjacent image parts must stay distinct
                    # placeholder RUNS (the engine maps one run per image)
                    tokens.extend(self.tokenizer.encode("\n"))
                elif part.get("type") == "text":
                    tokens.extend(self.tokenizer.encode(part.get("text", "")))
            tokens.extend(self.tokenizer.encode("\n"))
        tokens.extend(self.tokenizer.encode("<|assistant|>\n"))
        return tokens, images

    def tokenize_prompt(self, prompt: Union[str, List[int]]) -> List[int]:
        if isinstance(prompt, str):
            return self.tokenizer.encode(prompt)
        return list(prompt)

    @staticmethod
    def _guided_spec(request) -> Optional[Dict[str, Any]]:
        """Guided-decoding spec from the request, in the reference's
        precedence (common_ext.rs:175-219): explicit guided_json, then
        tool_choice-derived schema, then guided_regex/choice, then chat
        response_format. Tool-derived specs are marked soft=True — engines
        without guidance compiled in serve them unconstrained (the
        tool-call jail still enforces the framing) instead of erroring.

        Explicit specs are syntax-validated here so malformed grammars fail
        as 400s at the frontend (reference openai/validate.rs); the engine
        still enforces its own automaton caps at compile time."""

        def _checked(spec):
            if not spec.get("soft"):
                from ..guided import guided_regex_pattern
                from ..guided.regex import validate_pattern

                try:
                    validate_pattern(
                        guided_regex_pattern(spec["kind"], spec["value"])
                    )
                except Exception as e:
                    raise GuidedRejectedError(f"invalid guided grammar: {e}") from e
            return spec

        if getattr(request, "guided_json", None) is not None:
            return _checked({"kind": "json", "value": request.guided_json})
        tc = getattr(request, "tool_choice", None)
        if isinstance(tc, dict) and (tc.get("function") or {}).get("name"):
            name = tc["function"]["name"]
            for tool in getattr(request, "tools", None) or []:
                fn = tool.get("function") or {}
                if fn.get("name") == name:
                    params = fn.get("parameters") or {"type": "object"}
                    return {
                        "kind": "json",
                        "value": {
                            "type": "object",
                            "properties": {
                                "name": {"const": name},
                                "arguments": params,
                            },
                            "required": ["name", "arguments"],
                        },
                        "soft": True,
                    }
        if getattr(request, "guided_regex", None) is not None:
            return _checked({"kind": "regex", "value": request.guided_regex})
        if getattr(request, "guided_choice", None) is not None:
            return _checked({"kind": "choice", "value": list(request.guided_choice)})
        rf = getattr(request, "response_format", None) or {}
        if rf.get("type") == "json_schema":
            schema = (rf.get("json_schema") or {}).get("schema")
            if schema is not None:
                return _checked({"kind": "json", "value": schema})
        if rf.get("type") == "json_object":
            return {"kind": "json_object", "value": None}  # built-in grammar, always valid
        return None

    # -- request conversion --------------------------------------------------
    def _common(
        self,
        request: Union[ChatCompletionRequest, CompletionRequest],
        token_ids: List[int],
        request_id: str,
    ) -> PreprocessedRequest:
        if len(token_ids) >= self.card.context_length:
            raise ContextLengthError(
                f"prompt length {len(token_ids)} exceeds model context "
                f"{self.card.context_length}"
            )
        sampling = SamplingOptions(
            temperature=request.temperature if request.temperature is not None else 1.0,
            top_p=request.top_p if request.top_p is not None else 1.0,
            top_k=request.top_k if request.top_k is not None else -1,
            min_p=request.min_p or 0.0,
            seed=request.seed,
            frequency_penalty=request.frequency_penalty or 0.0,
            presence_penalty=request.presence_penalty or 0.0,
            repetition_penalty=request.repetition_penalty or 1.0,
            # chat style: logprobs=true (+ top_logprobs=N alternatives);
            # completions style: logprobs=N directly (N=0 still returns the
            # chosen token's logprob with no alternatives)
            logprobs=(
                int(request.logprobs)
                if isinstance(request.logprobs, int) and not isinstance(request.logprobs, bool)
                else int(request.top_logprobs or 0)
            ),
            want_logprobs=request.logprobs is not None and request.logprobs is not False,
            guided=self._guided_spec(request),
        )
        max_new = request.effective_max_tokens()
        budget = self.card.context_length - len(token_ids)
        stop = StopConditions(
            max_tokens=min(max_new, budget) if max_new else budget,
            stop_strings=request.stop_list(),
            ignore_eos=bool(request.ignore_eos),
        )
        annotations = {ANNOTATION_INPUT_TOKENS: len(token_ids)}
        if getattr(request, "lora", None):
            annotations["lora"] = request.lora
        if getattr(request, "logits_processors", None):
            annotations["logits_processors"] = list(request.logits_processors)
        return PreprocessedRequest(
            request_id=request_id,
            model=request.model,
            token_ids=token_ids,
            stop=stop,
            sampling=sampling,
            annotations=annotations,
        )

    def preprocess_chat(self, request: ChatCompletionRequest) -> PreprocessedRequest:
        rid = new_request_id("chatcmpl")
        self._check_audio(request)
        if self._has_images(request):
            tokens, images = self.tokenize_chat_multimodal(request)
            preq = self._common(request, tokens, rid)
            preq.annotations["images"] = images
            return preq
        return self._common(request, self.tokenize_chat(request), rid)

    def preprocess_completion(
        self, request: CompletionRequest, prompt: Union[str, List[int]]
    ) -> PreprocessedRequest:
        rid = new_request_id("cmpl")
        return self._common(request, self.tokenize_prompt(prompt), rid)
