"""Performance recording + analysis of streaming responses.

Analog of the reference's perf module (lib/llm/src/perf.rs +
perf/logprobs.rs): wrap any token stream to record timestamped responses
with minimal overhead, then analyze offline — TTFT/ITL percentiles,
throughput, and logprob sensitivity (how close sampling came to picking a
different token — the signal the reference's logprob analysis extracts).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple


@dataclasses.dataclass
class TimestampedResponse:
    response: Any
    elapsed_s: float        # since stream start
    sequence_number: int


@dataclasses.dataclass
class RecordedStream:
    """The recording a wrapped stream leaves behind (perf.rs:84-135)."""

    responses: List[TimestampedResponse] = dataclasses.field(default_factory=list)
    started_at: float = 0.0
    ended_at: float = 0.0

    @property
    def response_count(self) -> int:
        return len(self.responses)

    @property
    def total_duration_s(self) -> float:
        return max(self.ended_at - self.started_at, 0.0)

    # -- analysis ------------------------------------------------------------
    def token_timestamps(self) -> List[float]:
        """Per-token arrival times (a multi-token response's tokens share
        its timestamp — horizon emission)."""
        out: List[float] = []
        for r in self.responses:
            ids = getattr(r.response, "token_ids", None)
            if ids is None and isinstance(r.response, dict):
                ids = r.response.get("token_ids")
            for _ in ids or []:
                out.append(r.elapsed_s)
        return out

    def analyze(self) -> Dict[str, float]:
        ts = self.token_timestamps()
        if not ts:
            return {"tokens": 0}
        itls = [b - a for a, b in zip(ts, ts[1:]) if b > a]
        itls.sort()

        def pct(xs: List[float], p: float) -> float:
            return xs[min(len(xs) - 1, int(p * len(xs)))] if xs else 0.0

        dur = self.total_duration_s or ts[-1] or 1e-9
        return {
            "tokens": len(ts),
            "ttft_s": round(ts[0], 6),
            "itl_mean_s": round(sum(itls) / len(itls), 6) if itls else 0.0,
            "itl_p50_s": round(pct(itls, 0.50), 6),
            "itl_p95_s": round(pct(itls, 0.95), 6),
            "tokens_per_s": round(len(ts) / dur, 3),
            "duration_s": round(dur, 6),
        }


async def record_stream(
    stream: AsyncIterator[Any],
    recording: Optional[RecordedStream] = None,
) -> AsyncIterator[Any]:
    """Pass-through wrapper stamping every response (perf.rs RecordingStream:
    collection stays cheap; analysis happens after the stream ends)."""
    rec = recording if recording is not None else RecordedStream()
    rec.started_at = time.monotonic()
    seq = 0
    try:
        async for item in stream:
            rec.responses.append(TimestampedResponse(
                item, time.monotonic() - rec.started_at, seq
            ))
            seq += 1
            yield item
    finally:
        rec.ended_at = time.monotonic()


# ---------------------------------------------------------------------------
# logprob sensitivity (perf/logprobs.rs analog)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PositionCloseness:
    position: int
    selected_token: int
    selected_logprob: float
    runner_up_token: Optional[int]
    margin: float               # logprob gap to the runner-up (inf if none)

    @property
    def prob_ratio(self) -> float:
        """P(runner_up)/P(selected): 1.0 = a coin flip, 0 = deterministic."""
        return math.exp(-self.margin) if math.isfinite(self.margin) else 0.0


@dataclasses.dataclass
class SensitivityAnalysis:
    """How close each sampled position came to a different token."""

    positions: List[PositionCloseness]

    @property
    def close_calls(self) -> List[PositionCloseness]:
        return [p for p in self.positions if p.prob_ratio >= 0.5]

    @property
    def min_margin(self) -> float:
        return min((p.margin for p in self.positions), default=math.inf)

    def summary(self) -> Dict[str, Any]:
        return {
            "positions": len(self.positions),
            "close_calls": len(self.close_calls),
            "min_margin": round(self.min_margin, 6)
            if math.isfinite(self.min_margin) else None,
            "mean_prob_ratio": round(
                sum(p.prob_ratio for p in self.positions) / len(self.positions), 6
            ) if self.positions else 0.0,
        }


def analyze_logprobs(entries: List[Dict[str, Any]]) -> SensitivityAnalysis:
    """``logprob_entries`` from the backend (token, logprob, top_logprobs
    list of {token, logprob}) -> closeness per position."""
    positions: List[PositionCloseness] = []
    for n, e in enumerate(entries or []):
        sel_tok = e.get("token_id", e.get("token"))
        sel_lp = float(e.get("logprob", 0.0))
        runner: Tuple[Optional[int], float] = (None, math.inf)
        for alt in e.get("top_logprobs") or []:
            alt_tok = alt.get("token_id", alt.get("token"))
            if alt_tok == sel_tok:
                continue
            gap = sel_lp - float(alt.get("logprob", -math.inf))
            if gap < runner[1]:
                runner = (alt_tok, gap)
        positions.append(PositionCloseness(
            position=n, selected_token=sel_tok, selected_logprob=sel_lp,
            runner_up_token=runner[0], margin=max(runner[1], 0.0),
        ))
    return SensitivityAnalysis(positions)
