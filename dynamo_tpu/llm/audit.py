"""Request/response audit subsystem for the OpenAI frontends.

Analog of the reference's audit module (lib/llm/src/audit/{config,handle,
bus,sink}.rs): a policy decides per-request whether to audit (enabled via
``DYN_AUDIT_SINKS``; honored when the request sets ``store`` or
``DYN_AUDIT_FORCE_LOGGING`` is on), a handle accumulates the request and
final response, and ``emit()`` publishes one AuditRecord to every configured
sink exactly once. Sinks: ``stderr`` (structured log line), ``jsonl:<path>``
(file), ``event`` (the runtime event plane — the NATS-sink analog).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Any, Dict, List, Optional

from ..runtime.logging import get_logger

log = get_logger("llm.audit")

AUDIT_SCHEMA_VERSION = 1


@dataclasses.dataclass
class AuditPolicy:
    enabled: bool = False
    force_logging: bool = False
    sinks: List[str] = dataclasses.field(default_factory=list)

    @classmethod
    def from_env(cls) -> "AuditPolicy":
        from ..runtime.config import (
            ENV_AUDIT_FORCE_LOGGING,
            ENV_AUDIT_SINKS,
            is_truthy,
        )

        sinks_env = (
            os.environ.get(ENV_AUDIT_SINKS) or os.environ.get("DYN_AUDIT_SINKS", "")
        )
        sinks = [s.strip() for s in sinks_env.split(",") if s.strip()]
        return cls(
            enabled=bool(sinks),
            force_logging=is_truthy(
                os.environ.get(ENV_AUDIT_FORCE_LOGGING)
                or os.environ.get("DYN_AUDIT_FORCE_LOGGING")
            ),
            sinks=sinks,
        )


@dataclasses.dataclass
class AuditRecord:
    schema_version: int
    request_id: str
    requested_streaming: bool
    model: str
    request: Optional[Dict[str, Any]] = None
    response: Optional[Dict[str, Any]] = None

    def to_obj(self) -> Dict[str, Any]:
        obj = {
            "schema_version": self.schema_version,
            "request_id": self.request_id,
            "requested_streaming": self.requested_streaming,
            "model": self.model,
        }
        if self.request is not None:
            obj["request"] = self.request
        if self.response is not None:
            obj["response"] = self.response
        return obj


class StderrSink:
    name = "stderr"

    def emit(self, rec: AuditRecord) -> None:
        log.info("audit %s", json.dumps(rec.to_obj()))


class JsonlSink:
    name = "jsonl"

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def emit(self, rec: AuditRecord) -> None:
        with self._lock, open(self.path, "a") as f:
            f.write(json.dumps(rec.to_obj()) + "\n")


class EventPlaneSink:
    """Publish records on the runtime event plane (reference NatsSink analog,
    audit/sink.rs:35-63); subject from DYN_AUDIT_SUBJECT."""

    name = "event"

    def __init__(self, event_plane, subject: Optional[str] = None):
        from ..runtime.config import ENV_AUDIT_SUBJECT

        self.event_plane = event_plane
        self.subject = subject or os.environ.get(
            ENV_AUDIT_SUBJECT, os.environ.get("DYN_AUDIT_SUBJECT", "dynamo.audit.v1")
        )
        self._pending: List[AuditRecord] = []

    def emit(self, rec: AuditRecord) -> None:
        # event planes are async; buffer for the bus pump (AuditBus.drain)
        self._pending.append(rec)

    async def drain(self) -> None:
        import msgpack

        pending, self._pending = self._pending, []
        for rec in pending:
            await self.event_plane.publish(
                self.subject, msgpack.packb(rec.to_obj(), use_bin_type=True)
            )


class AuditBus:
    """Fan records out to every sink; the reference's broadcast bus
    (audit/bus.rs) collapsed to synchronous fan-out plus an async drain for
    the event-plane sink."""

    def __init__(self, policy: Optional[AuditPolicy] = None, event_plane=None):
        self.policy = policy or AuditPolicy.from_env()
        self.sinks: List[Any] = []
        for spec in self.policy.sinks:
            if spec == "stderr":
                self.sinks.append(StderrSink())
            elif spec.startswith("jsonl:"):
                self.sinks.append(JsonlSink(spec.split(":", 1)[1]))
            elif spec == "event":
                if event_plane is not None:
                    self.sinks.append(EventPlaneSink(event_plane))
                else:
                    log.warning("audit sink 'event' requested but no event plane wired")
            else:
                log.warning("unknown audit sink %r ignored", spec)

    def publish(self, rec: AuditRecord) -> None:
        for sink in self.sinks:
            try:
                sink.emit(rec)
            except Exception:
                log.exception("audit sink %s failed", getattr(sink, "name", "?"))

    async def drain_async_sinks(self) -> None:
        for sink in self.sinks:
            drain = getattr(sink, "drain", None)
            if drain is not None:
                try:
                    await drain()
                except Exception:
                    log.exception("audit sink %s drain failed", getattr(sink, "name", "?"))

    # -- handle creation ------------------------------------------------------
    def create_handle(
        self, request_obj: Dict[str, Any], request_id: str, model: str,
        streaming: bool,
    ) -> Optional["AuditHandle"]:
        """None unless policy says this request is audited (reference
        handle.rs:59-77: enabled + (store flag or force_logging))."""
        if not self.policy.enabled or not self.sinks:
            return None
        if not self.policy.force_logging and not request_obj.get("store"):
            return None
        return AuditHandle(
            bus=self,
            request_id=request_id,
            model=model,
            requested_streaming=streaming,
            request=request_obj,
        )


@dataclasses.dataclass
class AuditHandle:
    bus: AuditBus
    request_id: str
    model: str
    requested_streaming: bool
    request: Optional[Dict[str, Any]] = None
    response: Optional[Dict[str, Any]] = None
    _emitted: bool = False

    def set_response(self, response_obj: Dict[str, Any]) -> None:
        self.response = response_obj

    def emit(self) -> None:
        if self._emitted:
            return
        self._emitted = True
        self.bus.publish(AuditRecord(
            schema_version=AUDIT_SCHEMA_VERSION,
            request_id=self.request_id,
            requested_streaming=self.requested_streaming,
            model=self.model,
            request=self.request,
            response=self.response,
        ))
