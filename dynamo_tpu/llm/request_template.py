"""Preset request defaults loaded from a JSON file.

Analog of the reference's request template (lib/llm/src/request_template.rs:
a JSON file with model / temperature / max_completion_tokens, wired through
the frontend so clients may omit those fields; http/service/openai.rs:892-901
fills each field only when the request left it unset).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional


@dataclasses.dataclass
class RequestTemplate:
    model: str = ""
    temperature: Optional[float] = None
    max_completion_tokens: Optional[int] = None

    @classmethod
    def load(cls, path: str) -> "RequestTemplate":
        with open(path) as f:
            raw = json.load(f)
        if not isinstance(raw, dict):
            raise ValueError(f"request template {path!r} must be a JSON object")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(
                f"request template {path!r}: unknown keys {sorted(unknown)}"
            )
        return cls(**raw)

    def apply(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Fill template values into a raw request body, request wins: each
        field is taken from the template only when the request left it
        unset (absent, null, or empty-string model)."""
        if not isinstance(body, dict):
            # let request validation produce its normal 400 for non-object
            # bodies instead of raising TypeError here
            return body
        out = dict(body)
        if self.model and not out.get("model"):
            out["model"] = self.model
        if self.temperature is not None and out.get("temperature") is None:
            out["temperature"] = self.temperature
        if (
            self.max_completion_tokens is not None
            and out.get("max_completion_tokens") is None
            and out.get("max_tokens") is None
        ):
            out["max_completion_tokens"] = self.max_completion_tokens
        return out
