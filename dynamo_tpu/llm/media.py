"""Media decoding for multimodal requests.

Analog of the reference's preprocessor media path (lib/llm/src/preprocessor/
media/ — fetch + decode of image inputs before the engine sees them). Fully
offline: ``data:`` URLs (base64 image bytes via PIL, or raw ``.npy``
payloads) and local ``file://`` paths; remote http(s) fetch is refused (the
serving tier has no egress by policy — front it with a fetcher if needed).
"""

from __future__ import annotations

import base64
import io
import urllib.parse

import numpy as np

from ..runtime.logging import get_logger

log = get_logger("llm.media")


def decode_image(url: str, image_size: int) -> np.ndarray:
    """URL -> float32 RGB array [image_size, image_size, 3] in [0, 1]."""
    if url.startswith("data:"):
        header, _, b64 = url.partition(",")
        raw = base64.b64decode(b64)
        if "application/x-npy" in header:
            arr = np.load(io.BytesIO(raw), allow_pickle=False)
            return _normalize(arr, image_size)
        return _decode_bytes(raw, image_size)
    if url.startswith("file://"):
        import os

        # arbitrary local reads driven by client URLs are a file-disclosure
        # hole: file:// only works under an operator-allowlisted root
        root = os.environ.get("DTPU_MEDIA_FILE_ROOT")
        if not root:
            raise ValueError(
                "file:// image urls are disabled (set DTPU_MEDIA_FILE_ROOT "
                "to an allowed directory to enable)"
            )
        path = os.path.realpath(urllib.parse.urlparse(url).path)
        if not path.startswith(os.path.realpath(root) + os.sep):
            raise ValueError("image path outside DTPU_MEDIA_FILE_ROOT")
        if path.endswith(".npy"):
            return _normalize(np.load(path, allow_pickle=False), image_size)
        with open(path, "rb") as f:
            return _decode_bytes(f.read(), image_size)
    raise ValueError(
        f"unsupported image url scheme {url[:32]!r} (data: and file:// only)"
    )


def _decode_bytes(raw: bytes, image_size: int) -> np.ndarray:
    from PIL import Image

    img = Image.open(io.BytesIO(raw)).convert("RGB")
    img = img.resize((image_size, image_size), Image.BILINEAR)
    return np.asarray(img, np.float32) / 255.0


def _normalize(arr: np.ndarray, image_size: int) -> np.ndarray:
    arr = np.asarray(arr, np.float32)
    if arr.ndim != 3 or arr.shape[-1] != 3:
        raise ValueError(f"expected [H, W, 3] image array, got {arr.shape}")
    if arr.max() > 1.5:
        arr = arr / 255.0
    if arr.shape[:2] != (image_size, image_size):
        # nearest-neighbor resize without PIL dependency for arrays
        ys = (np.arange(image_size) * arr.shape[0] / image_size).astype(int)
        xs = (np.arange(image_size) * arr.shape[1] / image_size).astype(int)
        arr = arr[ys][:, xs]
    return np.ascontiguousarray(arr, np.float32)
