"""Request migration: replay in-flight requests to another worker on failure.

Analog of the reference's Migration operator (lib/llm/src/migration.rs:24-43):
if the worker dies before or during generation (NoResponders / dropped
stream), re-send the request to a different worker carrying the tokens already
generated (``prior_token_ids``) so decode resumes where it stopped, bounded by
``migration_limit`` attempts.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Awaitable, Callable, Dict, List, Optional

from ..runtime.engine import Context
from ..runtime.flight_recorder import get_flight_recorder
from ..runtime.logging import get_logger
from ..runtime.request_plane.tcp import NoResponders
from .protocols.common import BackendOutput, PreprocessedRequest

log = get_logger("llm.migration")

# send(request, context, exclude_instance_ids) -> response stream
SendFn = Callable[[PreprocessedRequest, Context, List[int]], Awaitable[AsyncIterator[Any]]]


class Migration:
    def __init__(self, send: SendFn, migration_limit: int = 0):
        self.send = send
        # DTPU_MIGRATION_LIMIT applies at the worker CLI boundary (the
        # --migration-limit argparse default) so an explicit 0 here still
        # means "migration disabled" — don't re-consult the env
        self.migration_limit = migration_limit

    async def generate(
        self, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[BackendOutput]:
        attempts_left = self.migration_limit
        accumulated: List[int] = list(request.prior_token_ids)
        excluded: List[int] = []
        # a draining worker's parting gift (docs/operations.md §13): its
        # error-finish frame references this request's sealed KV (transfer
        # address + block hashes); the replay carries it so routing prices
        # destinations by pull bandwidth and the chosen worker fetches the
        # KV instead of re-prefilling
        evacuation: Optional[Dict[str, Any]] = None

        while True:
            req = request
            if accumulated != list(request.prior_token_ids) or evacuation is not None:
                # re-issue with progress so the new worker resumes decode
                req = PreprocessedRequest.from_obj(request.to_obj())
                req.prior_token_ids = list(accumulated)
                if req.stop.max_tokens is not None:
                    req.stop.max_tokens = max(
                        1, req.stop.max_tokens - (len(accumulated) - len(request.prior_token_ids))
                    )
                if evacuation is not None:
                    req.kv_transfer = dict(evacuation)
            try:
                stream = await self.send(req, context, excluded)
                async for item in stream:
                    out = item if isinstance(item, BackendOutput) else BackendOutput.from_obj(item)
                    if out.finish_reason == "error" and attempts_left > 0:
                        # a worker-delivered error finish is the engine dying
                        # with the courtesy of a last frame (loop crash,
                        # multihost group teardown) — migrate like any other
                        # worker loss instead of surfacing the error
                        err = NoResponders("worker reported error finish")
                        iid = getattr(stream, "instance_id", None)
                        if iid is not None:
                            err.instance_id = iid  # type: ignore[attr-defined]
                        # the dying engine attaches its evacuation plan to
                        # the error frame (TpuEngine._evacuation_plan); the
                        # retry replays it as the kv_transfer fetch below
                        evac = out.kv_transfer or out.annotations.get("evacuation")
                        if evac:
                            err.evacuation = evac  # type: ignore[attr-defined]
                        raise err
                    accumulated.extend(out.token_ids)
                    # a resumed worker counts only ITS OWN tokens: normalize
                    # to the original request so usage accounting survives
                    # migration (completion = everything past the original
                    # prior tokens)
                    out.cumulative_tokens = max(
                        out.cumulative_tokens,
                        len(accumulated) - len(request.prior_token_ids),
                    )
                    yield out
                    if out.finish_reason is not None:
                        return
                # stream ended without finish_reason: worker died mid-request.
                # Attribute the instance (the request plane's _TaggedStream
                # carries it) so the retry excludes the dead worker even on a
                # clean EOF with no transport exception.
                eof = NoResponders("stream ended without finish")
                iid = getattr(stream, "instance_id", None)
                if iid is not None:
                    eof.instance_id = iid  # type: ignore[attr-defined]
                raise eof
            except (NoResponders, ConnectionError) as e:
                if context.is_stopped() or attempts_left <= 0:
                    if attempts_left <= 0 and not context.is_stopped():
                        log.warning("migration limit exhausted: %s", e)
                        raise
                    return
                attempts_left -= 1
                # exclude the failed worker on ANY transport loss — a
                # ConnectionError retry that can re-route to the same dead
                # instance defeats the whole operator (the request plane tags
                # instance_id on the exception, runtime/component.py)
                worker_id: Optional[int] = getattr(e, "instance_id", None)
                if worker_id is not None and worker_id not in excluded:
                    excluded.append(worker_id)
                evac = getattr(e, "evacuation", None)
                if evac:
                    evacuation = dict(evac)
                get_flight_recorder().record(
                    request.request_id, "migration",
                    tokens_so_far=len(accumulated),
                    attempts_left=attempts_left,
                    from_worker=(f"{worker_id:016x}" if worker_id is not None
                                 else "unknown"),
                    evacuated=bool(evac),
                    error=str(e)[:200],
                )
                log.info(
                    "migrating request %s (%d tokens so far, %d attempts left): %s",
                    req.request_id, len(accumulated), attempts_left, e,
                )
