"""Delta generators: BackendOutput stream -> OpenAI SSE response objects.

Analog of the reference's streaming delta generator + aggregators
(lib/llm/src/protocols/openai/chat_completions/delta.rs, aggregator.rs).
"""

from __future__ import annotations

from typing import AsyncIterator, Optional

from .common import BackendOutput
from .openai import (
    ChatChoice,
    ChatChunkChoice,
    ChatCompletionChunk,
    ChatCompletionResponse,
    ChatDelta,
    ChatResponseMessage,
    CompletionChoice,
    CompletionResponse,
    Usage,
    now_ts,
)


class ChatDeltaGenerator:
    def __init__(self, request_id: str, model: str, include_usage: bool = False):
        self.id = request_id
        self.model = model
        self.created = now_ts()
        self.include_usage = include_usage
        self.prompt_tokens = 0
        self.completion_tokens = 0
        self.cached_tokens: Optional[int] = None
        self._first = True

    def _chunk(self, delta: ChatDelta, finish: Optional[str] = None) -> ChatCompletionChunk:
        return ChatCompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[ChatChunkChoice(index=0, delta=delta, finish_reason=finish)],
        )

    def on_output(self, out: BackendOutput):
        """Yields zero or more chunks for one backend step."""
        if out.annotations:
            self.prompt_tokens = out.annotations.get("input_tokens", self.prompt_tokens)
            if "cached_tokens" in out.annotations:
                self.cached_tokens = out.annotations["cached_tokens"]
        self.completion_tokens = max(self.completion_tokens, out.cumulative_tokens)
        chunks = []
        if self._first:
            self._first = False
            chunks.append(self._chunk(ChatDelta(role="assistant", content="")))
        if out.text:
            chunks.append(self._chunk(ChatDelta(content=out.text)))
        if out.finish_reason is not None:
            chunks.append(self._chunk(ChatDelta(), finish=out.finish_reason))
            if self.include_usage:
                usage_chunk = ChatCompletionChunk(
                    id=self.id, created=self.created, model=self.model, choices=[],
                    usage=self.usage(),
                )
                chunks.append(usage_chunk)
        return chunks

    def usage(self) -> Usage:
        return Usage(
            prompt_tokens=self.prompt_tokens,
            completion_tokens=self.completion_tokens,
            total_tokens=self.prompt_tokens + self.completion_tokens,
            cached_tokens=self.cached_tokens,
        )


async def aggregate_chat(
    request_id: str, model: str, stream: AsyncIterator[BackendOutput]
) -> ChatCompletionResponse:
    """Non-streaming mode: fold the whole stream into one response."""
    gen = ChatDeltaGenerator(request_id, model)
    text_parts = []
    finish = None
    async for out in stream:
        gen.on_output(out)
        if out.text:
            text_parts.append(out.text)
        if out.finish_reason is not None:
            finish = out.finish_reason
    return ChatCompletionResponse(
        id=request_id,
        created=gen.created,
        model=model,
        choices=[
            ChatChoice(
                index=0,
                message=ChatResponseMessage(content="".join(text_parts)),
                finish_reason=finish or "stop",
            )
        ],
        usage=gen.usage(),
    )


class CompletionDeltaGenerator:
    """Streaming text-completions: each step is a partial CompletionResponse."""

    def __init__(self, request_id: str, model: str, include_usage: bool = False):
        self.id = request_id
        self.model = model
        self.created = now_ts()
        self.include_usage = include_usage
        self.prompt_tokens = 0
        self.completion_tokens = 0
        self.cached_tokens: Optional[int] = None

    def on_output(self, out: BackendOutput):
        if out.annotations:
            self.prompt_tokens = out.annotations.get("input_tokens", self.prompt_tokens)
            if "cached_tokens" in out.annotations:
                self.cached_tokens = out.annotations["cached_tokens"]
        self.completion_tokens = max(self.completion_tokens, out.cumulative_tokens)
        chunks = []
        if out.text or out.finish_reason is not None:
            resp = CompletionResponse(
                id=self.id, created=self.created, model=self.model,
                choices=[CompletionChoice(index=0, text=out.text or "", finish_reason=out.finish_reason)],
            )
            chunks.append(resp)
        if out.finish_reason is not None and self.include_usage:
            chunks.append(
                CompletionResponse(
                    id=self.id, created=self.created, model=self.model, choices=[],
                    usage=self.usage(),
                )
            )
        return chunks

    def usage(self) -> Usage:
        return Usage(
            prompt_tokens=self.prompt_tokens,
            completion_tokens=self.completion_tokens,
            total_tokens=self.prompt_tokens + self.completion_tokens,
            cached_tokens=self.cached_tokens,
        )


async def aggregate_completion(
    request_id: str, model: str, stream: AsyncIterator[BackendOutput], echo_text: str = ""
) -> CompletionResponse:
    gen = CompletionDeltaGenerator(request_id, model)
    parts = [echo_text] if echo_text else []
    finish = None
    async for out in stream:
        gen.on_output(out)
        if out.text:
            parts.append(out.text)
        if out.finish_reason is not None:
            finish = out.finish_reason
    return CompletionResponse(
        id=request_id,
        created=gen.created,
        model=model,
        choices=[CompletionChoice(index=0, text="".join(parts), finish_reason=finish or "stop")],
        usage=gen.usage(),
    )
