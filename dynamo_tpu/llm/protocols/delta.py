"""Delta generators: BackendOutput stream -> OpenAI SSE response objects.

Analog of the reference's streaming delta generator + aggregators
(lib/llm/src/protocols/openai/chat_completions/delta.rs, aggregator.rs).
"""

from __future__ import annotations

from typing import AsyncIterator, Optional

from .common import BackendOutput
from .openai import (
    ChatChoice,
    ChatChunkChoice,
    ChatCompletionChunk,
    ChatCompletionResponse,
    ChatDelta,
    ChatResponseMessage,
    CompletionChoice,
    CompletionResponse,
    Usage,
    now_ts,
)


class ChatDeltaGenerator:
    def __init__(
        self,
        request_id: str,
        model: str,
        include_usage: bool = False,
        reasoning_parser=None,
        tool_parser=None,
        tool_choice=None,
        index: int = 0,
    ):
        self.id = request_id
        self.model = model
        self.index = index
        self.created = now_ts()
        self.include_usage = include_usage
        self.prompt_tokens = 0
        self.completion_tokens = 0
        self.cached_tokens: Optional[int] = None
        self._first = True
        self.reasoning_parser = reasoning_parser
        self.tool_parser = tool_parser
        self._tool_call_count = 0
        # forced tool_choice = the reference jail's Immediate mode
        # (jail.rs JailMode::Immediate): the WHOLE output is a tool call, so
        # every token is jailed from the first and parsed at finish —
        # "required" expects a JSON array of calls, a named choice expects
        # that function's bare argument object
        self._forced: Optional[tuple] = None
        self._forced_buf = ""
        if tool_choice == "required":
            self._forced = ("required", None)
        elif tool_choice == "none":
            # explicit opt-out beats the model card: no tool parsing at all
            self.tool_parser = None
        elif isinstance(tool_choice, dict):
            name = (tool_choice.get("function") or {}).get("name")
            if name:
                self._forced = ("named", name)
        # logprob entries not yet attached to an emitted content chunk (jail
        # holdback / parser diversion can delay the text they belong to)
        self._pending_logprobs: list = []

    def _chunk(
        self,
        delta: ChatDelta,
        finish: Optional[str] = None,
        logprobs: Optional[dict] = None,
    ) -> ChatCompletionChunk:
        return ChatCompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[
                ChatChunkChoice(
                    index=self.index, delta=delta, finish_reason=finish,
                    logprobs=logprobs,
                )
            ],
        )

    def _split_reasoning(self, text: str, flush: bool):
        """(content, reasoning) via the model card's reasoning parser."""
        if self.reasoning_parser is None:
            return text, ""
        ev = self.reasoning_parser.feed(text)
        if flush:
            fin = self.reasoning_parser.flush()
            ev.content += fin.content
            ev.reasoning += fin.reasoning
        return ev.content, ev.reasoning

    def _parse(self, text: str, flush: bool = False):
        """Pipe raw text through the reasoning then tool parsers; returns
        (content, reasoning, tool_calls). Tool markers never appear inside
        reasoning spans, so reasoning splits first."""
        text, reasoning = self._split_reasoning(text, flush)
        tool_calls = []
        if self.tool_parser is not None:
            tev = self.tool_parser.feed(text)
            if flush:
                fin = self.tool_parser.flush()
                tev.content += fin.content
                tev.tool_calls.extend(fin.tool_calls)
            text, tool_calls = tev.content, tev.tool_calls
        for tc in tool_calls:
            tc["index"] = self._tool_call_count
            self._tool_call_count += 1
        return text, reasoning, tool_calls

    def _parse_forced(self):
        """End-of-stream parse of the jailed buffer (reference
        ToolChoiceFormat::{ArrayOfTools, SingleObject}). Malformed output
        degrades to plain content rather than a dropped response."""
        import json as _json

        from ...parsers.tool_calls import _mk_call

        mode, name = self._forced
        text = self._forced_buf.strip()
        self._forced_buf = ""
        try:
            obj = _json.loads(text)
        except Exception:
            return [], text
        if mode == "named":
            return [_mk_call(name, obj)], ""
        calls = obj if isinstance(obj, list) else [obj]
        try:
            return [
                _mk_call(
                    c["name"], c.get("arguments", c.get("parameters", {}))
                )
                for c in calls
            ], ""
        except (KeyError, TypeError):
            return [], text

    def on_output(self, out: BackendOutput):
        """Yields zero or more chunks for one backend step."""
        if out.annotations:
            self.prompt_tokens = out.annotations.get("input_tokens", self.prompt_tokens)
            if "cached_tokens" in out.annotations:
                self.cached_tokens = out.annotations["cached_tokens"]
        self.completion_tokens = max(self.completion_tokens, out.cumulative_tokens)
        chunks = []
        if self._first:
            self._first = False
            chunks.append(self._chunk(ChatDelta(role="assistant", content="")))
        finished = out.finish_reason is not None
        step_entries = list(out.logprob_entries or [])
        if self._forced is not None:
            # immediate jail: reasoning still streams (it is never part of
            # the call JSON — reasoning models wrap the payload in think/
            # channel markup that would break the end-of-stream parse), the
            # rest accumulates silently for the finish-time parse. logprob
            # entries ride along so the malformed-output content fallback
            # still carries every token's logprob
            text, reasoning = self._split_reasoning(out.text or "", finished)
            self._forced_buf += text
            self._pending_logprobs.extend(step_entries)
            step_entries = []
            if reasoning:
                chunks.append(self._chunk(ChatDelta(reasoning_content=reasoning)))
            if not finished:
                return chunks
            tool_calls, content = self._parse_forced()
            for tc in tool_calls:
                tc["index"] = self._tool_call_count
                self._tool_call_count += 1
            if tool_calls:
                # OpenAI logprobs.content covers content tokens only
                self._pending_logprobs = []
            reasoning = ""
        else:
            content, reasoning, tool_calls = self._parse(
                out.text or "", flush=finished
            )
        if reasoning:
            chunks.append(self._chunk(ChatDelta(reasoning_content=reasoning)))
        if content:
            # entries held back earlier (jail/UTF-8 holdback) belong to text
            # that is only now being released as content
            lp = None
            entries = self._pending_logprobs + step_entries
            self._pending_logprobs = []
            if entries:
                lp = {"content": entries}
            chunks.append(self._chunk(ChatDelta(content=content), logprobs=lp))
        elif not (reasoning or tool_calls):
            self._pending_logprobs.extend(step_entries)
        # else: this step's tokens were diverted into reasoning/tool-call
        # fields; OpenAI logprobs.content must only cover content tokens, so
        # their entries are dropped (the engine emits one token per step, so
        # step granularity == token granularity)
        if tool_calls:
            chunks.append(self._chunk(ChatDelta(tool_calls=tool_calls)))
        if finished:
            finish = out.finish_reason
            if self._tool_call_count and finish == "stop":
                finish = "tool_calls"
            lp = None
            if self._pending_logprobs:
                lp = {"content": self._pending_logprobs}
                self._pending_logprobs = []
            chunks.append(self._chunk(ChatDelta(), finish=finish, logprobs=lp))
            if self.include_usage:
                usage_chunk = ChatCompletionChunk(
                    id=self.id, created=self.created, model=self.model, choices=[],
                    usage=self.usage(),
                )
                chunks.append(usage_chunk)
        return chunks

    def usage(self) -> Usage:
        return Usage(
            prompt_tokens=self.prompt_tokens,
            completion_tokens=self.completion_tokens,
            total_tokens=self.prompt_tokens + self.completion_tokens,
            cached_tokens=self.cached_tokens,
        )


async def aggregate_chat(
    request_id: str,
    model: str,
    stream: AsyncIterator[BackendOutput],
    reasoning_parser=None,
    tool_parser=None,
    tool_choice=None,
    index: int = 0,
) -> ChatCompletionResponse:
    """Non-streaming mode: fold the whole stream into one response."""
    gen = ChatDeltaGenerator(
        request_id, model,
        reasoning_parser=reasoning_parser, tool_parser=tool_parser,
        tool_choice=tool_choice, index=index,
    )
    text_parts = []
    reasoning_parts = []
    tool_calls = []
    logprob_entries = []
    finish = None
    async for out in stream:
        for chunk in gen.on_output(out):
            for choice in chunk.choices:
                if choice.delta.content:
                    text_parts.append(choice.delta.content)
                if choice.delta.reasoning_content:
                    reasoning_parts.append(choice.delta.reasoning_content)
                if choice.delta.tool_calls:
                    tool_calls.extend(choice.delta.tool_calls)
                if choice.logprobs and choice.logprobs.get("content"):
                    logprob_entries.extend(choice.logprobs["content"])
                if choice.finish_reason is not None:
                    finish = choice.finish_reason
    return ChatCompletionResponse(
        id=request_id,
        created=gen.created,
        model=model,
        choices=[
            ChatChoice(
                index=index,
                message=ChatResponseMessage(
                    content="".join(text_parts),
                    reasoning_content="".join(reasoning_parts) or None,
                    tool_calls=[
                        {k: v for k, v in tc.items() if k != "index"}
                        for tc in tool_calls
                    ] or None,
                ),
                finish_reason=finish or "stop",
                logprobs={"content": logprob_entries} if logprob_entries else None,
            )
        ],
        usage=gen.usage(),
    )


class CompletionDeltaGenerator:
    """Streaming text-completions: each step is a partial CompletionResponse."""

    def __init__(
        self,
        request_id: str,
        model: str,
        include_usage: bool = False,
        text_offset: int = 0,
        index: int = 0,
    ):
        self.id = request_id
        self.model = model
        self.index = index
        self.created = now_ts()
        self.include_usage = include_usage
        self.prompt_tokens = 0
        self.completion_tokens = 0
        self.cached_tokens: Optional[int] = None
        self._text_offset = text_offset  # chars of response text emitted so far
        # entries from steps whose text was held back (stop-string jail /
        # split UTF-8); they ride on the next emitted chunk
        self._pending_entries: list = []

    def _completion_logprobs(self, entries: list, chunk_text: str) -> Optional[dict]:
        """Legacy completions logprobs block: parallel arrays keyed by token
        string. Offsets are anchored to the *actual* emitted text (cumulative
        token-string lengths, clamped to the chunk) so jail-trimmed or
        re-detokenized text never pushes offsets past the response."""
        if not entries:
            return None
        lp = {"tokens": [], "token_logprobs": [], "top_logprobs": [], "text_offset": []}
        base = self._text_offset
        cum = 0
        for e in entries:
            lp["tokens"].append(e["token"])
            lp["token_logprobs"].append(e["logprob"])
            lp["top_logprobs"].append(
                {alt["token"]: alt["logprob"] for alt in e.get("top_logprobs", [])}
            )
            lp["text_offset"].append(base + min(cum, len(chunk_text)))
            cum += len(e["token"])
        return lp

    def on_output(self, out: BackendOutput):
        if out.annotations:
            self.prompt_tokens = out.annotations.get("input_tokens", self.prompt_tokens)
            if "cached_tokens" in out.annotations:
                self.cached_tokens = out.annotations["cached_tokens"]
        self.completion_tokens = max(self.completion_tokens, out.cumulative_tokens)
        chunks = []
        if out.logprob_entries:
            self._pending_entries.extend(out.logprob_entries)
        if out.text or out.finish_reason is not None:
            text = out.text or ""
            entries, self._pending_entries = self._pending_entries, []
            resp = CompletionResponse(
                id=self.id, created=self.created, model=self.model,
                choices=[CompletionChoice(
                    index=self.index, text=text, finish_reason=out.finish_reason,
                    logprobs=self._completion_logprobs(entries, text),
                )],
            )
            self._text_offset += len(text)
            chunks.append(resp)
        if out.finish_reason is not None and self.include_usage:
            chunks.append(
                CompletionResponse(
                    id=self.id, created=self.created, model=self.model, choices=[],
                    usage=self.usage(),
                )
            )
        return chunks

    def usage(self) -> Usage:
        return Usage(
            prompt_tokens=self.prompt_tokens,
            completion_tokens=self.completion_tokens,
            total_tokens=self.prompt_tokens + self.completion_tokens,
            cached_tokens=self.cached_tokens,
        )


async def aggregate_completion(
    request_id: str, model: str, stream: AsyncIterator[BackendOutput],
    echo_text: str = "", index: int = 0,
) -> CompletionResponse:
    gen = CompletionDeltaGenerator(
        request_id, model, text_offset=len(echo_text), index=index
    )
    parts = [echo_text] if echo_text else []
    finish = None
    logprobs: Optional[dict] = None
    async for out in stream:
        for chunk in gen.on_output(out):
            for choice in chunk.choices:
                if choice.logprobs:
                    if logprobs is None:
                        logprobs = {k: [] for k in choice.logprobs}
                    for k, v in choice.logprobs.items():
                        logprobs[k].extend(v)
        if out.text:
            parts.append(out.text)
        if out.finish_reason is not None:
            finish = out.finish_reason
    return CompletionResponse(
        id=request_id,
        created=gen.created,
        model=model,
        choices=[CompletionChoice(
            index=index, text="".join(parts), finish_reason=finish or "stop",
            logprobs=logprobs,
        )],
        usage=gen.usage(),
    )


# -- multi-choice (n > 1) ----------------------------------------------------
# The reference's delta generator and jail operate per-choice
# (lib/llm/src/protocols/openai/chat_completions/{delta,jail}.rs): each choice
# is an independent engine stream with its own parser/jail state, re-indexed
# into one response. Same here: callers fan one request into n streams and
# these helpers fold them back together.


def merge_usage(gens) -> Usage:
    """One Usage covering all choices: the prompt is billed once, completion
    tokens sum across choices (OpenAI semantics for n>1)."""
    prompt = max((g.prompt_tokens for g in gens), default=0)
    cached = next((g.cached_tokens for g in gens if g.cached_tokens is not None), None)
    completion = sum(g.completion_tokens for g in gens)
    return Usage(
        prompt_tokens=prompt,
        completion_tokens=completion,
        total_tokens=prompt + completion,
        cached_tokens=cached,
    )


async def aggregate_chat_multi(
    request_id: str,
    model: str,
    streams,
    reasoning_parser_factory=None,
    tool_parser_factory=None,
    tool_choice=None,
) -> ChatCompletionResponse:
    """Aggregate n independent streams into one multi-choice response.

    Parser *factories* (not instances): streaming parsers are stateful, so
    every choice needs its own."""
    import asyncio

    results = await asyncio.gather(*[
        aggregate_chat(
            request_id, model, s,
            reasoning_parser=reasoning_parser_factory() if reasoning_parser_factory else None,
            tool_parser=tool_parser_factory() if tool_parser_factory else None,
            tool_choice=tool_choice,
            index=i,
        )
        for i, s in enumerate(streams)
    ])
    base = results[0]
    prompt = max(r.usage.prompt_tokens for r in results if r.usage)
    completion = sum(r.usage.completion_tokens for r in results if r.usage)
    cached = next(
        (r.usage.cached_tokens for r in results
         if r.usage and r.usage.cached_tokens is not None),
        None,
    )
    return ChatCompletionResponse(
        id=request_id,
        created=base.created,
        model=model,
        choices=[r.choices[0] for r in results],
        usage=Usage(
            prompt_tokens=prompt, completion_tokens=completion,
            total_tokens=prompt + completion, cached_tokens=cached,
        ),
    )


async def aggregate_completion_multi(
    request_id: str, model: str, streams, echo_text: str = ""
) -> CompletionResponse:
    import asyncio

    results = await asyncio.gather(*[
        aggregate_completion(request_id, model, s, echo_text, index=i)
        for i, s in enumerate(streams)
    ])
    base = results[0]
    prompt = max(r.usage.prompt_tokens for r in results if r.usage)
    completion = sum(r.usage.completion_tokens for r in results if r.usage)
    cached = next(
        (r.usage.cached_tokens for r in results
         if r.usage and r.usage.cached_tokens is not None),
        None,
    )
    return CompletionResponse(
        id=request_id,
        created=base.created,
        model=model,
        choices=[r.choices[0] for r in results],
        usage=Usage(
            prompt_tokens=prompt, completion_tokens=completion,
            total_tokens=prompt + completion, cached_tokens=cached,
        ),
    )
