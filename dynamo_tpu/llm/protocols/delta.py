"""Delta generators: BackendOutput stream -> OpenAI SSE response objects.

Analog of the reference's streaming delta generator + aggregators
(lib/llm/src/protocols/openai/chat_completions/delta.rs, aggregator.rs).
"""

from __future__ import annotations

from typing import AsyncIterator, Optional

from .common import BackendOutput
from .openai import (
    ChatChoice,
    ChatChunkChoice,
    ChatCompletionChunk,
    ChatCompletionResponse,
    ChatDelta,
    ChatResponseMessage,
    CompletionChoice,
    CompletionResponse,
    Usage,
    now_ts,
)


class ChatDeltaGenerator:
    def __init__(
        self,
        request_id: str,
        model: str,
        include_usage: bool = False,
        reasoning_parser=None,
        tool_parser=None,
    ):
        self.id = request_id
        self.model = model
        self.created = now_ts()
        self.include_usage = include_usage
        self.prompt_tokens = 0
        self.completion_tokens = 0
        self.cached_tokens: Optional[int] = None
        self._first = True
        self.reasoning_parser = reasoning_parser
        self.tool_parser = tool_parser
        self._tool_call_count = 0

    def _chunk(self, delta: ChatDelta, finish: Optional[str] = None) -> ChatCompletionChunk:
        return ChatCompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[ChatChunkChoice(index=0, delta=delta, finish_reason=finish)],
        )

    def _parse(self, text: str, flush: bool = False):
        """Pipe raw text through the reasoning then tool parsers; returns
        (content, reasoning, tool_calls). Tool markers never appear inside
        reasoning spans, so reasoning splits first."""
        reasoning = ""
        if self.reasoning_parser is not None:
            ev = self.reasoning_parser.feed(text)
            if flush:
                fin = self.reasoning_parser.flush()
                ev.content += fin.content
                ev.reasoning += fin.reasoning
            text, reasoning = ev.content, ev.reasoning
        tool_calls = []
        if self.tool_parser is not None:
            tev = self.tool_parser.feed(text)
            if flush:
                fin = self.tool_parser.flush()
                tev.content += fin.content
                tev.tool_calls.extend(fin.tool_calls)
            text, tool_calls = tev.content, tev.tool_calls
        for tc in tool_calls:
            tc["index"] = self._tool_call_count
            self._tool_call_count += 1
        return text, reasoning, tool_calls

    def on_output(self, out: BackendOutput):
        """Yields zero or more chunks for one backend step."""
        if out.annotations:
            self.prompt_tokens = out.annotations.get("input_tokens", self.prompt_tokens)
            if "cached_tokens" in out.annotations:
                self.cached_tokens = out.annotations["cached_tokens"]
        self.completion_tokens = max(self.completion_tokens, out.cumulative_tokens)
        chunks = []
        if self._first:
            self._first = False
            chunks.append(self._chunk(ChatDelta(role="assistant", content="")))
        finished = out.finish_reason is not None
        content, reasoning, tool_calls = self._parse(out.text or "", flush=finished)
        if reasoning:
            chunks.append(self._chunk(ChatDelta(reasoning_content=reasoning)))
        if content:
            chunks.append(self._chunk(ChatDelta(content=content)))
        if tool_calls:
            chunks.append(self._chunk(ChatDelta(tool_calls=tool_calls)))
        if finished:
            finish = out.finish_reason
            if self._tool_call_count and finish == "stop":
                finish = "tool_calls"
            chunks.append(self._chunk(ChatDelta(), finish=finish))
            if self.include_usage:
                usage_chunk = ChatCompletionChunk(
                    id=self.id, created=self.created, model=self.model, choices=[],
                    usage=self.usage(),
                )
                chunks.append(usage_chunk)
        return chunks

    def usage(self) -> Usage:
        return Usage(
            prompt_tokens=self.prompt_tokens,
            completion_tokens=self.completion_tokens,
            total_tokens=self.prompt_tokens + self.completion_tokens,
            cached_tokens=self.cached_tokens,
        )


async def aggregate_chat(
    request_id: str,
    model: str,
    stream: AsyncIterator[BackendOutput],
    reasoning_parser=None,
    tool_parser=None,
) -> ChatCompletionResponse:
    """Non-streaming mode: fold the whole stream into one response."""
    gen = ChatDeltaGenerator(
        request_id, model,
        reasoning_parser=reasoning_parser, tool_parser=tool_parser,
    )
    text_parts = []
    reasoning_parts = []
    tool_calls = []
    finish = None
    async for out in stream:
        for chunk in gen.on_output(out):
            for choice in chunk.choices:
                if choice.delta.content:
                    text_parts.append(choice.delta.content)
                if choice.delta.reasoning_content:
                    reasoning_parts.append(choice.delta.reasoning_content)
                if choice.delta.tool_calls:
                    tool_calls.extend(choice.delta.tool_calls)
                if choice.finish_reason is not None:
                    finish = choice.finish_reason
    return ChatCompletionResponse(
        id=request_id,
        created=gen.created,
        model=model,
        choices=[
            ChatChoice(
                index=0,
                message=ChatResponseMessage(
                    content="".join(text_parts),
                    reasoning_content="".join(reasoning_parts) or None,
                    tool_calls=[
                        {k: v for k, v in tc.items() if k != "index"}
                        for tc in tool_calls
                    ] or None,
                ),
                finish_reason=finish or "stop",
            )
        ],
        usage=gen.usage(),
    )


class CompletionDeltaGenerator:
    """Streaming text-completions: each step is a partial CompletionResponse."""

    def __init__(self, request_id: str, model: str, include_usage: bool = False):
        self.id = request_id
        self.model = model
        self.created = now_ts()
        self.include_usage = include_usage
        self.prompt_tokens = 0
        self.completion_tokens = 0
        self.cached_tokens: Optional[int] = None

    def on_output(self, out: BackendOutput):
        if out.annotations:
            self.prompt_tokens = out.annotations.get("input_tokens", self.prompt_tokens)
            if "cached_tokens" in out.annotations:
                self.cached_tokens = out.annotations["cached_tokens"]
        self.completion_tokens = max(self.completion_tokens, out.cumulative_tokens)
        chunks = []
        if out.text or out.finish_reason is not None:
            resp = CompletionResponse(
                id=self.id, created=self.created, model=self.model,
                choices=[CompletionChoice(index=0, text=out.text or "", finish_reason=out.finish_reason)],
            )
            chunks.append(resp)
        if out.finish_reason is not None and self.include_usage:
            chunks.append(
                CompletionResponse(
                    id=self.id, created=self.created, model=self.model, choices=[],
                    usage=self.usage(),
                )
            )
        return chunks

    def usage(self) -> Usage:
        return Usage(
            prompt_tokens=self.prompt_tokens,
            completion_tokens=self.completion_tokens,
            total_tokens=self.prompt_tokens + self.completion_tokens,
            cached_tokens=self.cached_tokens,
        )


async def aggregate_completion(
    request_id: str, model: str, stream: AsyncIterator[BackendOutput], echo_text: str = ""
) -> CompletionResponse:
    gen = CompletionDeltaGenerator(request_id, model)
    parts = [echo_text] if echo_text else []
    finish = None
    async for out in stream:
        gen.on_output(out)
        if out.text:
            parts.append(out.text)
        if out.finish_reason is not None:
            finish = out.finish_reason
    return CompletionResponse(
        id=request_id,
        created=gen.created,
        model=model,
        choices=[CompletionChoice(index=0, text="".join(parts), finish_reason=finish or "stop")],
        usage=gen.usage(),
    )
