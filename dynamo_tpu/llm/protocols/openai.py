"""OpenAI-compatible API types (chat completions, completions, embeddings).

Analog of the reference's protocol layer (lib/llm/src/protocols/openai/ and
the vendored async-openai types). Pydantic models validate user input at the
HTTP edge; everything internal converts to the compact dataclasses in
``common.py``.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field, model_validator


class _Lenient(BaseModel):
    model_config = ConfigDict(extra="allow")


# ---------------------------------------------------------------------------
# Requests
# ---------------------------------------------------------------------------


class ChatMessage(_Lenient):
    role: Literal["system", "user", "assistant", "tool", "developer"]
    content: Optional[Union[str, List[Dict[str, Any]]]] = None
    name: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None
    tool_call_id: Optional[str] = None

    def text_content(self) -> str:
        if isinstance(self.content, str):
            return self.content
        if isinstance(self.content, list):
            return "".join(
                part.get("text", "") for part in self.content if part.get("type") == "text"
            )
        return ""


class StreamOptions(_Lenient):
    include_usage: bool = False


class SamplingFields(_Lenient):
    """Fields shared by chat + text completion requests."""

    max_tokens: Optional[int] = Field(default=None, ge=1)
    max_completion_tokens: Optional[int] = Field(default=None, ge=1)
    temperature: Optional[float] = Field(default=None, ge=0.0, le=2.0)
    top_p: Optional[float] = Field(default=None, gt=0.0, le=1.0)
    top_k: Optional[int] = Field(default=None, ge=-1)
    min_p: Optional[float] = Field(default=None, ge=0.0, le=1.0)
    seed: Optional[int] = None
    stop: Optional[Union[str, List[str]]] = None
    frequency_penalty: Optional[float] = Field(default=None, ge=-2.0, le=2.0)
    presence_penalty: Optional[float] = Field(default=None, ge=-2.0, le=2.0)
    repetition_penalty: Optional[float] = Field(default=None, gt=0.0)
    # n>1 fans the request into n independent engine streams with per-choice
    # delta/jail state (reference delta.rs/jail.rs are per-choice)
    n: int = Field(default=1, ge=1, le=16)
    logprobs: Optional[Union[bool, int]] = None
    top_logprobs: Optional[int] = Field(default=None, ge=0, le=20)
    ignore_eos: Optional[bool] = None  # extension, matches reference nvext
    # SLA class extension (runtime/slo.py): named class ("interactive" /
    # "standard" / "batch" / DTPU_SLA_CLASSES); also accepted as the
    # x-dtpu-sla header — the body field wins when both are set
    sla: Optional[str] = None
    # guided decoding extensions (reference nvext guided_* fields,
    # lib/llm/src/protocols/openai/common_ext.rs:175-219): at most one may
    # be set; chat requests can also use response_format json_schema /
    # json_object (mapped in llm/preprocessor.py)
    guided_regex: Optional[str] = None
    guided_json: Optional[Union[Dict[str, Any], str]] = None
    guided_choice: Optional[List[str]] = None

    @model_validator(mode="after")
    def _guided_exclusive(self) -> "SamplingFields":
        set_ = [
            n for n in ("guided_regex", "guided_json", "guided_choice")
            if getattr(self, n) is not None
        ]
        if len(set_) > 1:
            raise ValueError(f"only one guided option may be set, got {set_}")
        return self

    @model_validator(mode="after")
    def _logprob_bounds(self) -> "SamplingFields":
        # completions-style integer logprobs: same 0..20 window the chat
        # top_logprobs field gets from its own Field constraint — reject
        # instead of silently clamping (engine returns up to 20 rows)
        if isinstance(self.logprobs, int) and not isinstance(self.logprobs, bool):
            if not 0 <= self.logprobs <= 20:
                raise ValueError("logprobs must be between 0 and 20")
        if self.top_logprobs is not None and not self.logprobs:
            raise ValueError("top_logprobs requires logprobs to be set")
        return self

    def stop_list(self) -> List[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)

    def effective_max_tokens(self) -> Optional[int]:
        return self.max_completion_tokens or self.max_tokens


class ChatAudioParams(_Lenient):
    """Request-side audio output options (reference async-openai
    ChatCompletionAudio types): which voice/format an audio-capable model
    should answer in."""

    voice: str = "alloy"
    format: Literal["wav", "mp3", "flac", "opus", "pcm16"] = "wav"


class ChatCompletionRequest(SamplingFields):
    model: str
    messages: List[ChatMessage]
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    tools: Optional[List[Dict[str, Any]]] = None
    tool_choice: Optional[Union[str, Dict[str, Any]]] = None
    response_format: Optional[Dict[str, Any]] = None
    user: Optional[str] = None
    # audio I/O (reference async-openai audio types): accepted and validated;
    # serving them requires an audio-capable model card (none ships yet —
    # requests against text models get a clear 400, not silent drop)
    modalities: Optional[List[Literal["text", "audio"]]] = None
    audio: Optional[ChatAudioParams] = None
    # routing extensions (reference nvext.rs): pin a worker / annotate
    routing: Optional[Dict[str, Any]] = None
    # multi-LoRA: adapter name to apply (lora/adapters.py; reference routes
    # adapter-named models via its LoraRoutingTable)
    lora: Optional[str] = None
    # named logits processors to enable (logits_processing/)
    logits_processors: Optional[List[str]] = None

    @model_validator(mode="after")
    def _non_empty(self) -> "ChatCompletionRequest":
        if not self.messages:
            raise ValueError("messages must not be empty")
        return self


class CompletionRequest(SamplingFields):
    model: str
    prompt: Union[str, List[str], List[int], List[List[int]]]
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    echo: bool = False
    user: Optional[str] = None
    routing: Optional[Dict[str, Any]] = None
    lora: Optional[str] = None
    logits_processors: Optional[List[str]] = None


class ResponsesRequest(_Lenient):
    """/v1/responses (reference openai.rs:1142 handler_responses): converted
    to a chat request internally, text inputs only."""

    model: str
    # string, or a list of {role, content} items (content: string or
    # [{type: "input_text"/"output_text"/"text", text}] parts)
    input: Union[str, List[Dict[str, Any]]]
    instructions: Optional[str] = None
    max_output_tokens: Optional[int] = Field(default=None, ge=1)
    temperature: Optional[float] = Field(default=None, ge=0.0, le=2.0)
    top_p: Optional[float] = Field(default=None, gt=0.0, le=1.0)
    stream: bool = False
    user: Optional[str] = None
    # SLA class extension (runtime/slo.py), same semantics as the chat field
    sla: Optional[str] = None

    def to_chat(self) -> "ChatCompletionRequest":
        messages: List[ChatMessage] = []
        if self.instructions:
            messages.append(ChatMessage(role="system", content=self.instructions))
        if isinstance(self.input, str):
            messages.append(ChatMessage(role="user", content=self.input))
        else:
            for item in self.input:
                content = item.get("content", "")
                if isinstance(content, list):
                    content = "".join(
                        p.get("text", "") for p in content
                        if p.get("type") in ("input_text", "output_text", "text")
                    )
                role = item.get("role", "user")
                if role not in ("system", "user", "assistant", "tool", "developer"):
                    role = "user"
                messages.append(ChatMessage(role=role, content=content))
        return ChatCompletionRequest(
            model=self.model, messages=messages,
            max_tokens=self.max_output_tokens,
            temperature=self.temperature, top_p=self.top_p,
            stream=self.stream, user=self.user,
        )


class ResponseOutputText(BaseModel):
    type: Literal["output_text"] = "output_text"
    text: str = ""
    annotations: List[Any] = []


class ResponseMessage(BaseModel):
    id: str
    type: Literal["message"] = "message"
    role: str = "assistant"
    status: str = "completed"
    content: List[ResponseOutputText]


class ResponseUsage(BaseModel):
    input_tokens: int = 0
    output_tokens: int = 0
    total_tokens: int = 0


class ResponseObject(BaseModel):
    id: str
    object: Literal["response"] = "response"
    created_at: int
    status: str = "completed"
    model: str
    output: List[ResponseMessage]
    usage: Optional[ResponseUsage] = None

    @property
    def output_text(self) -> str:
        return "".join(
            part.text for msg in self.output for part in msg.content
        )


class SpeechRequest(_Lenient):
    """/v1/audio/speech wire type (reference async-openai CreateSpeechRequest
    — the vendored fork carries audio types; serving needs a TTS model)."""

    model: str
    input: str
    voice: str = "alloy"
    response_format: Literal["wav", "mp3", "flac", "opus", "pcm16"] = "wav"
    speed: float = Field(default=1.0, ge=0.25, le=4.0)


class TranscriptionRequest(_Lenient):
    """/v1/audio/transcriptions wire type (async-openai
    CreateTranscriptionRequest; file rides as base64 in the JSON shape)."""

    model: str
    file: Optional[str] = None  # base64 audio payload
    language: Optional[str] = None
    prompt: Optional[str] = None
    response_format: Literal["json", "text", "srt", "verbose_json", "vtt"] = "json"
    temperature: float = Field(default=0.0, ge=0.0, le=1.0)


class TranscriptionResponse(BaseModel):
    text: str
    language: Optional[str] = None
    duration: Optional[float] = None


class EmbeddingRequest(_Lenient):
    model: str
    input: Union[str, List[str], List[int], List[List[int]]]
    encoding_format: Literal["float", "base64"] = "float"
    dimensions: Optional[int] = Field(default=None, ge=1)


# ---------------------------------------------------------------------------
# Responses
# ---------------------------------------------------------------------------


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0
    # extension: prefix-cache hit accounting (reference LLMMetricAnnotation)
    cached_tokens: Optional[int] = None


class ChatAudioResponse(BaseModel):
    """Response-side audio payload (async-openai ChatCompletionAudio):
    base64 data + transcript, with an expiry for the audio id."""

    id: str
    data: Optional[str] = None       # base64-encoded audio
    transcript: Optional[str] = None
    expires_at: Optional[int] = None


class ChatResponseMessage(BaseModel):
    role: str = "assistant"
    content: Optional[str] = None
    reasoning_content: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None
    audio: Optional[ChatAudioResponse] = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatResponseMessage
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class ChatCompletionResponse(BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int
    model: str
    choices: List[ChatChoice]
    usage: Optional[Usage] = None


class ChatDelta(BaseModel):
    role: Optional[str] = None
    content: Optional[str] = None
    reasoning_content: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None
    audio: Optional[Dict[str, Any]] = None  # streamed audio chunk fields


class ChatChunkChoice(BaseModel):
    index: int = 0
    delta: ChatDelta
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int
    model: str
    choices: List[ChatChunkChoice]
    usage: Optional[Usage] = None


class CompletionChoice(BaseModel):
    index: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class CompletionResponse(BaseModel):
    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int
    model: str
    choices: List[CompletionChoice]
    usage: Optional[Usage] = None


class EmbeddingData(BaseModel):
    object: Literal["embedding"] = "embedding"
    index: int
    # list of floats, or base64 of little-endian float32 when the request
    # asked for encoding_format="base64"
    embedding: Union[List[float], str]


class EmbeddingResponse(BaseModel):
    object: Literal["list"] = "list"
    data: List[EmbeddingData]
    model: str
    usage: Optional[Usage] = None


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = 0
    owned_by: str = "dynamo-tpu"


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: List[ModelInfo]


def new_request_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def now_ts() -> int:
    return int(time.time())
