"""Internal wire types between frontend pipeline and workers.

Analog of the reference's PreprocessedRequest / BackendOutput / LLMEngineOutput
(lib/llm/src/protocols/common/llm_backend.rs). These are msgpack-friendly
dicts-with-codecs: the request plane carries plain objects.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

FINISH_STOP = "stop"
FINISH_LENGTH = "length"
FINISH_CANCELLED = "cancelled"
FINISH_ERROR = "error"


@dataclasses.dataclass
class StopConditions:
    max_tokens: Optional[int] = None
    stop_strings: List[str] = dataclasses.field(default_factory=list)
    stop_token_ids: List[int] = dataclasses.field(default_factory=list)
    ignore_eos: bool = False
    min_tokens: int = 0

    def to_obj(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "StopConditions":
        return cls(**obj)


@dataclasses.dataclass
class SamplingOptions:
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = -1
    min_p: float = 0.0
    seed: Optional[int] = None
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    repetition_penalty: float = 1.0
    logprobs: int = 0       # number of top-logprob alternatives to return
    # logprobs can be "on" with zero alternatives (chat logprobs:true without
    # top_logprobs; completions logprobs:0) — the chosen token's logprob is
    # still returned, so a separate enable flag is needed
    want_logprobs: bool = False
    # guided decoding (dynamo_tpu/guided; reference GuidedDecodingOptions,
    # lib/llm/src/protocols/common.rs:336): {"kind": "regex"|"json"|
    # "choice"|"json_object", "value": ...} — compiled to on-device token
    # masks by the engine
    guided: Optional[Dict[str, Any]] = None

    def to_obj(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "SamplingOptions":
        return cls(**obj)


@dataclasses.dataclass
class PreprocessedRequest:
    """What actually travels to a worker: token ids + generation config."""

    request_id: str
    model: str
    token_ids: List[int]
    stop: StopConditions = dataclasses.field(default_factory=StopConditions)
    sampling: SamplingOptions = dataclasses.field(default_factory=SamplingOptions)
    # routing annotations: estimated prefix-cache overlap etc.
    annotations: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # disaggregation: transfer metadata injected between prefill and decode
    kv_transfer: Optional[Dict[str, Any]] = None
    # request migration: tokens already generated before a worker died
    prior_token_ids: List[int] = dataclasses.field(default_factory=list)

    def to_obj(self) -> Dict[str, Any]:
        return {
            "request_id": self.request_id,
            "model": self.model,
            "token_ids": self.token_ids,
            "stop": self.stop.to_obj(),
            "sampling": self.sampling.to_obj(),
            "annotations": self.annotations,
            "kv_transfer": self.kv_transfer,
            "prior_token_ids": self.prior_token_ids,
        }

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "PreprocessedRequest":
        return cls(
            request_id=obj["request_id"],
            model=obj["model"],
            token_ids=list(obj["token_ids"]),
            stop=StopConditions.from_obj(obj.get("stop", {})),
            sampling=SamplingOptions.from_obj(obj.get("sampling", {})),
            annotations=obj.get("annotations") or {},
            kv_transfer=obj.get("kv_transfer"),
            prior_token_ids=list(obj.get("prior_token_ids") or []),
        )


@dataclasses.dataclass
class BackendOutput:
    """One streamed step from a worker: newly generated token ids (+ text if
    the worker detokenizes), cumulative counts, and finish state."""

    token_ids: List[int] = dataclasses.field(default_factory=list)
    text: Optional[str] = None
    finish_reason: Optional[str] = None
    cumulative_tokens: int = 0
    # logprob of each token in token_ids (parallel list), optional
    logprobs: Optional[List[float]] = None
    top_logprobs: Optional[List[Dict[int, float]]] = None
    # detokenized OpenAI-shaped logprob entries, parallel to token_ids; built
    # by the worker-side Backend (it owns the tokenizer):
    # {token, logprob, bytes, top_logprobs: [{token, logprob, bytes}, ...]}
    logprob_entries: Optional[List[Dict[str, Any]]] = None
    # metrics annotations (first chunk): cached_tokens, input_tokens, and the
    # router-stamped worker_id echoed back for flight-recorder attribution;
    # error-finish frames carry "error" (the reason) and optionally
    # "evacuation" (a kv_transfer plan for the retry). The key namespace is
    # a declared contract (tools/analysis/contracts.py request-annotations).
    annotations: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # disaggregation: prefill worker returns kv transfer params here
    kv_transfer: Optional[Dict[str, Any]] = None

    def to_obj(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"token_ids": self.token_ids, "cum": self.cumulative_tokens}
        if self.text is not None:
            out["text"] = self.text
        if self.finish_reason is not None:
            out["finish"] = self.finish_reason
        if self.logprobs is not None:
            out["logprobs"] = self.logprobs
        if self.top_logprobs is not None:
            out["top_logprobs"] = [
                {str(k): v for k, v in d.items()} for d in self.top_logprobs
            ]
        if self.logprob_entries is not None:
            out["logprob_entries"] = self.logprob_entries
        if self.annotations:
            out["ann"] = self.annotations
        if self.kv_transfer is not None:
            out["kv_transfer"] = self.kv_transfer
        return out

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "BackendOutput":
        return cls(
            token_ids=list(obj.get("token_ids", [])),
            text=obj.get("text"),
            finish_reason=obj.get("finish"),
            cumulative_tokens=obj.get("cum", 0),
            logprobs=obj.get("logprobs"),
            top_logprobs=[
                {int(k): v for k, v in d.items()} for d in obj["top_logprobs"]
            ]
            if obj.get("top_logprobs")
            else None,
            logprob_entries=obj.get("logprob_entries"),
            annotations=obj.get("ann") or {},
            kv_transfer=obj.get("kv_transfer"),
        )
