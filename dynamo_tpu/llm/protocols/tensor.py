"""Generic tensor inference protocol: named tensors in, named tensors out.

Analog of the reference's tensor protocol (lib/llm/src/protocols/tensor.rs +
grpc/service/tensor.rs): models registered with model_type "tensor" skip the
tokenizer/OpenAI machinery entirely — the KServe frontend converts
ModelInferRequest tensors to this wire form, the worker's handler computes on
numpy arrays, and the response converts back (including raw byte contents
when the client asked with raw_input_contents).

Wire form (msgpack over the request plane; bytes ride natively):
    request : {"id": str, "model": str,
               "tensors": [{"name", "datatype", "shape", "data": bytes}]}
    response: one item of the same shape under key "tensors"
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

import numpy as np

# KServe v2 datatype name -> numpy dtype (BYTES handled separately)
DTYPES = {
    "BOOL": np.bool_,
    "INT8": np.int8, "INT16": np.int16, "INT32": np.int32, "INT64": np.int64,
    "UINT8": np.uint8, "UINT16": np.uint16, "UINT32": np.uint32,
    "UINT64": np.uint64,
    "FP16": np.float16, "FP32": np.float32, "FP64": np.float64,
}
_NP_TO_NAME = {np.dtype(v).name: k for k, v in DTYPES.items()}


@dataclasses.dataclass
class Tensor:
    name: str
    datatype: str          # KServe v2 name (FP32, INT64, BYTES, ...)
    shape: List[int]
    data: bytes            # C-order payload; BYTES = 4-byte-LE-len-prefixed

    @classmethod
    def from_numpy(cls, name: str, arr: np.ndarray) -> "Tensor":
        dt = _NP_TO_NAME.get(arr.dtype.name)
        if dt is None:
            raise ValueError(f"unsupported tensor dtype {arr.dtype}")
        return cls(name, dt, list(arr.shape), np.ascontiguousarray(arr).tobytes())

    @classmethod
    def from_bytes_list(cls, name: str, items: List[bytes],
                        shape: List[int]) -> "Tensor":
        out = b"".join(
            len(b).to_bytes(4, "little") + b for b in items
        )
        return cls(name, "BYTES", shape, out)

    def to_numpy(self) -> np.ndarray:
        if self.datatype == "BYTES":
            raise ValueError("BYTES tensors: use to_bytes_list()")
        dt = DTYPES.get(self.datatype)
        if dt is None:
            raise ValueError(f"unsupported tensor datatype {self.datatype!r}")
        return np.frombuffer(self.data, dtype=dt).reshape(self.shape)

    def to_bytes_list(self) -> List[bytes]:
        out, i = [], 0
        while i + 4 <= len(self.data):
            n = int.from_bytes(self.data[i:i + 4], "little")
            out.append(self.data[i + 4:i + 4 + n])
            i += 4 + n
        return out

    def to_obj(self) -> Dict[str, Any]:
        return {
            "name": self.name, "datatype": self.datatype,
            "shape": list(self.shape), "data": self.data,
        }

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "Tensor":
        return cls(obj["name"], obj["datatype"], list(obj["shape"]),
                   obj.get("data", b""))


@dataclasses.dataclass
class TensorRequest:
    request_id: str
    model: str
    tensors: List[Tensor] = dataclasses.field(default_factory=list)
    parameters: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def tensor(self, name: str) -> Tensor:
        for t in self.tensors:
            if t.name == name:
                return t
        raise KeyError(name)

    def to_obj(self) -> Dict[str, Any]:
        return {
            "op": "tensor",
            "id": self.request_id, "model": self.model,
            "tensors": [t.to_obj() for t in self.tensors],
            "parameters": self.parameters,
        }

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "TensorRequest":
        op = obj.get("op", "tensor")
        if op != "tensor":
            # the discriminator to_obj writes: a mis-routed request-plane
            # payload (chat/embed/image) must fail loudly here, not decode
            # into an empty tensor list
            raise ValueError(f"not a tensor request: op={op!r}")
        return cls(
            request_id=obj.get("id", ""), model=obj.get("model", ""),
            tensors=[Tensor.from_obj(t) for t in obj.get("tensors", [])],
            parameters=obj.get("parameters") or {},
        )


@dataclasses.dataclass
class TensorResponse:
    tensors: List[Tensor] = dataclasses.field(default_factory=list)
    error: str = ""

    def to_obj(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"tensors": [t.to_obj() for t in self.tensors]}
        if self.error:
            out["error"] = self.error
        return out

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "TensorResponse":
        return cls(
            tensors=[Tensor.from_obj(t) for t in obj.get("tensors", [])],
            error=obj.get("error", ""),
        )
