"""Model hub ingestion: model reference -> local snapshot directory.

Analog of the reference's hub.rs (lib/llm/src/hub.rs): `from_hf("org/name")`
resolves a model reference to a directory holding config.json + safetensors +
tokenizer files, in precedence order:

  1. an existing local directory (used as-is);
  2. the HuggingFace cache layout under $HF_HOME (or DTPU_HUB_CACHE):
     ``hub/models--{org}--{name}/snapshots/{revision}/`` — the revision comes
     from ``refs/main`` when present, else the newest snapshot;
  3. a live download via huggingface_hub.snapshot_download, gated on
     DTPU_HUB_OFFLINE (zero-egress deployments set it and never dial out —
     the reference gates the same way on HF_HUB_OFFLINE).

Everything downstream (engine/weights.py safetensors -> sharded device_put,
llm/tokenizer.py chat template) consumes the returned directory, so CLI
flags accept either a path or a hub reference transparently.
"""

from __future__ import annotations

import os
from typing import Optional

from ..runtime.logging import get_logger

log = get_logger("llm.hub")


def hub_cache_dir() -> str:
    """The HF hub cache root, honoring the standard env precedence."""
    if os.environ.get("DTPU_HUB_CACHE"):
        return os.environ["DTPU_HUB_CACHE"]
    if os.environ.get("HF_HUB_CACHE"):
        return os.environ["HF_HUB_CACHE"]
    hf_home = os.environ.get("HF_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache", "huggingface"
    )
    return os.path.join(hf_home, "hub")


def _snapshot_from_cache(ref: str, cache: str) -> Optional[str]:
    """models--org--name/snapshots/<rev> for ``org/name``, or None."""
    repo_dir = os.path.join(cache, "models--" + ref.replace("/", "--"))
    snaps = os.path.join(repo_dir, "snapshots")
    if not os.path.isdir(snaps):
        return None
    rev: Optional[str] = None
    main_ref = os.path.join(repo_dir, "refs", "main")
    if os.path.isfile(main_ref):
        with open(main_ref) as f:
            rev = f.read().strip()
    if rev and os.path.isdir(os.path.join(snaps, rev)):
        return os.path.join(snaps, rev)
    revs = sorted(
        (os.path.getmtime(os.path.join(snaps, d)), d)
        for d in os.listdir(snaps)
        if os.path.isdir(os.path.join(snaps, d))
    )
    return os.path.join(snaps, revs[-1][1]) if revs else None


def _offline() -> bool:
    return os.environ.get(
        "DTPU_HUB_OFFLINE", os.environ.get("HF_HUB_OFFLINE", "0")
    ) not in ("0", "", "false")


def resolve_model_path(ref: str, cache_dir: Optional[str] = None) -> str:
    """Model reference (path or org/name) -> local snapshot directory.

    Raises FileNotFoundError with an actionable message when the reference
    is neither a directory, nor cached, nor downloadable (offline)."""
    if os.path.isdir(ref):
        return ref
    cache = cache_dir or hub_cache_dir()
    snap = _snapshot_from_cache(ref, cache)
    if snap is not None:
        log.info("resolved %s from hub cache: %s", ref, snap)
        return snap
    if not _offline():
        try:
            from huggingface_hub import snapshot_download  # optional dep

            path = snapshot_download(ref, cache_dir=cache)
            log.info("downloaded %s -> %s", ref, path)
            return path
        except ImportError:
            raise FileNotFoundError(
                f"model {ref!r}: not a directory, not in hub cache {cache}, "
                f"and huggingface_hub is not installed — install it to "
                f"download, or pre-populate the cache / pass a local path"
            ) from None
        except Exception as e:
            raise FileNotFoundError(
                f"model {ref!r}: not a directory, not in hub cache {cache}, "
                f"and download failed: {e}"
            ) from e
    raise FileNotFoundError(
        f"model {ref!r}: not a directory and not in hub cache {cache} "
        f"(offline mode — pre-populate the cache or pass a local path)"
    )
