"""Worker-side Backend operator: detokenization + stop-condition handling.

Analog of the reference's Backend operator (lib/llm/src/backend.rs:1-16) plus
the stop-string "jail" that holds back text which might still complete a stop
sequence (reference: lib/llm/src/protocols/openai/chat_completions/jail.rs).

Wraps a token engine: takes PreprocessedRequest objects off the request plane,
streams BackendOutput objects back with incremental text attached and stop
strings enforced exactly (the emitted text never contains the stop string).
"""

from __future__ import annotations

from typing import Any, AsyncIterator, List, Optional, Tuple

from ..runtime.engine import AsyncEngine, Context
from ..runtime.logging import get_logger
from .protocols.common import FINISH_STOP, BackendOutput, PreprocessedRequest
from .tokenizer import DecodeStream, Tokenizer

log = get_logger("llm.backend")


class StopStringJail:
    """Text-side stop handling with partial-match holdback."""

    def __init__(self, stop_strings: List[str]):
        self._stops = [s for s in stop_strings if s]
        self._held = ""
        self._max_len = max((len(s) for s in self._stops), default=0)

    def push(self, delta: str) -> Tuple[str, bool]:
        """Returns (text safe to emit, hit_stop)."""
        if not self._stops:
            return delta, False
        buf = self._held + delta
        # full match anywhere in the buffer -> emit up to match, stop
        best: Optional[int] = None
        for s in self._stops:
            idx = buf.find(s)
            if idx != -1 and (best is None or idx < best):
                best = idx
        if best is not None:
            self._held = ""
            return buf[:best], True
        # hold back the longest suffix that is a proper prefix of any stop
        hold = 0
        max_check = min(len(buf), self._max_len - 1)
        for k in range(max_check, 0, -1):
            suffix = buf[len(buf) - k :]
            if any(s.startswith(suffix) for s in self._stops):
                hold = k
                break
        if hold:
            self._held = buf[len(buf) - hold :]
            return buf[: len(buf) - hold], False
        self._held = ""
        return buf, False

    def flush(self) -> str:
        out, self._held = self._held, ""
        return out


class Backend:
    """Operator: engine's raw token stream -> detokenized, stop-enforced stream."""

    def __init__(self, engine: AsyncEngine, tokenizer: Tokenizer):
        self.engine = engine
        self.tokenizer = tokenizer

    def _token_entry(self, tid: int, lp: float) -> dict:
        s = self.tokenizer.decode([tid], skip_special_tokens=False)
        return {"token": s, "logprob": lp, "bytes": list(s.encode("utf-8"))}

    def _logprob_entries(
        self,
        emit_ids: List[int],
        logprobs: Optional[List[float]],
        top_logprobs: Optional[List[dict]],
        n_top: int,
    ) -> Optional[List[dict]]:
        """OpenAI-shaped logprob entries, one per emitted token: the chosen
        token's own (token, logprob, bytes) plus the top-N alternatives,
        sorted descending. The chosen token is guaranteed present: when it
        falls outside the engine's top-N it is appended as an N+1th entry
        (vLLM semantics), so under greedy sampling it always leads the list."""
        if logprobs is None:
            return None
        entries: List[dict] = []
        for i, tid in enumerate(emit_ids):
            lp = float(logprobs[i]) if i < len(logprobs) else 0.0
            entry = self._token_entry(tid, lp)
            if n_top > 0 and top_logprobs is not None and i < len(top_logprobs):
                alts = {int(t): float(v) for t, v in top_logprobs[i].items()}
                chosen_lp = alts.pop(tid, lp)
                # top-n of the *other* candidates + the chosen token: when the
                # chosen was in the engine's top-n this yields exactly n rows,
                # otherwise n+1 rows with the chosen ranked last
                merged = sorted(alts.items(), key=lambda kv: -kv[1])[:n_top]
                merged.append((tid, chosen_lp))
                merged.sort(key=lambda kv: -kv[1])
                entry["top_logprobs"] = [
                    self._token_entry(int(t), float(v)) for t, v in merged
                ]
            else:
                entry["top_logprobs"] = []
            entries.append(entry)
        return entries

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        req = request if isinstance(request, PreprocessedRequest) else PreprocessedRequest.from_obj(request)
        # distributed tracing: continue the frontend's trace across the
        # request-plane hop (runtime/tracing.py; reference logging.rs:206-270)
        from ..runtime.tracing import get_tracer

        tracer = get_tracer()
        if not tracer.enabled:
            async for item in self._generate_inner(req, context):
                yield item
            return
        with tracer.span(
            "worker.generate",
            traceparent=req.annotations.get("traceparent"),
            request_id=req.request_id,
        ):
            async for item in self._generate_inner(req, context):
                yield item

    async def _generate_inner(
        self, req: PreprocessedRequest, context: Context
    ) -> AsyncIterator[Any]:
        decode = DecodeStream(self.tokenizer)
        jail = StopStringJail(req.stop.stop_strings)
        stop_token_ids = set(req.stop.stop_token_ids)
        if not req.stop.ignore_eos and self.tokenizer.eos_token_id is not None:
            stop_token_ids.add(self.tokenizer.eos_token_id)
        max_tokens = req.stop.max_tokens
        produced = 0
        finished = False

        async for step in self.engine.generate(req, context):
            out = step if isinstance(step, BackendOutput) else BackendOutput.from_obj(step)
            emit_ids: List[int] = []
            finish: Optional[str] = out.finish_reason
            for tid in out.token_ids:
                if finished:
                    break
                produced += 1
                if tid in stop_token_ids and produced > req.stop.min_tokens:
                    finish = FINISH_STOP
                    finished = True
                    break  # eos/stop token excluded from output
                emit_ids.append(tid)
                if max_tokens is not None and produced >= max_tokens:
                    finish = finish or "length"
                    finished = True
                    break
            text_delta = decode.step(emit_ids) if emit_ids else ""
            hit = False
            if text_delta or finish:
                text_delta, hit = jail.push(text_delta)
                if hit:
                    finish = FINISH_STOP
                    finished = True
                elif finish is not None:
                    # generation over without completing a stop string: release
                    # everything held back (jail prefixes + split UTF-8 tail)
                    tail, hit = jail.push(decode.flush())
                    if hit:
                        text_delta += tail
                    else:
                        text_delta += tail + jail.flush()
            entries = None
            if (req.sampling.want_logprobs or req.sampling.logprobs > 0) and emit_ids:
                entries = self._logprob_entries(
                    emit_ids, out.logprobs, out.top_logprobs, req.sampling.logprobs
                )
            yield BackendOutput(
                token_ids=emit_ids,
                text=text_delta,
                finish_reason=finish,
                cumulative_tokens=produced,
                logprobs=out.logprobs,
                top_logprobs=out.top_logprobs,
                logprob_entries=entries,
                annotations=out.annotations,
                kv_transfer=out.kv_transfer,
            ).to_obj()
            if finish is not None:
                return
            if context.is_stopped():
                yield BackendOutput(
                    finish_reason="cancelled", cumulative_tokens=produced
                ).to_obj()
                return
