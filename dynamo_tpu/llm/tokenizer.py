"""Tokenizer layer: HF wrapper + offline byte-level fallback + incremental decode.

Analog of the reference's tokenizers wrapper with DecodeStream
(lib/llm/src/tokenizers.rs). Two implementations:

- ``HFTokenizer``: transformers.AutoTokenizer over a *local* path or cached
  repo (this environment has no egress, so remote downloads are not assumed);
  brings the model's own chat template.
- ``ByteTokenizer``: deterministic byte-level vocab (256 bytes + specials)
  with a ChatML-style template — exact text roundtrip, zero assets, the
  default for tests and benchmarks.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterable, List, Optional, Protocol, Sequence

from ..runtime.logging import get_logger

log = get_logger("llm.tokenizer")


class Tokenizer(Protocol):
    eos_token_id: int
    bos_token_id: Optional[int]
    vocab_size: int

    def encode(self, text: str) -> List[int]: ...

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str: ...

    def apply_chat_template(
        self, messages: List[Dict[str, Any]], add_generation_prompt: bool = True
    ) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes as tokens; ids 256+ are special tokens.

    vocab_size is padded to 512 so embedding tables tile cleanly on the MXU
    (multiples of 128 lanes)."""

    BOS = 256
    EOS = 257
    PAD = 258
    IM_START = 259   # chat-turn delimiters (ChatML-style)
    IM_END = 260

    _SPECIAL = {BOS: "<s>", EOS: "</s>", PAD: "<pad>", IM_START: "<|im_start|>", IM_END: "<|im_end|>"}

    def __init__(self):
        self.eos_token_id = self.EOS
        self.bos_token_id = self.BOS
        self.pad_token_id = self.PAD
        self.vocab_size = 512

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        out = bytearray()
        parts: List[str] = []
        for i in ids:
            if i < 256:
                out.append(i)
            elif i in self._SPECIAL:
                if not skip_special_tokens:
                    if out:
                        parts.append(out.decode("utf-8", errors="replace"))
                        out = bytearray()
                    parts.append(self._SPECIAL[i])
            else:
                # out-of-vocab id (e.g. random-init model with a larger lm
                # head than the byte vocab): emit a visible placeholder
                # instead of silently dropping — smoke tests stream
                # *something*. Must not be U+FFFD: DecodeStream holds back
                # trailing U+FFFD as a split-multibyte sentinel.
                if out:
                    parts.append(out.decode("utf-8", errors="replace"))
                    out = bytearray()
                parts.append(f"<unk:{i}>")
        if out:
            parts.append(out.decode("utf-8", errors="replace"))
        return "".join(parts)

    def apply_chat_template(
        self, messages: List[Dict[str, Any]], add_generation_prompt: bool = True
    ) -> str:
        parts = []
        for m in messages:
            content = m.get("content") or ""
            if isinstance(content, list):
                content = "".join(
                    p.get("text", "") for p in content if p.get("type") == "text"
                )
            parts.append(f"<|im_start|>{m['role']}\n{content}<|im_end|>\n")
        if add_generation_prompt:
            parts.append("<|im_start|>assistant\n")
        return "".join(parts)

    def encode_chat(self, messages: List[Dict[str, Any]]) -> List[int]:
        """Template-aware encoding: delimiters become real special ids so the
        model (and stop handling) can see turn boundaries."""
        ids: List[int] = [self.BOS]
        for m in messages:
            content = m.get("content") or ""
            if isinstance(content, list):
                content = "".join(
                    p.get("text", "") for p in content if p.get("type") == "text"
                )
            ids.append(self.IM_START)
            ids.extend(self.encode(f"{m['role']}\n{content}"))
            ids.append(self.IM_END)
        ids.append(self.IM_START)
        ids.extend(self.encode("assistant\n"))
        return ids


class HFTokenizer:
    """transformers.AutoTokenizer adapter (local paths; offline-safe)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer  # deferred: heavy import

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.eos_token_id = self._tok.eos_token_id
        self.bos_token_id = self._tok.bos_token_id
        self.vocab_size = len(self._tok)

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(ids, skip_special_tokens=skip_special_tokens)

    def apply_chat_template(
        self, messages: List[Dict[str, Any]], add_generation_prompt: bool = True
    ) -> str:
        return self._tok.apply_chat_template(
            messages, tokenize=False, add_generation_prompt=add_generation_prompt
        )

    def encode_chat(self, messages: List[Dict[str, Any]]) -> List[int]:
        return self._tok.apply_chat_template(
            messages, tokenize=True, add_generation_prompt=True
        )


_CACHE: Dict[str, Tokenizer] = {}


def load_tokenizer(ref: Optional[str]) -> Tokenizer:
    """ref: None/"byte" -> ByteTokenizer; else local path for HFTokenizer."""
    key = ref or "byte"
    if key in _CACHE:
        return _CACHE[key]
    if ref is None or ref == "byte":
        tok: Tokenizer = ByteTokenizer()
    elif os.path.exists(ref):
        tok = HFTokenizer(ref)
    else:
        log.warning("tokenizer ref %r not found locally; falling back to byte tokenizer", ref)
        tok = ByteTokenizer()
    _CACHE[key] = tok
    return tok


class DecodeStream:
    """Incremental detokenization: feed token ids, get printable text deltas.

    Holds back text while the current suffix could still be an incomplete
    UTF-8 sequence (decode yields U+FFFD at the boundary)."""

    def __init__(self, tokenizer: Tokenizer, skip_special_tokens: bool = True):
        self._tok = tokenizer
        self._ids: List[int] = []
        self._emitted = 0  # chars already released
        self._skip_special = skip_special_tokens

    def step(self, token_ids: Iterable[int]) -> str:
        self._ids.extend(token_ids)
        text = self._tok.decode(self._ids, skip_special_tokens=self._skip_special)
        # hold back a trailing replacement char: likely a split multibyte seq
        safe_end = len(text)
        while safe_end > self._emitted and text[safe_end - 1] == "�":
            safe_end -= 1
        delta = text[self._emitted : safe_end]
        self._emitted = safe_end
        return delta

    def flush(self) -> str:
        text = self._tok.decode(self._ids, skip_special_tokens=self._skip_special)
        delta = text[self._emitted :]
        self._emitted = len(text)
        return delta
