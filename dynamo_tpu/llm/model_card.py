"""ModelDeploymentCard: the unit of model discovery.

Analog of the reference's MDC (lib/llm/src/model_card.rs, stored under
``v1/mdc``): everything a frontend needs to serve a model — name, tokenizer
source, context limits, KV block size, model type, migration limit, runtime
config — written to the discovery store by workers under their lease, watched
by frontends.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ..models.vision import IMAGE_TOKEN_ID as _IMAGE_TOKEN_ID

MDC_PREFIX = "v1/mdc"

MODEL_TYPE_CHAT = "chat"
MODEL_TYPE_COMPLETIONS = "completions"
MODEL_TYPE_EMBEDDING = "embedding"
MODEL_TYPE_PREFILL = "prefill"  # prefill-only pool member (disaggregation)
# generic tensor-in/tensor-out model (llm/protocols/tensor.py; reference
# protocols/tensor.rs + grpc/service/tensor.rs): served over KServe gRPC,
# no tokenizer/OpenAI machinery
MODEL_TYPE_TENSOR = "tensor"
MODEL_TYPE_IMAGES = "images"  # image generation (/v1/images/generations)

MODEL_INPUT_TEXT = "text"      # worker wants raw text (does its own tokenize)
MODEL_INPUT_TOKENS = "tokens"  # worker wants token ids (frontend preprocesses)


def mdc_key(namespace: str, model_slug: str, instance_id: int) -> str:
    return f"{MDC_PREFIX}/{namespace}/{model_slug}/{instance_id:016x}"


def model_slug(name: str) -> str:
    return name.replace("/", "--").lower()


@dataclasses.dataclass
class ModelRuntimeConfig:
    """Worker capability advertisement (reference: runtime_config.rs)."""

    total_kv_blocks: int = 0
    kv_block_size: int = 16
    max_batch_size: int = 0
    data_parallel_size: int = 1
    tensor_parallel_size: int = 1
    max_context_len: int = 0
    # wire bytes of one KV block in this worker's cache storage format
    # (kvbm/layout.kv_bytes_per_token * block_size; int8 is ~half bf16) —
    # transfer-cost-aware disagg routing prices candidate wires with it
    kv_bytes_per_block: int = 0
    # per-model SLA target overrides keyed by class name, e.g.
    # {"interactive": {"ttft_target_s": 0.3}} — merged over the named-class
    # table by runtime/slo.resolve_sla at the frontend
    sla_classes: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)

    def to_obj(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "ModelRuntimeConfig":
        return cls(**{k: v for k, v in obj.items() if k in {f.name for f in dataclasses.fields(cls)}})


@dataclasses.dataclass
class ModelDeploymentCard:
    name: str                                  # served model name ("meta-llama/Llama-3-8B")
    namespace: str = "dynamo"
    component: str = "backend"
    endpoint: str = "generate"
    model_type: List[str] = dataclasses.field(default_factory=lambda: [MODEL_TYPE_CHAT, MODEL_TYPE_COMPLETIONS])
    model_input: str = MODEL_INPUT_TOKENS
    # tokenizer/template source: HF repo id or local path; None -> no preprocessor
    tokenizer: Optional[str] = None
    context_length: int = 8192
    kv_block_size: int = 16
    migration_limit: int = 0
    # streaming output parsers (dynamo_tpu/parsers registry names); None
    # passes raw text through (reference: parser selection in lib/parsers)
    reasoning_parser: Optional[str] = None
    tool_parser: Optional[str] = None
    # multimodal (models/vision.py): placeholder token id, soft tokens per
    # image, and the square input size images are resized to. image_tokens=0
    # means the model is text-only.
    image_token_id: int = _IMAGE_TOKEN_ID
    image_tokens: int = 0
    image_size: int = 0
    # audio capability (reference async-openai audio types): False means
    # audio parts / modalities=["audio"] requests get a clear 400
    audio: bool = False
    runtime_config: ModelRuntimeConfig = dataclasses.field(default_factory=ModelRuntimeConfig)
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def slug(self) -> str:
        return model_slug(self.name)

    def to_obj(self) -> Dict[str, Any]:
        obj = dataclasses.asdict(self)
        obj["runtime_config"] = self.runtime_config.to_obj()
        return obj

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "ModelDeploymentCard":
        rc = ModelRuntimeConfig.from_obj(obj.get("runtime_config") or {})
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in obj.items() if k in known and k != "runtime_config"}
        return cls(runtime_config=rc, **kwargs)
