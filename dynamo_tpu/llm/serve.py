"""Worker-side registration: serve an engine + publish its model card.

Analog of the reference's ``register_llm`` binding
(lib/bindings/python/rust/lib.rs:230-248): wraps the engine in the Backend
operator (detokenize + stop handling), serves the endpoint on the request
plane, and writes the ModelDeploymentCard into the store under the worker's
lease so frontends discover it (reference: lib/llm/src/model_card.rs:32).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from ..runtime.component import ServedEndpoint
from ..runtime.distributed import DistributedRuntime
from ..runtime.engine import AsyncEngine
from .backend import Backend
from .model_card import ModelDeploymentCard, mdc_key
from .tokenizer import Tokenizer, load_tokenizer


async def register_llm(
    runtime: DistributedRuntime,
    engine: AsyncEngine,
    card: ModelDeploymentCard,
    tokenizer: Optional[Tokenizer] = None,
    raw_token_stream: bool = False,
    metadata: Optional[Dict[str, Any]] = None,
    instance_id: Optional[int] = None,
) -> ServedEndpoint:
    """Serve ``engine`` for ``card`` and announce it.

    raw_token_stream=True skips the Backend wrapper (engine already emits
    finished BackendOutput objs with text + stop handling)."""
    tok = tokenizer or load_tokenizer(card.tokenizer)
    handler = engine.generate if raw_token_stream else Backend(engine, tok).generate
    endpoint = (
        runtime.namespace(card.namespace).component(card.component).endpoint(card.endpoint)
    )
    md = {
        "model": card.name,
        "data_parallel_size": card.runtime_config.data_parallel_size,
        "total_kv_blocks": card.runtime_config.total_kv_blocks,
    }
    # disaggregation: a worker already serving KV transfer advertises its
    # fetch address (streamed disagg dispatches the decode hop BEFORE the
    # prefill finishes, so the frontend needs the address at routing time)
    # and a wire-class hint for the transfer-cost-aware router
    transfer_address = getattr(engine, "transfer_address", None)
    if transfer_address:
        md.setdefault("transfer_address", transfer_address)
        md.setdefault("kv_wire", os.environ.get("DTPU_KV_WIRE", "inline"))
    bpb = int(getattr(engine, "kv_bytes_per_block", 0) or 0)
    if bpb and not card.runtime_config.kv_bytes_per_block:
        card.runtime_config.kv_bytes_per_block = bpb
    if metadata:
        md.update(metadata)
    served = await endpoint.serve(handler, instance_id=instance_id, metadata=md)
    key = mdc_key(card.namespace, card.slug, served.instance_id)
    await served.publish_extra(key, card.to_obj())
    return served


async def serve_clear_endpoint(
    runtime: DistributedRuntime,
    namespace: str,
    component: str,
    engines,
    instance_id: int,
) -> ServedEndpoint:
    """Serve a ``clear_kv_blocks`` admin endpoint beside generate, under the
    SAME instance id so the frontend's per-worker fan-out targets line up
    (reference http/clear_kv_blocks.rs + block_manager/controller.rs). One
    shared shim for every worker main: engines is the list of engine objects
    whose caches this worker owns (dp>1 = one per rank); integer tier counts
    sum across them."""

    async def handle_clear_kv(request, context):
        levels = (request or {}).get("levels")
        results = []
        for e in engines:
            results.append(await e.clear_kv_blocks(levels))
        out = {k: v for k, v in results[0].items() if isinstance(v, int)}
        for r in results[1:]:
            for k, v in r.items():
                if isinstance(v, int):
                    out[k] = out.get(k, 0) + v
        out["snapshot"] = results[0].get("snapshot")
        yield out

    return await (
        runtime.namespace(namespace).component(component)
        .endpoint("clear_kv_blocks")
        .serve(handle_clear_kv, instance_id=instance_id)
    )


async def serve_eplb_endpoint(
    runtime: DistributedRuntime,
    namespace: str,
    component: str,
    engines,
    instance_id: int,
) -> ServedEndpoint:
    """Serve an ``eplb_rebalance`` admin endpoint beside generate (reference:
    SGLang's EPLB rebalances from periodically collected expert counts; here
    an operator/cron drives it). Request: {"counts": [E] or [L, E]} to
    rebalance from external stats, or {"probe_tokens": [...]} to measure on
    a representative batch first and rebalance from the result."""

    async def handle_eplb(request, context):
        import asyncio as _aio

        import numpy as _np

        req = request or {}
        loop = _aio.get_event_loop()
        counts = req.get("counts")
        if counts is None:
            probe = req.get("probe_tokens")
            if not probe:
                raise ValueError(
                    "eplb_rebalance wants counts=[E]|[L,E] or "
                    "probe_tokens=[...]"
                )
            # dp replicas hold identical weights: measure ONCE, feed the
            # same counts to every rank's rebalance
            measured = await loop.run_in_executor(
                None, engines[0].measure_expert_load,
                [int(t) for t in probe],
            )
            counts = measured.sum(axis=0)
        counts = _np.asarray(counts, float)
        results = []
        for e in engines:
            results.append(
                await loop.run_in_executor(None, e.eplb_rebalance, counts)
            )
        out = dict(results[0])
        out["engines"] = len(results)
        yield out

    return await (
        runtime.namespace(namespace).component(component)
        .endpoint("eplb_rebalance")
        .serve(handle_eplb, instance_id=instance_id)
    )
