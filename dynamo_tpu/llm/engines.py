"""Built-in toy engines: echo (tokens in -> tokens out) for tests and wiring.

Analog of the reference's EchoEngine (lib/llm/src/engines.rs:67)."""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator

from ..runtime.engine import Context
from .protocols.common import FINISH_LENGTH, FINISH_STOP, BackendOutput, PreprocessedRequest


class EchoEngine:
    """Streams the prompt's token ids back one at a time (bounded by
    max_tokens), with a configurable per-token delay to exercise streaming."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        req = request if isinstance(request, PreprocessedRequest) else PreprocessedRequest.from_obj(request)
        if req.annotations.get("op") == "embed":
            # deterministic toy embedding so the API surface is testable
            vec = [float(len(req.token_ids))] + [float(t) for t in req.token_ids[:3]]
            yield BackendOutput(
                finish_reason=FINISH_STOP,
                annotations={"embedding": vec, "input_tokens": len(req.token_ids)},
            )
            return
        limit = req.stop.max_tokens or len(req.token_ids)
        produced = 0
        for tid in req.token_ids:
            if context.is_stopped():
                return
            if produced >= limit:
                yield BackendOutput(finish_reason=FINISH_LENGTH, cumulative_tokens=produced)
                return
            produced += 1
            # deterministic synthetic logprobs (chosen token is always the
            # argmax) so API-surface tests can exercise the full
            # engine->Backend->delta logprob path without a real model
            lps = None
            tlps = None
            if req.sampling.want_logprobs or req.sampling.logprobs > 0:
                lps = [-0.25]
                tlps = [{tid: -0.25, (tid + 1) % 512: -1.25, (tid + 2) % 512: -2.25}]
            yield BackendOutput(
                token_ids=[tid], cumulative_tokens=produced,
                logprobs=lps, top_logprobs=tlps,
            )
            if self.delay_s:
                await asyncio.sleep(self.delay_s)
        yield BackendOutput(finish_reason=FINISH_STOP, cumulative_tokens=produced)
