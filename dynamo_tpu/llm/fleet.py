"""One-call fleet snapshot: the frontend's ``/debug/fleet`` fan-out.

Every worker exposes its full observability document on its status server's
``/debug/worker`` route (runtime/health.py StatusServer) and advertises the
server's address in its discovery metadata (``status_address``, stamped by
``engine/__main__.py`` after the side port binds). ``fleet_snapshot`` fans
out to every discovered worker — bounded concurrency, per-worker timeout —
and merges the answers with the frontend's own view (SLO ledger, attribution
windows, per-model breakers) into one JSON document: "what is the fleet
doing right now" in one call instead of N scrapes plus a join by hand.

Partial results are a feature, not a failure: a worker that times out, is
mid-restart, or never advertised an address gets a ``stale: true`` entry
carrying the error, and the merge proceeds — a degraded fleet is exactly
when the snapshot matters most, so a dead worker must never turn the whole
endpoint into a 500.

Knobs: ``DTPU_FLEET_FANOUT`` bounds concurrent worker fetches (default 8);
``DTPU_FLEET_TIMEOUT_S`` is the per-worker fetch timeout (default 2.0 s).
The fetch itself is injectable so the simulator and tests drive the real
fan-out/merge logic without sockets.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional

from ..runtime.config import (
    ENV_FLEET_FANOUT,
    ENV_FLEET_TIMEOUT_S,
    env_float,
    env_int,
)
from ..runtime.logging import get_logger

log = get_logger("llm.fleet")

DEFAULT_FANOUT = 8
DEFAULT_TIMEOUT_S = 2.0

FetchFn = Callable[[str], Awaitable[Dict[str, Any]]]


async def _http_fetch(address: str, timeout_s: float) -> Dict[str, Any]:
    """Default fetch: GET http://<address>/debug/worker."""
    import aiohttp

    timeout = aiohttp.ClientTimeout(total=timeout_s)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        async with session.get(f"http://{address}/debug/worker") as resp:
            resp.raise_for_status()
            return await resp.json()


def _discover_workers(pipelines) -> List[Dict[str, Any]]:
    """Flatten every pipeline's discovery records into fetch targets."""
    targets = []
    for pipe in pipelines:
        client = getattr(pipe, "client", None)
        instances = getattr(client, "instances", None) or {}
        for iid, rec in sorted(instances.items()):
            md = getattr(rec, "metadata", None) or {}
            targets.append({
                "worker_id": f"{iid:016x}" if isinstance(iid, int) else str(iid),
                "model": pipe.card.name,
                "state": md.get("state", "ready"),
                "status_address": md.get("status_address"),
            })
    return targets


async def fleet_snapshot(
    pipelines,
    fetch: Optional[FetchFn] = None,
    fanout: Optional[int] = None,
    timeout_s: Optional[float] = None,
    frontend: Optional[Dict[str, Any]] = None,
    clock: Callable[[], float] = time.time,
) -> Dict[str, Any]:
    """Fan out to every discovered worker's ``/debug/worker`` and merge.

    ``pipelines``: iterable of llm/discovery.py ModelPipeline (duck-typed:
    ``.card.name``, ``.client.instances``, ``._worker_breakers``).
    ``fetch``: injectable ``async (address) -> dict`` (tests/sim); the
    default does a real HTTP GET with the per-worker timeout applied
    around the call either way.
    """
    if fanout is None:
        fanout = env_int(ENV_FLEET_FANOUT, DEFAULT_FANOUT)
    if timeout_s is None:
        timeout_s = env_float(ENV_FLEET_TIMEOUT_S, DEFAULT_TIMEOUT_S)
    targets = _discover_workers(pipelines)
    sem = asyncio.Semaphore(max(1, fanout))

    async def _one(target: Dict[str, Any]) -> Dict[str, Any]:
        entry = dict(target, stale=False)
        address = target["status_address"]
        if not address:
            entry["stale"] = True
            entry["error"] = "no status_address advertised"
            return entry
        try:
            async with sem:
                if fetch is not None:
                    doc = await asyncio.wait_for(fetch(address), timeout_s)
                else:
                    doc = await _http_fetch(address, timeout_s)
            entry["snapshot"] = doc
        except asyncio.TimeoutError:
            entry["stale"] = True
            entry["error"] = f"timed out after {timeout_s}s"
        except Exception as e:
            entry["stale"] = True
            entry["error"] = f"{type(e).__name__}: {e}"
        return entry

    workers = list(await asyncio.gather(*(_one(t) for t in targets)))
    stale = sum(1 for w in workers if w["stale"])
    if stale:
        log.warning("fleet snapshot: %d/%d workers stale", stale, len(workers))

    # per-model rollup: instance counts, frontend breaker, per-worker
    # breaker states (open circuits are the routing plane's own view of
    # worker health — worth seeing next to the workers' self-reports)
    models: Dict[str, Any] = {}
    for pipe in pipelines:
        name = pipe.card.name
        breakers = {
            f"{iid:016x}" if isinstance(iid, int) else str(iid): cb.state
            for iid, cb in sorted(
                getattr(pipe, "_worker_breakers", {}).items()
            )
        }
        models[name] = {
            "instances": len(
                getattr(getattr(pipe, "client", None), "instances", None) or {}
            ),
            "worker_breakers": breakers,
            "open_circuits": sum(1 for s in breakers.values() if s == "open"),
        }

    doc: Dict[str, Any] = {
        "generated_at": round(clock(), 3),
        "fleet": {
            "workers_total": len(workers),
            "workers_live": len(workers) - stale,
            "workers_stale": stale,
            "draining": sum(1 for w in workers if w["state"] == "draining"),
        },
        "models": models,
        "workers": workers,
    }
    doc.update(_merge_worker_sections(workers))
    if frontend is not None:
        doc["frontend"] = frontend
    return doc


def _merge_worker_sections(workers: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fleet-level rollups computed from the live worker documents."""
    kv = {"active_blocks": 0, "free_blocks": 0, "total_blocks": 0}
    gkv = {"published": 0, "inflight_fetches": 0, "dedupe_skipped": 0}
    restore_modes: Dict[str, int] = {}
    health_active: List[Dict[str, Any]] = []
    saw_kv = saw_gkv = False
    for w in workers:
        snap = w.get("snapshot")
        if not isinstance(snap, dict):
            continue
        wkv = snap.get("kv")
        if isinstance(wkv, dict):
            saw_kv = True
            for k in kv:
                v = wkv.get(k)
                if isinstance(v, (int, float)):
                    kv[k] += int(v)
        wgkv = snap.get("global_kv")
        if isinstance(wgkv, dict):
            saw_gkv = True
            for k in gkv:
                v = wgkv.get(k)
                if isinstance(v, (int, float)):
                    gkv[k] += int(v)
        mode = snap.get("restore_mode")
        if isinstance(mode, str):
            restore_modes[mode] = restore_modes.get(mode, 0) + 1
        health = snap.get("health")
        if isinstance(health, dict):
            for item in health.get("active", []) or []:
                health_active.append(dict(item, worker_id=w["worker_id"]))
    merged: Dict[str, Any] = {}
    if saw_kv:
        merged["kv"] = kv
    if saw_gkv:
        merged["global_kv"] = gkv
    if restore_modes:
        merged["restore_modes"] = dict(sorted(restore_modes.items()))
    if health_active:
        merged["health_active"] = health_active
    return merged
