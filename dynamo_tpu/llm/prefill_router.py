"""PrefillRouter: disaggregated prefill/decode orchestration on the frontend.

Analog of the reference's PrefillRouter (lib/llm/src/kv_router/
prefill_router.rs:102,505 + docs/design_docs/disagg_serving.md): when a
prefill pool is registered for a model, each request is first sent to a
prefill worker as a clone with ``max_tokens=1``; the first token streams to
the client immediately, and the decode request carries the prefill worker's
KV-transfer metadata (address + block hashes) plus the first token as prior
context. If no prefill pool exists (elastic xPyD: pools scale to zero) the
request falls through to the aggregated path — runtime-reconfigurable
disaggregation, like the reference (disagg_serving.md:67-69).

Three disagg-era behaviors layer on top (``DisaggConfig``):

- **transfer-cost-aware selection** (NetKV-style): every prefill candidate's
  logit carries the estimated seconds to ship the request's KV over that
  candidate's advertised wire class (per-wire EWMA bandwidth from
  ``runtime/bandwidth.py``, observed on real ``kv.transfer.pull`` legs),
  normalized into the scheduler's block units — a candidate behind a slow
  wire loses to one a device hop away at equal queue depth.
- **prefill deflection** (load-aware): short prompts, requests whose prefix
  is already hot in the DECODE pool's radix tree, and requests whose best
  disagg plan costs more than ``deflect_margin``x the local prefill skip
  the hop entirely and prefill on the decode worker (mixed continuous
  batching makes the deflected chunk ride the decode dispatch).
- **streamed dispatch**: when the chosen prefill worker advertises its
  transfer address in instance metadata, the decode request ships
  IMMEDIATELY with a streamed ``kv_transfer`` handshake — its block-window
  pull overlaps the prefill compute instead of serializing behind it.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from ..kv_router import KvRouter, KvRouterConfig, WorkerWithDpRank
from ..runtime import metrics as M
from ..runtime.bandwidth import get_bandwidth_estimator
from ..runtime.component import Client, RouterMode
from ..runtime.engine import Context
from ..runtime.errors import is_terminal
from ..runtime.flight_recorder import get_flight_recorder
from ..runtime.logging import get_logger
from ..runtime.request_plane.tcp import NoResponders
from ..runtime.tasks import spawn_bg
from ..runtime.tracing import get_tracer
from ..tokens import compute_sequence_hashes
from .model_card import ModelDeploymentCard
from .preprocessor import ANNOTATION_PREFILL_WORKER_ID
from .protocols.common import BackendOutput, PreprocessedRequest

log = get_logger("llm.prefill_router")

# fallback KV footprint when neither the config nor the card advertises one:
# a mid-size bf16 model's order of magnitude (the estimate only has to rank
# wires, not bill them)
_DEFAULT_KV_BYTES_PER_BLOCK = 256 * 1024


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclasses.dataclass
class DisaggConfig:
    """Knobs for streamed disagg, transfer-cost-aware routing and prefill
    deflection (env-overridable; docs/operations.md 'Disaggregation')."""

    # streamed decode dispatch (DTPU_STREAM_KV=0 restores the sequential
    # prefill -> transfer -> decode pipeline)
    streamed: bool = True
    # deflection master switch (DTPU_DEFLECT=0 -> every request pays the hop)
    deflect: bool = True
    # prompts at or under this many tokens never take the disagg hop: the
    # handshake + wire tail exceeds what their prefill costs locally
    deflect_max_tokens: int = 128
    # deflect when the decode pool already holds at least this fraction of
    # the prompt's blocks (radix-hot prefix: shipping KV it has is waste)
    deflect_overlap_frac: float = 0.5
    # deflect when the best disagg plan's cost (queue + prefill + wire, in
    # block units) exceeds (1 + margin) x the local prefill cost — the
    # load-skew valve: deep prefill queues push traffic back to decode
    deflect_margin: float = 1.0
    # seconds to prefill one KV block, used to convert wire seconds into
    # the scheduler's block-unit logits (coarse; DTPU_PREFILL_BLOCK_MS)
    prefill_block_time_s: float = 0.010
    # override the per-block wire bytes (0 = card's advertised value)
    kv_bytes_per_block: int = 0

    @classmethod
    def from_env(cls) -> "DisaggConfig":
        return cls(
            streamed=os.environ.get("DTPU_STREAM_KV", "1") != "0",
            deflect=os.environ.get("DTPU_DEFLECT", "1") != "0",
            deflect_max_tokens=int(
                _env_f("DTPU_DEFLECT_MAX_TOKENS", cls.deflect_max_tokens)
            ),
            deflect_overlap_frac=_env_f(
                "DTPU_DEFLECT_OVERLAP", cls.deflect_overlap_frac
            ),
            deflect_margin=_env_f("DTPU_DEFLECT_MARGIN", cls.deflect_margin),
            prefill_block_time_s=_env_f("DTPU_PREFILL_BLOCK_MS", 10.0) / 1e3,
            kv_bytes_per_block=int(_env_f("DTPU_KV_BYTES_PER_BLOCK", 0)),
        )


@dataclasses.dataclass
class PrefillPlan:
    """One routing decision for the disagg hop (or the decision to skip it).

    ``deflect_reason`` set => serve aggregated. Otherwise ``worker_id``
    names the prefill worker; ``transfer_address`` (from its instance
    metadata) non-None + ``streamed`` => early decode dispatch with a
    streamed kv_transfer handshake."""

    deflect_reason: Optional[str] = None
    worker_id: Optional[int] = None
    dp_rank: int = 0
    overlap_blocks: int = 0
    query_blocks: int = 0
    transfer_address: Optional[str] = None
    wire: str = "inline"
    streamed: bool = False
    est_transfer_s: float = 0.0
    hashes: List[int] = dataclasses.field(default_factory=list)

    @property
    def deflected(self) -> bool:
        return self.deflect_reason is not None


class GlobalKvFetchPlanner:
    """Fleet-wide KV reuse planning on the frontend (kvbm/directory.py).

    On a local radix miss, the missing prefix may be sealed in some OTHER
    worker's G2/G3 tier. This planner looks the miss up in the global block
    directory, prices onboard-from-peer-tier against recompute
    (``ops/costs.fetch_vs_recompute``, fed by the same wire-bandwidth EWMA
    the disagg hop prices with plus the holder tier's read latency), and —
    when fetching wins — returns a ``kv_transfer`` plan (``tier=True``)
    that streams the blocks from the holder over the block-window protocol
    instead of re-prefilling them. Directory staleness, a dead holder or a
    mid-fetch loss all degrade to recompute on the worker (engine-side
    fallback); the plan is advisory, never load-bearing for correctness."""

    # the tier wire class the fetch path observes into the bandwidth EWMA
    # (engine/transfer.py _pull_tier); unseen it prices at the inline prior
    WIRE = "tier"

    def __init__(
        self,
        directory,
        *,
        block_size: int,
        kv_bytes_per_block: int = 0,
        prefill_block_time_s: float = 0.010,
        prefill_base_s: float = 0.0,
        margin: Optional[float] = None,
        min_run_blocks: int = 1,
        bandwidth=None,
    ):
        from ..kvbm.directory import fetch_margin

        self.directory = directory
        self.block_size = int(block_size)
        self.kv_bytes_per_block = int(
            kv_bytes_per_block or _DEFAULT_KV_BYTES_PER_BLOCK
        )
        self.prefill_block_time_s = float(prefill_block_time_s)
        self.prefill_base_s = float(prefill_base_s)
        self.margin = float(margin if margin is not None else fetch_margin())
        self.min_run_blocks = max(1, int(min_run_blocks))
        self.bandwidth = bandwidth or get_bandwidth_estimator()

    def price(self, num_blocks: int, tier: str = "g2") -> Dict:
        """The fetch-vs-recompute verdict for ``num_blocks`` missing blocks
        (ops/costs.fetch_vs_recompute, tier-1 grid-gated)."""
        from ..ops.costs import fetch_vs_recompute

        return fetch_vs_recompute(
            num_blocks,
            block_size=self.block_size,
            kv_bytes_per_block=self.kv_bytes_per_block,
            bandwidth_bytes_s=self.bandwidth.bandwidth(self.WIRE),
            prefill_base_s=self.prefill_base_s,
            prefill_per_token_s=self.prefill_block_time_s / self.block_size,
            tier=tier,
            margin=self.margin,
        )

    async def plan_fetch(
        self,
        req: PreprocessedRequest,
        hashes: List[int],
        overlap_blocks: int,
        exclude_holder: Optional[str] = None,
    ) -> Optional[Dict]:
        """Return a ``kv_transfer`` plan dict for the request's missing
        prefix, or None to recompute. ``overlap_blocks`` is the decode
        pool's best local radix overlap (those blocks never fetch);
        ``hashes`` must be at this planner's block size."""
        miss = [int(h) for h in hashes[overlap_blocks:]]
        if len(miss) < self.min_run_blocks:
            return None
        run = await self.directory.lookup_run(
            miss, exclude_holder=exclude_holder
        )
        if len(run) < self.min_run_blocks:
            return None  # nobody (live) holds the prefix: plain recompute
        head = run[0]
        verdict = self.price(len(run), tier=head.tier)
        get_flight_recorder().record(
            req.request_id, "global_kv_plan",
            holder=head.holder, tier=head.tier, blocks=len(run),
            fetch_s=round(verdict["fetch_s"], 6),
            recompute_s=round(verdict["recompute_s"], 6),
            fetch_wins=verdict["fetch_wins"],
        )
        if not verdict["fetch_wins"] or not head.address:
            # the directory HAD the prefix but recompute prices cheaper
            # (or the holder advertises no fetch endpoint)
            self.directory.record_outcome("recomputed")
            return None
        return {
            "address": head.address,
            "hashes": [e.hash for e in run],
            "num_tokens": len(run) * self.block_size,
            "tier": True,
            "holder": head.holder,
            "est_fetch_s": verdict["fetch_s"],
        }


class PrefillRouter:
    def __init__(
        self,
        runtime,
        card: ModelDeploymentCard,
        kv_router_config: Optional[KvRouterConfig] = None,
        disagg: Optional[DisaggConfig] = None,
    ):
        self.runtime = runtime
        self.card = card  # the *prefill* pool's card
        self.client: Optional[Client] = None
        self.kv_router: Optional[KvRouter] = None
        self.kv_router_config = kv_router_config
        self.disagg = disagg or DisaggConfig.from_env()
        self.bandwidth = get_bandwidth_estimator()
        metrics = getattr(runtime, "metrics", None)
        self._deflected = (
            metrics.counter(
                M.PREFILL_DEFLECTED_TOTAL,
                "requests that skipped the disagg prefill hop",
                extra_labels=("reason",),
            )
            if metrics is not None else None
        )
        if metrics is not None:
            # frontend processes: expose the per-wire EWMA this router
            # prices candidates with (workers attach in engine/__main__)
            self.bandwidth.attach_metrics(metrics)

    async def start(self) -> "PrefillRouter":
        endpoint = (
            self.runtime.namespace(self.card.namespace)
            .component(self.card.component)
            .endpoint(self.card.endpoint)
        )
        self.client = await endpoint.client(RouterMode.ROUND_ROBIN)
        if self.kv_router_config is not None:
            self.kv_router = await KvRouter(
                self.runtime.event_plane,
                self.card.namespace,
                self.card.component,
                block_size=self.card.kv_block_size,
                config=self.kv_router_config,
                metrics=getattr(self.runtime, "metrics", None),
            ).start()
        return self

    @property
    def has_workers(self) -> bool:
        return self.client is not None and bool(self.client.instances)

    # -- transfer-cost-aware planning + deflection ---------------------------
    def _kv_bytes_per_block(self) -> int:
        if self.disagg.kv_bytes_per_block > 0:
            return self.disagg.kv_bytes_per_block
        adv = int(getattr(self.card.runtime_config, "kv_bytes_per_block", 0) or 0)
        return adv or _DEFAULT_KV_BYTES_PER_BLOCK

    def _candidates(self) -> List[WorkerWithDpRank]:
        cands: List[WorkerWithDpRank] = []
        if self.client is None:
            return cands
        # dp-aware like the decode path (scheduler.rs:543-560): every
        # (instance, dp_rank) is a candidate, and the chosen rank rides the
        # annotation so the worker's DpEngineGroup dispatches to it
        for iid, inst in self.client.instances.items():
            dp = int(inst.metadata.get("data_parallel_size", 1) or 1)
            for r in range(dp):
                cands.append(WorkerWithDpRank(iid, r))
        return cands

    def _instance_meta(self, iid: int, key: str):
        inst = self.client.instances.get(iid) if self.client else None
        return inst.metadata.get(key) if inst is not None else None

    def _record_deflect(self, req: PreprocessedRequest, reason: str) -> PrefillPlan:
        get_flight_recorder().record(
            req.request_id, "prefill_deflected", reason=reason
        )
        if self._deflected is not None:
            self._deflected.inc(reason=reason)
        log.debug("deflecting %s (%s)", req.request_id[:8], reason)
        return PrefillPlan(deflect_reason=reason)

    def plan(
        self, req: PreprocessedRequest, decode_overlap_blocks: int = 0,
        hashes: Optional[List[int]] = None,
    ) -> Optional[PrefillPlan]:
        """Price the disagg hop for this request: deflect it, or pick the
        prefill worker whose (queue + remaining prefill + wire) cost is
        lowest. ``decode_overlap_blocks`` is how much of the prompt the
        decode pool's radix tree already holds (those blocks never ship);
        ``hashes`` shares a caller's hash pass (must match this card's
        block size). Returns None when the pool has no candidates (caller
        falls through to aggregated, same as before).

        Scoring is side-effect-free (``score_tokens``); the router's
        optimistic load / approx-index bookkeeping is committed only when
        the request actually takes the hop — a deflected request must not
        leave phantom route state on the prefill pool."""
        cfg = self.disagg
        cands = self._candidates()
        if not cands:
            return None
        tokens = list(req.token_ids)
        block_size = self.card.kv_block_size
        from ..models.vision import IMAGE_TOKEN_ID

        # image placeholder runs hash identically across different images:
        # their blocks are never servable from cache, so neither the
        # overlap estimate nor a streamed handshake may trust the hashes
        # (the prefill engine marks them no_cache and never commits them —
        # a streamed decode pull would stall out waiting)
        cacheable = IMAGE_TOKEN_ID not in tokens
        if hashes is None:
            hashes = compute_sequence_hashes(tokens, block_size)
        query_blocks = max(len(tokens) // block_size, 0)
        if cfg.deflect:
            if len(tokens) <= cfg.deflect_max_tokens:
                return self._record_deflect(req, "short_prompt")
            if (
                cacheable
                and query_blocks > 0
                and decode_overlap_blocks
                >= cfg.deflect_overlap_frac * query_blocks
            ):
                return self._record_deflect(req, "radix_hit")
        # per-candidate wire cost in block units: bytes that must ship over
        # the candidate's advertised wire class, at the EWMA bandwidth
        move_blocks = max(query_blocks - decode_overlap_blocks, 0)
        move_bytes = move_blocks * self._kv_bytes_per_block()
        wires: Dict[WorkerWithDpRank, str] = {}
        extra: Dict[WorkerWithDpRank, float] = {}
        for cand in cands:
            wire = str(self._instance_meta(cand.worker_id, "kv_wire") or "inline")
            wires[cand] = wire
            extra[cand] = (
                self.bandwidth.transfer_seconds(wire, move_bytes)
                / cfg.prefill_block_time_s
            )
        decision = None
        if self.kv_router is not None:
            decision = self.kv_router.score_tokens(
                tokens, cands, extra_costs=extra,
                hashes=hashes if cacheable else [],
            )
            chosen = decision.worker
            overlap = decision.overlap_blocks
            remote_cost = decision.logits[chosen]
        else:
            # round-robin pools still price the wire: cheapest wire wins
            chosen = min(cands, key=lambda c: (extra[c], c))
            overlap = 0
            remote_cost = query_blocks + extra[chosen]
        wire = wires[chosen]
        est_transfer_s = self.bandwidth.transfer_seconds(wire, move_bytes)
        if cfg.deflect:
            # load-aware valve: the hop must beat (1+margin)x local prefill
            local_cost = max(query_blocks - decode_overlap_blocks, 1)
            if remote_cost > (1.0 + cfg.deflect_margin) * local_cost:
                return self._record_deflect(req, "load_skew")
        if decision is not None:
            # taking the hop: NOW commit the route bookkeeping the scoring
            # pass deliberately skipped
            self.kv_router.commit_route(
                decision, hashes if cacheable else []
            )
        address = self._instance_meta(chosen.worker_id, "transfer_address")
        # streamed dispatch only targets rank 0: the transfer server serves
        # engines[0]'s cache, so a dp_rank>0 clone's blocks would never
        # appear on the advertised address and the decode pull would stall
        # out its wait budget before recomputing
        streamed = bool(
            cfg.streamed and address and cacheable and chosen.dp_rank == 0
        )
        return PrefillPlan(
            worker_id=chosen.worker_id,
            dp_rank=chosen.dp_rank,
            overlap_blocks=overlap,
            query_blocks=query_blocks,
            transfer_address=address if streamed else None,
            wire=wire,
            streamed=streamed,
            est_transfer_s=est_transfer_s,
            hashes=[int(h) for h in hashes[:query_blocks]] if cacheable else [],
        )

    def _prefill_clone(self, req: PreprocessedRequest) -> PreprocessedRequest:
        preq = PreprocessedRequest.from_obj(req.to_obj())
        preq.stop.max_tokens = 1
        preq.stop.min_tokens = 0
        preq.stop.stop_strings = []
        preq.annotations["disagg"] = "prefill"
        return preq

    def start_streamed_prefill(
        self, req: PreprocessedRequest, context: Context, plan: PrefillPlan
    ):
        """Fire the max_tokens=1 prefill clone WITHOUT waiting for it: the
        caller dispatches the decode request immediately with a streamed
        kv_transfer handshake, so the decode side's block-window pull
        overlaps this prefill's compute. The clone's sampled token is
        dropped (the decode worker samples the first token itself from the
        imported KV); its only job is producing the KV blocks. Returns the
        background task (bounded: max_tokens=1 finishes on its own)."""
        preq = self._prefill_clone(req)
        preq.annotations["dp_rank"] = plan.dp_rank

        async def drive() -> None:
            get_flight_recorder().record(
                preq.request_id, "prefill_streamed",
                worker=f"{plan.worker_id:016x}", wire=plan.wire,
                est_transfer_s=round(plan.est_transfer_s, 6),
            )
            try:
                stream = await self.client.generate(
                    preq.to_obj(), context.child(), plan.worker_id
                )
                async for item in stream:
                    out = (
                        item if isinstance(item, BackendOutput)
                        else BackendOutput.from_obj(item)
                    )
                    if out.finish_reason is not None:
                        break
            except Exception:
                # decode side recomputes whatever never streams over — the
                # request still completes, just without the overlap win
                log.exception(
                    "streamed prefill failed for %s; decode side recomputes",
                    preq.request_id[:8],
                )

        # spawn_bg: a swallowed prefill failure would silently serialize
        # every streamed request behind the decode-side wait budget
        return spawn_bg(drive())

    async def run_prefill(
        self, req: PreprocessedRequest, context: Context,
        plan: Optional[PrefillPlan] = None,
    ) -> Optional[BackendOutput]:
        """Send the max_tokens=1 clone to a prefill worker.

        Returns the prefill output (first token + kv_transfer metadata), or
        None if prefill failed/unavailable (caller falls back to aggregated).

        ``plan`` (from :meth:`plan`) pins the transfer-cost-aware worker
        choice; without one the legacy overlap-only scheduling applies.
        """
        assert self.client is not None
        preq = self._prefill_clone(req)

        # trace hop: the prefill dispatch is its own span, and the prefill
        # worker's spans parent on IT (frontend -> router.prefill -> worker)
        tracer = get_tracer()
        span = None
        if tracer.enabled:
            span = tracer.span(
                "router.prefill",
                traceparent=preq.annotations.get("traceparent"),
                request_id=preq.request_id,
            )
            span.__enter__()
            preq.annotations["traceparent"] = span.traceparent()
        instance_id: Optional[int] = None
        try:
            if plan is not None and plan.worker_id is not None:
                instance_id = plan.worker_id
                preq.annotations["dp_rank"] = plan.dp_rank
                if span is not None:
                    span.set(
                        worker=f"{instance_id:016x}",
                        dp_rank=plan.dp_rank,
                        overlap_blocks=plan.overlap_blocks,
                        wire=plan.wire,
                        est_transfer_s=round(plan.est_transfer_s, 6),
                    )
            elif self.kv_router is not None and self.client.instances:
                # dp-aware like the decode path (scheduler.rs:543-560): every
                # (instance, dp_rank) is a candidate, and the chosen rank rides
                # the annotation so the worker's DpEngineGroup dispatches to it
                cands = self._candidates()
                decision = self.kv_router.schedule_tokens(preq.token_ids, cands)
                instance_id = decision.worker.worker_id
                preq.annotations["dp_rank"] = decision.worker.dp_rank
                if span is not None:
                    span.set(
                        worker=f"{instance_id:016x}",
                        dp_rank=decision.worker.dp_rank,
                        overlap_blocks=decision.overlap_blocks,
                    )
            get_flight_recorder().record(
                preq.request_id, "prefill_routed",
                worker=(f"{instance_id:016x}" if instance_id is not None
                        else "round-robin"),
            )
            try:
                stream = await self.client.generate(preq.to_obj(), context.child(), instance_id)
                last: Optional[BackendOutput] = None
                async for item in stream:
                    out = item if isinstance(item, BackendOutput) else BackendOutput.from_obj(item)
                    last = out
                    if out.finish_reason is not None:
                        break
                if last is not None and instance_id is not None:
                    last.annotations[ANNOTATION_PREFILL_WORKER_ID] = instance_id
                return last
            except NoResponders:
                log.info("prefill pool unavailable; falling back to aggregated")
                if span is not None:
                    span.status = "ERROR"
                    span.set(error="no responders")
                return None
            except Exception as e:
                if is_terminal(e):
                    # typed 4xx-class failure (context length, guided
                    # grammar, ...): the request itself is wrong, so the
                    # aggregated path would only re-run the same doomed
                    # prefill and fail again — surface it to the client now
                    if span is not None:
                        span.status = "ERROR"
                        span.set(error=repr(e))
                    raise
                log.exception("prefill failed; falling back to aggregated")
                if span is not None:
                    span.status = "ERROR"
                    span.set(error=repr(e))
                return None
        finally:
            if span is not None:
                span.__exit__(None, None, None)

    async def stop(self) -> None:
        if self.kv_router is not None:
            await self.kv_router.stop()
        if self.client is not None:
            await self.client.stop()
