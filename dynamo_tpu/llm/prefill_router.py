"""PrefillRouter: disaggregated prefill/decode orchestration on the frontend.

Analog of the reference's PrefillRouter (lib/llm/src/kv_router/
prefill_router.rs:102,505 + docs/design_docs/disagg_serving.md): when a
prefill pool is registered for a model, each request is first sent to a
prefill worker as a clone with ``max_tokens=1``; the first token streams to
the client immediately, and the decode request carries the prefill worker's
KV-transfer metadata (address + block hashes) plus the first token as prior
context. If no prefill pool exists (elastic xPyD: pools scale to zero) the
request falls through to the aggregated path — runtime-reconfigurable
disaggregation, like the reference (disagg_serving.md:67-69).
"""

from __future__ import annotations

from typing import Optional

from ..kv_router import KvRouter, KvRouterConfig, WorkerWithDpRank
from ..runtime.component import Client, RouterMode
from ..runtime.engine import Context
from ..runtime.errors import is_terminal
from ..runtime.flight_recorder import get_flight_recorder
from ..runtime.logging import get_logger
from ..runtime.request_plane.tcp import NoResponders
from ..runtime.tracing import get_tracer
from .model_card import ModelDeploymentCard
from .preprocessor import ANNOTATION_PREFILL_WORKER_ID
from .protocols.common import BackendOutput, PreprocessedRequest

log = get_logger("llm.prefill_router")


class PrefillRouter:
    def __init__(
        self,
        runtime,
        card: ModelDeploymentCard,
        kv_router_config: Optional[KvRouterConfig] = None,
    ):
        self.runtime = runtime
        self.card = card  # the *prefill* pool's card
        self.client: Optional[Client] = None
        self.kv_router: Optional[KvRouter] = None
        self.kv_router_config = kv_router_config

    async def start(self) -> "PrefillRouter":
        endpoint = (
            self.runtime.namespace(self.card.namespace)
            .component(self.card.component)
            .endpoint(self.card.endpoint)
        )
        self.client = await endpoint.client(RouterMode.ROUND_ROBIN)
        if self.kv_router_config is not None:
            self.kv_router = await KvRouter(
                self.runtime.event_plane,
                self.card.namespace,
                self.card.component,
                block_size=self.card.kv_block_size,
                config=self.kv_router_config,
                metrics=getattr(self.runtime, "metrics", None),
            ).start()
        return self

    @property
    def has_workers(self) -> bool:
        return self.client is not None and bool(self.client.instances)

    async def run_prefill(
        self, req: PreprocessedRequest, context: Context
    ) -> Optional[BackendOutput]:
        """Send the max_tokens=1 clone to a prefill worker.

        Returns the prefill output (first token + kv_transfer metadata), or
        None if prefill failed/unavailable (caller falls back to aggregated).
        """
        assert self.client is not None
        preq = PreprocessedRequest.from_obj(req.to_obj())
        preq.stop.max_tokens = 1
        preq.stop.min_tokens = 0
        preq.stop.stop_strings = []
        preq.annotations["disagg"] = "prefill"

        # trace hop: the prefill dispatch is its own span, and the prefill
        # worker's spans parent on IT (frontend -> router.prefill -> worker)
        tracer = get_tracer()
        span = None
        if tracer.enabled:
            span = tracer.span(
                "router.prefill",
                traceparent=preq.annotations.get("traceparent"),
                request_id=preq.request_id,
            )
            span.__enter__()
            preq.annotations["traceparent"] = span.traceparent()
        instance_id: Optional[int] = None
        try:
            if self.kv_router is not None and self.client.instances:
                # dp-aware like the decode path (scheduler.rs:543-560): every
                # (instance, dp_rank) is a candidate, and the chosen rank rides
                # the annotation so the worker's DpEngineGroup dispatches to it
                cands = []
                for iid, inst in self.client.instances.items():
                    dp = int(inst.metadata.get("data_parallel_size", 1) or 1)
                    for r in range(dp):
                        cands.append(WorkerWithDpRank(iid, r))
                decision = self.kv_router.schedule_tokens(preq.token_ids, cands)
                instance_id = decision.worker.worker_id
                preq.annotations["dp_rank"] = decision.worker.dp_rank
                if span is not None:
                    span.set(
                        worker=f"{instance_id:016x}",
                        dp_rank=decision.worker.dp_rank,
                        overlap_blocks=decision.overlap_blocks,
                    )
            get_flight_recorder().record(
                preq.request_id, "prefill_routed",
                worker=(f"{instance_id:016x}" if instance_id is not None
                        else "round-robin"),
            )
            try:
                stream = await self.client.generate(preq.to_obj(), context.child(), instance_id)
                last: Optional[BackendOutput] = None
                async for item in stream:
                    out = item if isinstance(item, BackendOutput) else BackendOutput.from_obj(item)
                    last = out
                    if out.finish_reason is not None:
                        break
                if last is not None and instance_id is not None:
                    last.annotations[ANNOTATION_PREFILL_WORKER_ID] = instance_id
                return last
            except NoResponders:
                log.info("prefill pool unavailable; falling back to aggregated")
                if span is not None:
                    span.status = "ERROR"
                    span.set(error="no responders")
                return None
            except Exception as e:
                if is_terminal(e):
                    # typed 4xx-class failure (context length, guided
                    # grammar, ...): the request itself is wrong, so the
                    # aggregated path would only re-run the same doomed
                    # prefill and fail again — surface it to the client now
                    if span is not None:
                        span.status = "ERROR"
                        span.set(error=repr(e))
                    raise
                log.exception("prefill failed; falling back to aggregated")
                if span is not None:
                    span.status = "ERROR"
                    span.set(error=repr(e))
                return None
        finally:
            if span is not None:
                span.__exit__(None, None, None)

    async def stop(self) -> None:
        if self.kv_router is not None:
            await self.kv_router.stop()
        if self.client is not None:
            await self.client.stop()
