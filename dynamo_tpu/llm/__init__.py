"""LLM serving layer: protocols, preprocessing, discovery, HTTP frontend."""

from .backend import Backend, StopStringJail
from .discovery import ModelManager, ModelPipeline, ModelWatcher
from .engines import EchoEngine
from .migration import Migration
from .model_card import (
    MDC_PREFIX,
    ModelDeploymentCard,
    ModelRuntimeConfig,
    mdc_key,
    model_slug,
)
from .preprocessor import OpenAIPreprocessor
from .serve import register_llm
from .tokenizer import ByteTokenizer, DecodeStream, HFTokenizer, load_tokenizer

__all__ = [
    "Backend",
    "ByteTokenizer",
    "DecodeStream",
    "EchoEngine",
    "HFTokenizer",
    "MDC_PREFIX",
    "Migration",
    "ModelDeploymentCard",
    "ModelManager",
    "ModelPipeline",
    "ModelRuntimeConfig",
    "ModelWatcher",
    "OpenAIPreprocessor",
    "StopStringJail",
    "load_tokenizer",
    "mdc_key",
    "model_slug",
    "register_llm",
]
