"""OpenAI-compatible HTTP frontend (aiohttp) with SSE streaming.

Analog of the reference's axum HTTP service (lib/llm/src/http/service/
service_v2.rs + openai.rs handlers): /v1/chat/completions, /v1/completions,
/v1/models plus /health, /live, /metrics. Includes the reference's operational
behaviors: client-disconnect -> request cancellation (disconnect.rs), busy
threshold -> 503 (busy_threshold.rs), per-model TTFT/ITL metrics
(service/metrics.rs). Chat and text completions share one request path; the
only per-endpoint differences are request parsing and delta generation.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import AsyncIterator, Optional

from aiohttp import web
from aiohttp.client_exceptions import ClientConnectionResetError

from ...runtime import metrics as M
from ...runtime.engine import Context
from ...runtime.errors import InvalidRequestError, http_status_for
from ...runtime.flight_recorder import get_flight_recorder
from ...runtime.logging import get_logger
from ...runtime.request_plane.tcp import NoResponders
from ...runtime.resilience import CircuitBreaker
from ...runtime.slo import (
    SLA_HEADER,
    ANNOTATION_SLA,
    SlaSpec,
    SloAccountant,
    debug_slo_payload,
    resolve_sla,
)
from ...runtime.tracing import Tracer, get_tracer
from ..audit import AuditBus
from ...parsers import get_reasoning_parser, get_tool_parser
from ..discovery import ModelManager, ModelPipeline
from ..protocols.common import BackendOutput, PreprocessedRequest
from ..protocols.delta import (
    ChatDeltaGenerator,
    CompletionDeltaGenerator,
    aggregate_chat,
    aggregate_completion,
)
from ..protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    EmbeddingData,
    EmbeddingRequest,
    EmbeddingResponse,
    ModelInfo,
    ModelList,
    ResponseMessage,
    ResponseObject,
    ResponseOutputText,
    ResponsesRequest,
    ResponseUsage,
    Usage,
    new_request_id,
)

log = get_logger("llm.http")

SSE_HEADERS = {
    "Content-Type": "text/event-stream",
    "Cache-Control": "no-cache",
    "Connection": "keep-alive",
    "X-Accel-Buffering": "no",
}

_DISCONNECT = (ConnectionResetError, ClientConnectionResetError)


def _safe_parser(factory, name):
    """A bad parser name on a model card must degrade to pass-through, not
    turn every chat request into a 500."""
    try:
        return factory(name)
    except ValueError:
        log.warning("unknown parser %r on model card; passing text through", name)
        return None


def _stream_fail_status(e: Exception) -> tuple:
    """(status, err_type) for a request that died before/while streaming.
    Classification is by TYPE (runtime/errors.py taxonomy) locally and by
    the typed ``code`` the request plane propagates for worker-side errors
    — never by substring-matching exception messages."""
    return http_status_for(e)


def _preprocess_err_type(e: Exception) -> str:
    """OpenAI-style error type for a preprocess-stage failure: typed errors
    (ContextLengthError, GuidedRejectedError, ...) carry their own wire
    type; a plain ValueError is a generic invalid request."""
    if isinstance(e, InvalidRequestError):
        return e.err_type
    return "invalid_request_error"


def _error(
    status: int,
    message: str,
    err_type: str = "invalid_request_error",
    headers: Optional[dict] = None,
) -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": err_type, "code": status}},
        status=status, headers=headers,
    )


def _sse_error_event(message: str, err_type: str) -> bytes:
    payload = json.dumps({"error": {"message": message, "type": err_type}})
    return f"data: {payload}\n\n".encode()


def _openapi_spec() -> dict:
    """OpenAPI 3.1 description of the serving surface (reference:
    lib/llm/src/http/service/openapi_docs.rs). Request/response bodies are
    the OpenAI-compatible schemas; kept summary-level here — the wire types
    live in llm/protocols/openai.py (pydantic) and can regenerate full
    schemas on demand."""

    def op(summary, streaming=False, tag="openai"):
        out = {
            "summary": summary, "tags": [tag],
            "responses": {"200": {"description": "success"}},
        }
        if streaming:
            out["description"] = (
                "Set stream=true for text/event-stream SSE chunks."
            )
        return out

    return {
        "openapi": "3.1.0",
        "info": {"title": "dynamo-tpu OpenAI-compatible frontend",
                 "version": "1.0"},
        "paths": {
            "/clear_kv_blocks": {"post": op("Reset worker KV caches (g1/g2/g3)", tag="admin")},
            "/v1/chat/completions": {"post": op("Chat completion", True)},
            "/v1/completions": {"post": op("Text completion", True)},
            "/v1/embeddings": {"post": op("Embeddings")},
            "/v1/responses": {"post": op("Responses API", True)},
            "/v1/images/generations": {"post": op("Image generation")},
            "/v1/models": {"get": op("List served models")},
            "/health": {"get": op("Service + model health", tag="system")},
            "/live": {"get": op("Liveness", tag="system")},
            "/metrics": {"get": op("Prometheus metrics", tag="system")},
            "/debug/requests": {"get": op(
                "Flight-recorder request timelines", tag="system"
            )},
            "/debug/slo": {"get": op(
                "Per-class SLO attainment / burn-rate ledger", tag="system"
            )},
            "/debug/fleet": {"get": op(
                "Merged fleet snapshot (fan-out to every worker)",
                tag="system",
            )},
            "/openapi.json": {"get": op("This document", tag="system")},
        },
    }


class HttpService:
    def __init__(
        self,
        manager: ModelManager,
        metrics_scope: Optional[M.MetricsScope] = None,
        busy_threshold: Optional[int] = None,
        host: str = "0.0.0.0",
        port: int = 8000,
        tracer: Optional[Tracer] = None,
        audit_bus: Optional[AuditBus] = None,
        stats_hook=None,
        tls_cert: Optional[str] = None,
        tls_key: Optional[str] = None,
        request_template=None,
    ):
        # stats_hook(prompt_tokens, completion_tokens, ttft_s, itl_s) fires
        # once per completed generation — the planner's demand/correction
        # feed (planner/metrics_source.py FrontendStatsPublisher)
        self.stats_hook = stats_hook
        self.manager = manager
        self.host = host
        self.port = port
        self.busy_threshold = busy_threshold
        # observability: W3C traceparent in -> spans out (runtime/tracing.py,
        # reference logging.rs:206-270); audit records per policy (llm/audit.py)
        self.tracer = tracer if tracer is not None else get_tracer()
        self.audit = audit_bus if audit_bus is not None else AuditBus()
        self.inflight = 0
        self.metrics = metrics_scope or M.MetricsScope()
        self._requests = self.metrics.counter(
            M.REQUESTS_TOTAL, "requests", extra_labels=(M.LABEL_MODEL, "status")
        )
        self._inflight_g = self.metrics.gauge(M.INFLIGHT_REQUESTS, "in-flight requests")
        self._duration = self.metrics.histogram(
            M.REQUEST_DURATION_SECONDS, "end-to-end request duration",
            extra_labels=(M.LABEL_MODEL, M.LABEL_SLA_CLASS),
            buckets=(0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     120.0),
        )
        # SLO accounting (runtime/slo.py): this frontend's client-observed
        # ledger — attainment/burn-rate/goodput per (model, sla_class), fed
        # from the same stream observation that drives the histograms above
        # and served on /debug/slo. Worker-side engines keep their own
        # ledger from milestone timestamps (StatusServer /debug/slo).
        self.slo = SloAccountant(metrics=self.metrics)
        # critical-path attribution (runtime/attribution.py): every finished
        # request's flight-recorder timeline decomposed into phases that sum
        # to e2e, rolled up per (model, class) window — "where does p99 go"
        # without reading timelines by hand. Served in /debug/fleet.
        from ...runtime.attribution import AttributionAggregator

        self.attribution = AttributionAggregator(metrics=self.metrics)
        self._ttft = self.metrics.histogram(
            M.TTFT_SECONDS, "time to first token",
            extra_labels=(M.LABEL_MODEL, M.LABEL_SLA_CLASS),
            buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
        )
        self._itl = self.metrics.histogram(
            M.ITL_SECONDS, "inter-token latency",
            extra_labels=(M.LABEL_MODEL, M.LABEL_SLA_CLASS),
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0),
        )
        self._input_tokens = self.metrics.counter(
            M.INPUT_TOKENS, "input tokens", extra_labels=(M.LABEL_MODEL,)
        )
        self._output_tokens = self.metrics.counter(
            M.OUTPUT_TOKENS, "output tokens", extra_labels=(M.LABEL_MODEL,)
        )
        # HTTPS serving (reference frontend --tls-cert-path/--tls-key-path):
        # both paths or neither; the context is built at start()
        if bool(tls_cert) != bool(tls_key):
            raise ValueError("tls_cert and tls_key must be given together")
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        # optional llm.request_template.RequestTemplate: fills model /
        # temperature / max_completion_tokens on requests that omit them
        self.request_template = request_template
        # per-model circuit breaker over worker availability: repeated
        # no-responders (migration exhausted) trip it, and while open the
        # frontend sheds load with busy-503 + Retry-After instead of
        # burning a full migration cycle per doomed request. Tunable via
        # DTPU_CB_FRONTEND (runtime/resilience.py); state/transition
        # metrics ride this service's /metrics registry.
        self._model_breakers: dict = {}
        self._runner: Optional[web.AppRunner] = None
        self.app = self._build_app()

    def _breaker(self, model: str) -> CircuitBreaker:
        cb = self._model_breakers.get(model)
        if cb is None:
            cb = self._model_breakers[model] = CircuitBreaker.from_env(
                "frontend", name=f"frontend.{model}",
                failure_threshold=5, failure_rate=0.5, window_s=10.0,
                reset_timeout_s=2.0, metrics=self.metrics,
            )
        return cb

    def _check_circuit(self, model: str) -> Optional[web.Response]:
        """Busy-503 with Retry-After while the model's circuit is open."""
        cb = self._breaker(model)
        if cb.allow():
            return None
        retry_after = max(1, int(cb.retry_after_s() + 0.999))
        self._requests.inc(model=model, status="503")
        return _error(
            503, f"no workers responding for {model!r} (circuit open)",
            "service_unavailable", headers={"Retry-After": str(retry_after)},
        )

    def _build_app(self) -> web.Application:
        app = web.Application(client_max_size=64 * 1024 * 1024)
        app.router.add_post("/v1/chat/completions", self.chat_completions)
        app.router.add_post("/v1/completions", self.completions)
        app.router.add_post("/v1/embeddings", self.embeddings)
        app.router.add_post("/v1/responses", self.responses)
        app.router.add_post("/v1/images/generations", self.images)
        app.router.add_post("/clear_kv_blocks", self.clear_kv_blocks)
        app.router.add_get("/v1/models", self.models)
        app.router.add_get("/health", self.health)
        app.router.add_get("/live", self.live)
        app.router.add_get("/metrics", self.metrics_handler)
        app.router.add_get("/openapi.json", self.openapi)
        app.router.add_get("/docs", self.docs)
        app.router.add_get("/debug/requests", self.debug_requests)
        app.router.add_get("/debug/slo", self.debug_slo)
        app.router.add_get("/debug/fleet", self.debug_fleet)
        return app

    async def start(self) -> str:
        ssl_ctx = None
        if self.tls_cert:
            import ssl

            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_ctx.load_cert_chain(self.tls_cert, self.tls_key)
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port, ssl_context=ssl_ctx)
        await site.start()
        actual = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        self.port = actual
        log.info(
            "OpenAI %s frontend listening on %s:%d",
            "HTTPS" if ssl_ctx else "HTTP", self.host, actual,
        )
        return f"{self.host}:{actual}"

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        # short-lived processes would otherwise drop a partial span batch;
        # shutdown also drains the OTLP exporter's background queue
        self.tracer.shutdown()

    # -- aux handlers --------------------------------------------------------
    async def health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "healthy", "models": self.manager.list_models()})

    async def live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def metrics_handler(self, request: web.Request) -> web.Response:
        # attainment/burn gauges are derived from rolling windows: refresh
        # them at scrape time so they track the scrape clock, not traffic
        self.slo.export_metrics()
        return web.Response(body=self.metrics.expose(), content_type="text/plain")

    async def debug_requests(self, request: web.Request) -> web.Response:
        """Flight-recorder timelines (runtime/flight_recorder.py):
        ``/debug/requests`` lists recent requests most-recent-first,
        ``?id=<request_id>`` returns one timeline (404 once evicted)."""
        from ...runtime.flight_recorder import debug_requests_payload

        status, payload = debug_requests_payload(
            get_flight_recorder(),
            request.query.get("id"), request.query.get("limit"),
        )
        return web.json_response(payload, status=status)

    async def debug_slo(self, request: web.Request) -> web.Response:
        """Per-(model, sla_class) attainment/burn-rate ledger
        (runtime/slo.py) — the client-observed view this frontend keeps."""
        return web.json_response(debug_slo_payload(self.slo))

    async def debug_fleet(self, request: web.Request) -> web.Response:
        """One-call fleet snapshot (llm/fleet.py): fan out to every
        discovered worker's ``/debug/worker``, merge with the frontend's
        own SLO/attribution/breaker view. Unreachable workers come back
        ``stale``-marked, never as a 500 — a degraded fleet is exactly
        when this endpoint matters."""
        from ..fleet import fleet_snapshot

        doc = await fleet_snapshot(
            self.manager.pipelines(),
            frontend={
                "slo": self.slo.snapshot(),
                "attribution": self.attribution.snapshot(),
                "model_breakers": {
                    m: cb.state
                    for m, cb in sorted(self._model_breakers.items())
                },
            },
        )
        return web.json_response(doc)

    def _resolve_sla(self, request: web.Request, body_class: Optional[str],
                     pipeline: ModelPipeline):
        """(spec, error_response): the request's SLA class from the body
        ``sla`` field, the x-dtpu-sla header, or the default class — with
        the model card's per-class target overrides applied. An unknown
        class is a 400 (silently serving untracked would defeat the
        accounting plane)."""
        name = body_class or request.headers.get(SLA_HEADER)
        spec = resolve_sla(name, pipeline.card.runtime_config.sla_classes)
        if spec is None:
            return None, _error(
                400, f"unknown SLA class {name!r}", "invalid_request_error"
            )
        return spec, None

    async def models(self, request: web.Request) -> web.Response:
        data = ModelList(
            data=[ModelInfo(id=m, created=int(time.time())) for m in self.manager.list_models()]
        )
        return web.json_response(data.model_dump())

    async def clear_kv_blocks(self, request: web.Request) -> web.Response:
        """Runtime cache reset across workers (reference
        lib/llm/src/http/clear_kv_blocks.rs + block_manager/controller.rs).
        Body (all optional): {"model": name, "levels": ["g1","g2","g3"]}.
        Fans out to every instance's ``clear_kv_blocks`` endpoint (served
        beside generate under the same instance id) and reports per-worker
        results; workers without the endpoint are reported, not fatal."""
        try:
            body = await request.json() if request.can_read_body else {}
        except json.JSONDecodeError:
            return _error(400, "invalid JSON body")
        model = body.get("model")
        levels = body.get("levels")
        if levels is not None and (
            not isinstance(levels, list)
            or not all(isinstance(lv, str) for lv in levels)
        ):
            # a bare string would iterate character-wise downstream and
            # silently clear nothing — reject loudly
            return _error(400, 'levels must be a list of strings, e.g. ["g1"]')
        pipelines = (
            [self.manager.get(model)] if model else self.manager.pipelines()
        )
        if model and pipelines[0] is None:
            return _error(404, f"model {model!r} not found", "model_not_found")
        results: dict = {}
        for pipe in pipelines:
            if pipe is None or pipe.client is None:
                continue
            card = pipe.card
            endpoint = (
                pipe.runtime.namespace(card.namespace)
                .component(card.component)
                .endpoint("clear_kv_blocks")
            )
            client = await endpoint.client()
            per_worker: dict = {}
            try:
                targets = pipe.client.instance_ids()
                # the fresh client's discovery snapshot arrives async; give
                # it a moment to see the instances the generate client sees
                try:
                    await client.wait_for_instances(len(targets), timeout=5.0)
                except TimeoutError:
                    pass
                for iid in targets:
                    wk = f"{iid:016x}"
                    if iid not in client.instances:
                        per_worker[wk] = {"error": "no clear_kv_blocks endpoint"}
                        continue
                    try:
                        async for item in await client.generate(
                            {"levels": levels}, instance_id=iid
                        ):
                            per_worker[wk] = item
                    except (NoResponders, ConnectionError, OSError) as e:
                        per_worker[wk] = {"error": str(e)}
            finally:
                await client.stop()
            results[card.name] = per_worker
        return web.json_response({"cleared": results})

    async def openapi(self, request: web.Request) -> web.Response:
        """Machine-readable API description (reference
        http/service/openapi_docs.rs serves the same via utoipa)."""
        return web.json_response(_openapi_spec())

    async def docs(self, request: web.Request) -> web.Response:
        """Minimal human-readable endpoint index (the swagger-ui analog
        without vendored JS: zero-egress images cannot fetch the bundle)."""
        spec = _openapi_spec()
        rows = "".join(
            f"<li><code>{method.upper()} {path}</code> — "
            f"{op.get('summary', '')}</li>"
            for path, ops in spec["paths"].items()
            for method, op in ops.items()
        )
        return web.Response(
            text=(
                f"<html><body><h1>{spec['info']['title']}</h1>"
                f"<p>spec: <a href='/openapi.json'>/openapi.json</a></p>"
                f"<ul>{rows}</ul></body></html>"
            ),
            content_type="text/html",
        )

    async def images(self, request: web.Request) -> web.Response:
        """/v1/images/generations (reference http/service/openai.rs:1638):
        routes the prompt to a model registered with model_type 'images';
        the worker returns base64 image payloads in annotations."""
        busy = self._check_capacity()
        if busy is not None:
            return busy
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        try:
            n = int(body.get("n", 1))
            prompt = str(body.get("prompt", ""))
            size = str(body.get("size", "1024x1024"))
            if n < 1 or n > 16:
                raise ValueError("n must be in [1, 16]")
        except (TypeError, ValueError) as e:
            return _error(400, f"invalid request: {e}")
        model = body.get("model")
        pipe = self.manager.get(model) if model else None
        if pipe is None or "images" not in (pipe.card.model_type or []):
            return _error(
                404, f"no image-generation model named {model!r}", "not_found"
            )
        preq = PreprocessedRequest(
            request_id=new_request_id("img"), model=model,
            token_ids=[], annotations={
                "op": "image", "prompt": prompt, "n": n, "size": size,
            },
        )
        circuit = self._check_circuit(model)
        if circuit is not None:
            return circuit
        cb = self._breaker(model)
        ctx = Context(preq.request_id)
        self.inflight += 1
        self._inflight_g.set(self.inflight)
        data = []
        ok = True
        try:
            async for out in pipe.generate_tokens(preq, ctx):
                ann = out.annotations or {}
                if out.finish_reason == "error":
                    # the engine's error frame carries the reason in the
                    # "error" annotation — surface it instead of returning
                    # 200 with an empty data list
                    ok = False
                    return await self._fail(
                        None, 502, ann.get("error") or "image generation failed",
                        "upstream_error",
                    )
                for img in ann.get("images", []):
                    data.append({"b64_json": img})
        except NoResponders:
            ok = False
            return await self._fail(None, 503, "no workers available",
                                    "service_unavailable")
        finally:
            cb.record(ok)
            ctx.stop_generating()
            self.inflight -= 1
            self._inflight_g.set(self.inflight)
        return web.json_response({"created": int(time.time()), "data": data})

    # -- shared request path -------------------------------------------------
    def _observed(
        self, stream: AsyncIterator[BackendOutput], model: str, t_start: float,
        prompt_tokens: int = 0, request_id: str = "",
        sla: Optional[SlaSpec] = None,
    ) -> AsyncIterator[BackendOutput]:
        """Wrap the token stream with TTFT/ITL observation. With an
        ``sla`` spec the samples land class-labeled and the stream's
        outcome feeds the frontend SLO ledger + the planner stats topic."""
        cls = sla.sla_class if sla is not None else ""

        async def gen():
            first_at = None
            last_at = None
            n_tokens = 0
            try:
                async for out in stream:
                    now = time.monotonic()
                    ann = out.annotations or {}
                    if "prefill_worker_id" in ann:
                        # disagg attribution: the prefill router stamps the
                        # remote prefill worker on its final frame
                        get_flight_recorder().record(
                            request_id, "prefill_done",
                            prefill_worker_id=ann["prefill_worker_id"],
                        )
                    if out.token_ids:
                        n_tokens += len(out.token_ids)
                        if first_at is None:
                            first_at = now
                            self._ttft.observe(
                                now - t_start, model=model, sla_class=cls
                            )
                            # the engine echoes the serving worker on its
                            # first-chunk metrics annotations
                            wid = {"worker_id": ann["worker_id"]} if (
                                "worker_id" in ann
                            ) else {}
                            get_flight_recorder().record(
                                request_id, "first_token",
                                ttft_ms=round((now - t_start) * 1e3, 3),
                                **wid,
                            )
                        elif last_at is not None:
                            self._itl.observe(
                                now - last_at, model=model, sla_class=cls
                            )
                        last_at = now
                    yield out
            finally:
                itl = (
                    (last_at - first_at) / (n_tokens - 1)
                    if first_at is not None and last_at and n_tokens > 1
                    else 0.0
                )
                met = None
                if sla is not None and first_at is not None:
                    met = self.slo.record(
                        model, sla,
                        ttft_s=first_at - t_start,
                        itl_s=(itl if n_tokens > 1 else None),
                        output_tokens=n_tokens,
                        e2e_s=time.monotonic() - t_start,
                    )
                if self.stats_hook is not None and first_at is not None:
                    try:
                        self.stats_hook(
                            prompt_tokens, n_tokens, first_at - t_start, itl,
                            **(
                                dict(
                                    sla_class=sla.sla_class,
                                    ttft_target_s=sla.ttft_target_s,
                                    itl_target_s=sla.itl_target_s,
                                    # the accountant's verdict rides along
                                    # so the planner's per-class attainment
                                    # can't drift from /debug/slo semantics
                                    sla_met=met,
                                )
                                if sla is not None else {}
                            ),
                        )
                    except Exception:
                        log.exception("stats hook failed")

        return gen()

    @staticmethod
    def _fan_choices(preq: PreprocessedRequest, n: int) -> list:
        """One PreprocessedRequest per choice. Each choice is an independent
        engine stream with its own request id (routing/migration track per
        stream); a set seed is offset per choice so choices actually differ
        (same-seed fan-out would sample n identical completions)."""
        if n <= 1:
            return [preq]
        import copy

        preqs = []
        for i in range(n):
            p = copy.deepcopy(preq)
            p.request_id = f"{preq.request_id}-c{i}"
            if p.sampling.seed is not None:
                p.sampling.seed += i
            preqs.append(p)
        return preqs

    @staticmethod
    async def _merged(streams):
        """Interleave n token streams as (stream_index, output) pairs in
        arrival order. One failing stream fails the merge (the caller's
        error path kills the surviving contexts)."""
        q: asyncio.Queue = asyncio.Queue()
        _DONE = object()

        async def pump(i, s):
            try:
                async for out in s:
                    await q.put((i, out, None))
            except BaseException as e:  # noqa: BLE001 — relayed, not dropped
                await q.put((i, _DONE, e))
            else:
                await q.put((i, _DONE, None))

        tasks = [asyncio.create_task(pump(i, s)) for i, s in enumerate(streams)]
        done = 0
        try:
            while done < len(streams):
                i, out, err = await q.get()
                if out is _DONE:
                    if err is not None:
                        raise err
                    done += 1
                    continue
                yield i, out
        finally:
            for t in tasks:
                t.cancel()

    async def _run(
        self,
        request: web.Request,
        preqs,
        pipeline: ModelPipeline,
        model: str,
        stream_mode: bool,
        delta_gens,
        aggregator,
        audit_handle=None,
        usage_chunk_factory=None,
        sla: Optional[SlaSpec] = None,
    ) -> web.StreamResponse:
        """Execute one generation request: routing, streaming, metrics, errors.

        ``preqs``/``delta_gens`` are parallel lists, one entry per choice
        (n>1 requests fan into n engine streams; reference delta.rs/jail.rs
        hold per-choice state). ``aggregator`` receives the list of streams.
        ``usage_chunk_factory`` builds the single trailing usage chunk for
        multi-choice streaming (single-choice generators emit their own)."""
        circuit = self._check_circuit(model)
        if circuit is not None:
            return circuit
        cb = self._breaker(model)
        ctxs = [Context(p.request_id) for p in preqs]
        self.inflight += 1
        self._inflight_g.set(self.inflight)
        status = "200"
        resp: Optional[web.StreamResponse] = None
        prompt_tokens = completion_tokens = 0
        rid = preqs[0].request_id
        # span parents on the client's traceparent header when present;
        # downstream hops (request plane -> worker) get it via annotations
        span = self.tracer.span(
            "http.generate",
            traceparent=request.headers.get("traceparent"),
            request_id=rid, model=model, streaming=stream_mode,
            n=len(preqs),
        )
        sla_ann = sla.to_annotation() if sla is not None else None
        for p in preqs:
            p.annotations["traceparent"] = span.traceparent()
            if sla_ann is not None:
                # the promise rides the request plane like the traceparent:
                # router, prefill router, engine and flight recorder all see
                # (sla_class, ttft/itl targets, deadline, receipt stamp)
                p.annotations[ANNOTATION_SLA] = dict(sla_ann)
        span.__enter__()
        flight = get_flight_recorder()
        flight.record(
            rid, "received",
            model=model, streaming=stream_mode, choices=len(preqs),
            **({"sla_class": sla.sla_class} if sla is not None else {}),
        )
        flight.record(rid, "tokenized", prompt_tokens=len(preqs[0].token_ids))
        fail_msg: Optional[str] = None
        fail_type = "internal_error"
        try:
            t0 = time.monotonic()
            streams = [
                self._observed(
                    pipeline.generate_tokens(p, c), model, t0,
                    prompt_tokens=len(p.token_ids), request_id=rid,
                    sla=sla,
                )
                for p, c in zip(preqs, ctxs)
            ]
            if stream_mode:
                resp = web.StreamResponse(headers=SSE_HEADERS)
                await resp.prepare(request)
                try:
                    if len(streams) == 1:
                        # hot path: no queue hop per token
                        async for out in streams[0]:
                            for chunk in delta_gens[0].on_output(out):
                                await resp.write(
                                    f"data: {chunk.model_dump_json(exclude_none=True)}\n\n".encode()
                                )
                    else:
                        async for i, out in self._merged(streams):
                            for chunk in delta_gens[i].on_output(out):
                                await resp.write(
                                    f"data: {chunk.model_dump_json(exclude_none=True)}\n\n".encode()
                                )
                        if usage_chunk_factory is not None:
                            chunk = usage_chunk_factory()
                            if chunk is not None:
                                await resp.write(
                                    f"data: {chunk.model_dump_json(exclude_none=True)}\n\n".encode()
                                )
                    await resp.write(b"data: [DONE]\n\n")
                    await resp.write_eof()
                except _DISCONNECT:
                    status = "499"
                    for c in ctxs:
                        c.kill()
                finally:
                    prompt_tokens = max(g.prompt_tokens for g in delta_gens)
                    completion_tokens = sum(g.completion_tokens for g in delta_gens)
                    if audit_handle is not None:
                        audit_handle.set_response({
                            "streamed": True,
                            "completion_tokens": completion_tokens,
                            "prompt_tokens": prompt_tokens,
                        })
                return resp
            result = await aggregator(streams)
            usage = result.usage
            if usage is not None:
                prompt_tokens, completion_tokens = usage.prompt_tokens, usage.completion_tokens
            if audit_handle is not None:
                audit_handle.set_response(result.model_dump(exclude_none=True))
            return web.json_response(result.model_dump(exclude_none=True))
        except NoResponders:
            status = "503"
            fail_msg, fail_type = "no workers available", "service_unavailable"
            return await self._fail(resp, 503, "no workers available", "service_unavailable")
        except asyncio.CancelledError:
            status = "499"
            for c in ctxs:
                c.kill()
            raise
        except Exception as e:
            log.exception("request %s failed", rid[:16])
            code, etype = _stream_fail_status(e)
            status = str(code)
            fail_msg, fail_type = str(e), etype
            return await self._fail(resp, code, str(e), etype)
        finally:
            self.inflight -= 1
            self._inflight_g.set(self.inflight)
            # only worker loss (503) counts against the circuit; application
            # errors mean the workers ARE responding
            cb.record(status != "503")
            if (
                sla is not None and status not in ("200", "499")
                and completion_tokens == 0
            ):
                # died before a first token: _observed never accounted it,
                # but a broken promise during an outage is exactly what the
                # client-observed ledger exists to surface (ttft unobserved
                # counts as a combined miss, not a ttft sample)
                self.slo.record(
                    model, sla, ttft_s=None, output_tokens=0,
                    e2e_s=time.monotonic() - t0,
                )
            self._requests.inc(model=model, status=status)
            self._duration.observe(
                time.monotonic() - t0, model=model,
                sla_class=(sla.sla_class if sla is not None else ""),
            )
            self._input_tokens.inc(prompt_tokens, model=model)
            self._output_tokens.inc(completion_tokens, model=model)
            for c in ctxs:
                c.stop_generating()
            span.set(status=status, completion_tokens=completion_tokens)
            if status not in ("200", "499"):
                # the handler converts errors to responses before the span
                # closes, so mark failure explicitly or OTLP status reads OK
                span.status = "ERROR"
            span.__exit__(None, None, None)
            # a failed request auto-dumps its timeline (flight_recorder.py);
            # 499 is the client hanging up, not a failure
            flight.finish(
                rid,
                error=(fail_msg if status not in ("200", "499") else None),
                error_class=fail_type,
                status=status, completion_tokens=completion_tokens,
            )
            self._observe_attribution(model, sla, rid, flight)
            if audit_handle is not None:
                audit_handle.emit()
                await self.audit.drain_async_sinks()

    def _observe_attribution(self, model, sla, rid, flight) -> None:
        """Fold the finished request's timeline into the rolling phase
        aggregates. The timeline is read back from the recorder AFTER
        finish() so engine-stamped milestones (queued, admitted, first
        token) that raced the frontend's view are included."""
        try:
            timeline = flight.timeline(rid)
            if timeline is not None:
                self.attribution.observe_flight(
                    model,
                    sla.sla_class if sla is not None else "unclassified",
                    timeline,
                )
        except Exception:
            log.exception("attribution observe failed for %s", rid[:16])

    async def _fail(
        self, resp: Optional[web.StreamResponse], status: int, msg: str, err_type: str
    ) -> web.StreamResponse:
        """Error path that respects an already-started SSE stream: once
        headers went out we can only append an error event, never start a
        second response on the same connection."""
        if resp is None:
            return _error(status, msg, err_type)
        try:
            await resp.write(_sse_error_event(msg, err_type))
            await resp.write_eof()
        except _DISCONNECT:
            pass
        return resp

    def _check_capacity(self) -> Optional[web.Response]:
        if self.busy_threshold is not None and self.inflight >= self.busy_threshold:
            return _error(503, "service busy", "service_unavailable")
        return None

    # -- endpoints -----------------------------------------------------------
    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        busy = self._check_capacity()
        if busy is not None:
            return busy
        try:
            body = await request.json()
            if self.request_template is not None:
                body = self.request_template.apply(body)
            req = ChatCompletionRequest.model_validate(body)
        except (json.JSONDecodeError, ValueError) as e:
            return _error(400, f"invalid request: {e}")
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            return _error(404, f"model '{req.model}' not found", "model_not_found")
        sla, sla_err = self._resolve_sla(request, req.sla, pipeline)
        if sla_err is not None:
            return sla_err
        try:
            preq = pipeline.preprocessor.preprocess_chat(req)
        except ValueError as e:
            return _error(400, str(e), _preprocess_err_type(e))

        include_usage = bool(req.stream_options and req.stream_options.include_usage)
        card = pipeline.card
        rid = preq.request_id
        preqs = self._fan_choices(preq, req.n)
        # parsers are stateful stream machines: one instance per choice
        reasoning_factory = lambda: _safe_parser(get_reasoning_parser, card.reasoning_parser)  # noqa: E731
        tool_factory = lambda: _safe_parser(get_tool_parser, card.tool_parser)  # noqa: E731
        gens = [
            ChatDeltaGenerator(
                rid, req.model,
                # multi-choice: one merged usage chunk at stream end instead
                # of one per choice
                include_usage and len(preqs) == 1,
                reasoning_parser=reasoning_factory(),
                tool_parser=tool_factory(),
                tool_choice=req.tool_choice,
                index=i,
            )
            for i in range(len(preqs))
        ]
        usage_chunk_factory = None
        if include_usage and len(preqs) > 1:
            from ..protocols.delta import merge_usage
            from ..protocols.openai import ChatCompletionChunk

            usage_chunk_factory = lambda: ChatCompletionChunk(  # noqa: E731
                id=rid, created=gens[0].created, model=req.model, choices=[],
                usage=merge_usage(gens),
            )
        if len(preqs) == 1:
            aggregator = lambda ss: aggregate_chat(  # noqa: E731
                rid, req.model, ss[0],
                reasoning_parser=reasoning_factory(),
                tool_parser=tool_factory(),
                tool_choice=req.tool_choice,
            )
        else:
            from ..protocols.delta import aggregate_chat_multi

            aggregator = lambda ss: aggregate_chat_multi(  # noqa: E731
                rid, req.model, ss,
                reasoning_parser_factory=reasoning_factory,
                tool_parser_factory=tool_factory,
                tool_choice=req.tool_choice,
            )
        audit_handle = self.audit.create_handle(body, rid, req.model, req.stream)
        return await self._run(
            request, preqs, pipeline, req.model, req.stream, gens,
            aggregator,
            audit_handle=audit_handle,
            usage_chunk_factory=usage_chunk_factory,
            sla=sla,
        )

    async def embeddings(self, request: web.Request) -> web.Response:
        """/v1/embeddings off a pooled forward (reference:
        http/service/openai.rs:641 embeddings handler + ModelType::Embedding).
        Accepts a string, list of strings, or pre-tokenized int lists."""
        busy = self._check_capacity()
        if busy is not None:
            return busy
        try:
            body = await request.json()
            req = EmbeddingRequest.model_validate(body)
        except (json.JSONDecodeError, ValueError) as e:
            return _error(400, f"invalid request: {e}")
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            return _error(404, f"model '{req.model}' not found", "model_not_found")
        inputs = req.input
        if isinstance(inputs, str) or (inputs and isinstance(inputs[0], int)):
            inputs = [inputs]
        if not inputs or any(
            (isinstance(item, (str, list)) and len(item) == 0) for item in inputs
        ):
            return _error(400, "input must not be empty")
        model = req.model
        # preprocess up front so client mistakes are 400s, not worker errors
        preqs = []
        try:
            for item in inputs:
                preq = pipeline.preprocessor.preprocess_completion(
                    CompletionRequest(model=model, prompt=item, max_tokens=1), item
                )
                preq.request_id = new_request_id("embd")
                preq.annotations["op"] = "embed"
                preqs.append(preq)
        except ValueError as e:
            return _error(400, str(e), _preprocess_err_type(e))
        circuit = self._check_circuit(model)
        if circuit is not None:
            return circuit
        cb = self._breaker(model)
        self.inflight += 1
        self._inflight_g.set(self.inflight)
        status = "200"
        prompt_tokens = 0

        async def one(preq) -> tuple:
            ctx = Context(preq.request_id)
            try:
                async for out in pipeline.generate_tokens(preq, ctx):
                    if out.annotations and "embedding" in out.annotations:
                        return (
                            out.annotations["embedding"],
                            out.annotations.get("input_tokens", len(preq.token_ids)),
                        )
            finally:
                ctx.stop_generating()
            return None, 0

        try:
            # independent pooled forwards: fan out, assemble by index.
            # return_exceptions so one failure doesn't leave siblings
            # running unsupervised after the error response goes out
            results = await asyncio.gather(
                *[one(p) for p in preqs], return_exceptions=True
            )
            for r in results:
                if isinstance(r, BaseException):
                    raise r
            data = []
            for i, (emb, n_toks) in enumerate(results):
                if emb is None:
                    status = "500"
                    return _error(
                        500, "worker returned no embedding (model may not "
                        "support embeddings)", "internal_error",
                    )
                prompt_tokens += n_toks
                if req.dimensions:
                    # renormalize after Matryoshka-style truncation so
                    # consumers still get unit vectors (OpenAI semantics)
                    emb = emb[: req.dimensions]
                    norm = sum(v * v for v in emb) ** 0.5
                    if norm > 0:
                        emb = [v / norm for v in emb]
                if req.encoding_format == "base64":
                    import base64
                    import struct

                    packed = struct.pack(f"<{len(emb)}f", *emb)
                    emb = base64.b64encode(packed).decode()
                data.append(EmbeddingData(index=i, embedding=emb))
            resp = EmbeddingResponse(
                data=data, model=model,
                usage=Usage(prompt_tokens=prompt_tokens, total_tokens=prompt_tokens),
            )
            return web.json_response(resp.model_dump(exclude_none=True))
        except NoResponders:
            status = "503"
            return _error(503, "no workers available", "service_unavailable")
        except Exception as e:
            log.exception("embeddings request failed")
            status = "500"
            return _error(500, str(e), "internal_error")
        finally:
            self.inflight -= 1
            self._inflight_g.set(self.inflight)
            cb.record(status != "503")
            self._requests.inc(model=model, status=status)
            self._input_tokens.inc(prompt_tokens, model=model)

    async def responses(self, request: web.Request) -> web.StreamResponse:
        """/v1/responses adapter (reference openai.rs:1142): the request is
        converted to a chat completion, run through the normal pipeline, and
        the aggregated result converted back to a Response object. Streaming
        emits Responses-style SSE events."""
        busy = self._check_capacity()
        if busy is not None:
            return busy
        try:
            body = await request.json()
            rreq = ResponsesRequest.model_validate(body)
            chat = rreq.to_chat()
        except (json.JSONDecodeError, ValueError) as e:
            return _error(400, f"invalid request: {e}")
        pipeline = self.manager.get(rreq.model)
        if pipeline is None:
            return _error(404, f"model '{rreq.model}' not found", "model_not_found")
        sla, sla_err = self._resolve_sla(request, rreq.sla, pipeline)
        if sla_err is not None:
            return sla_err
        try:
            preq = pipeline.preprocessor.preprocess_chat(chat)
        except ValueError as e:
            return _error(400, str(e), _preprocess_err_type(e))
        circuit = self._check_circuit(rreq.model)
        if circuit is not None:
            return circuit
        cb = self._breaker(rreq.model)
        rid = preq.request_id.replace("chatcmpl-", "resp_")
        ctx = Context(preq.request_id)
        created = int(time.time())

        def final_object(text: str, prompt_tokens: int, completion_tokens: int,
                        status: str = "completed") -> ResponseObject:
            return ResponseObject(
                id=rid, created_at=created, model=rreq.model, status=status,
                output=[ResponseMessage(
                    id=rid + "-msg0",
                    content=[ResponseOutputText(text=text)],
                )],
                usage=ResponseUsage(
                    input_tokens=prompt_tokens, output_tokens=completion_tokens,
                    total_tokens=prompt_tokens + completion_tokens,
                ),
            )

        self.inflight += 1
        self._inflight_g.set(self.inflight)
        status = "200"
        resp: Optional[web.StreamResponse] = None
        prompt_tokens = completion_tokens = 0
        span = self.tracer.span(
            "http.responses",
            traceparent=request.headers.get("traceparent"),
            request_id=preq.request_id, model=rreq.model, streaming=rreq.stream,
        )
        preq.annotations["traceparent"] = span.traceparent()
        if sla is not None:
            preq.annotations[ANNOTATION_SLA] = sla.to_annotation()
        span.__enter__()
        flight = get_flight_recorder()
        flight.record(
            preq.request_id, "received",
            model=rreq.model, streaming=rreq.stream, choices=1,
            **({"sla_class": sla.sla_class} if sla is not None else {}),
        )
        flight.record(
            preq.request_id, "tokenized", prompt_tokens=len(preq.token_ids)
        )
        fail_msg: Optional[str] = None
        fail_type = "internal_error"
        t0 = time.monotonic()
        try:
            stream = self._observed(
                pipeline.generate_tokens(preq, ctx), rreq.model, t0,
                prompt_tokens=len(preq.token_ids), request_id=preq.request_id,
                sla=sla,
            )
            if not rreq.stream:
                text = []
                async for out in stream:
                    if out.text:
                        text.append(out.text)
                    completion_tokens = out.cumulative_tokens or completion_tokens
                    if out.annotations and "input_tokens" in out.annotations:
                        prompt_tokens = out.annotations["input_tokens"]
                obj = final_object("".join(text), prompt_tokens, completion_tokens)
                return web.json_response(obj.model_dump(exclude_none=True))
            resp = web.StreamResponse(headers=SSE_HEADERS)
            await resp.prepare(request)

            async def emit(event: str, data: dict) -> None:
                await resp.write(
                    f"event: {event}\ndata: {json.dumps(data)}\n\n".encode()
                )

            text = []
            try:
                await emit("response.created", {
                    "type": "response.created",
                    "response": {"id": rid, "object": "response",
                                 "status": "in_progress", "model": rreq.model},
                })
                async for out in stream:
                    if out.text:
                        text.append(out.text)
                        await emit("response.output_text.delta", {
                            "type": "response.output_text.delta",
                            "item_id": rid + "-msg0", "delta": out.text,
                        })
                    completion_tokens = out.cumulative_tokens or completion_tokens
                    if out.annotations and "input_tokens" in out.annotations:
                        prompt_tokens = out.annotations["input_tokens"]
                obj = final_object("".join(text), prompt_tokens, completion_tokens)
                await emit("response.completed", {
                    "type": "response.completed",
                    "response": obj.model_dump(exclude_none=True),
                })
                await resp.write_eof()
            except _DISCONNECT:
                status = "499"
                ctx.kill()
            return resp
        except NoResponders:
            status = "503"
            fail_msg, fail_type = "no workers available", "service_unavailable"
            return await self._fail(resp, 503, "no workers available", "service_unavailable")
        except asyncio.CancelledError:
            status = "499"
            ctx.kill()
            raise
        except Exception as e:
            log.exception("responses request %s failed", preq.request_id[:16])
            code, etype = _stream_fail_status(e)
            status = str(code)
            fail_msg, fail_type = str(e), etype
            return await self._fail(resp, code, str(e), etype)
        finally:
            self.inflight -= 1
            self._inflight_g.set(self.inflight)
            cb.record(status != "503")
            if (
                sla is not None and status not in ("200", "499")
                and completion_tokens == 0
            ):
                self.slo.record(
                    rreq.model, sla, ttft_s=None, output_tokens=0,
                    e2e_s=time.monotonic() - t0,
                )
            self._requests.inc(model=rreq.model, status=status)
            self._duration.observe(
                time.monotonic() - t0, model=rreq.model,
                sla_class=(sla.sla_class if sla is not None else ""),
            )
            self._input_tokens.inc(prompt_tokens, model=rreq.model)
            self._output_tokens.inc(completion_tokens, model=rreq.model)
            ctx.stop_generating()
            span.set(status=status, completion_tokens=completion_tokens)
            if status not in ("200", "499"):
                span.status = "ERROR"
            span.__exit__(None, None, None)
            flight.finish(
                preq.request_id,
                error=(fail_msg if status not in ("200", "499") else None),
                error_class=fail_type,
                status=status, completion_tokens=completion_tokens,
            )
            self._observe_attribution(rreq.model, sla, preq.request_id, flight)

    async def completions(self, request: web.Request) -> web.StreamResponse:
        busy = self._check_capacity()
        if busy is not None:
            return busy
        try:
            body = await request.json()
            if self.request_template is not None:
                body = self.request_template.apply(body)
            req = CompletionRequest.model_validate(body)
        except (json.JSONDecodeError, ValueError) as e:
            return _error(400, f"invalid request: {e}")
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            return _error(404, f"model '{req.model}' not found", "model_not_found")
        sla, sla_err = self._resolve_sla(request, req.sla, pipeline)
        if sla_err is not None:
            return sla_err
        prompt = req.prompt
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], (list, str)):
            if len(prompt) > 1 or isinstance(prompt[0], list):
                return _error(400, "batched prompts not supported; send one request per prompt")
            prompt = prompt[0]
        try:
            preq = pipeline.preprocessor.preprocess_completion(req, prompt)
        except ValueError as e:
            return _error(400, str(e), _preprocess_err_type(e))

        include_usage = bool(req.stream_options and req.stream_options.include_usage)
        rid = preq.request_id
        preqs = self._fan_choices(preq, req.n)
        echo_text = prompt if (req.echo and isinstance(prompt, str)) else ""
        gens = [
            CompletionDeltaGenerator(
                rid, req.model, include_usage and len(preqs) == 1,
                text_offset=len(echo_text), index=i,
            )
            for i in range(len(preqs))
        ]
        usage_chunk_factory = None
        if include_usage and len(preqs) > 1:
            from ..protocols.delta import merge_usage
            from ..protocols.openai import CompletionResponse

            usage_chunk_factory = lambda: CompletionResponse(  # noqa: E731
                id=rid, created=gens[0].created, model=req.model, choices=[],
                usage=merge_usage(gens),
            )
        if len(preqs) == 1:
            aggregator = lambda ss: aggregate_completion(  # noqa: E731
                rid, req.model, ss[0], echo_text
            )
        else:
            from ..protocols.delta import aggregate_completion_multi

            aggregator = lambda ss: aggregate_completion_multi(  # noqa: E731
                rid, req.model, ss, echo_text
            )
        return await self._run(
            request, preqs, pipeline, req.model, req.stream, gens,
            aggregator,
            usage_chunk_factory=usage_chunk_factory,
            audit_handle=self.audit.create_handle(
                body, rid, req.model, req.stream
            ),
            sla=sla,
        )
