"""KServe v2 gRPC frontend (reference: lib/llm/src/grpc/service/kserve.rs)."""

from .service import KserveGrpcService

__all__ = ["KserveGrpcService"]
