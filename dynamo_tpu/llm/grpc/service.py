"""KServe v2 gRPC inference frontend.

Analog of the reference's KServe service (lib/llm/src/grpc/service/kserve.rs):
the same discovered model pipelines the OpenAI HTTP frontend serves, exposed
over the standard v2 inference protocol — text in ("text_input" BYTES tensor),
text out ("text_output"), with generation knobs as request parameters and
token streaming via ModelStreamInfer.

grpc_tools isn't in the image, so the message classes come from `protoc
--python_out` (protos/kserve.proto -> kserve_pb2.py) and the service is
registered with hand-rolled method handlers — ~30 lines that replace the
generated *_pb2_grpc stubs.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional

import grpc

from ...runtime.engine import Context
from ...runtime.logging import get_logger
from ..discovery import ModelManager
from ..protocols.openai import CompletionRequest
from . import kserve_pb2 as pb

log = get_logger("llm.grpc")

SERVICE_NAME = "inference.GRPCInferenceService"


def _param(params, name: str, default=None):
    p = params.get(name)
    if p is None:
        return default
    which = p.WhichOneof("parameter_choice")
    return getattr(p, which) if which else default


class KserveGrpcService:
    def __init__(self, manager: ModelManager, host: str = "0.0.0.0", port: int = 0):
        self.manager = manager
        self.host = host
        self.port = port
        self._server: Optional[grpc.aio.Server] = None

    # -- rpc handlers --------------------------------------------------------
    async def ServerLive(self, request, context) -> pb.ServerLiveResponse:
        return pb.ServerLiveResponse(live=True)

    async def ServerReady(self, request, context) -> pb.ServerReadyResponse:
        return pb.ServerReadyResponse(ready=bool(self.manager.list_models()))

    async def ModelReady(self, request, context) -> pb.ModelReadyResponse:
        pipe = self.manager.get(request.name)
        ready = pipe is not None and bool(pipe.client and pipe.client.instances)
        return pb.ModelReadyResponse(ready=ready)

    async def ModelMetadata(self, request, context) -> pb.ModelMetadataResponse:
        if self.manager.get(request.name) is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"model '{request.name}' not found"
            )
        resp = pb.ModelMetadataResponse(
            name=request.name, versions=["1"], platform="dynamo_tpu"
        )
        inp = resp.inputs.add()
        inp.name, inp.datatype = "text_input", "BYTES"
        inp.shape.append(1)
        out = resp.outputs.add()
        out.name, out.datatype = "text_output", "BYTES"
        out.shape.append(1)
        return resp

    def _to_preq(self, request: pb.ModelInferRequest):
        pipe = self.manager.get(request.model_name)
        if pipe is None:
            return None, None
        text = ""
        max_tokens = _param(request.parameters, "max_tokens")
        temperature = _param(request.parameters, "temperature")
        ignore_eos = _param(request.parameters, "ignore_eos")
        for t in request.inputs:
            if t.name == "text_input" and t.contents.bytes_contents:
                text = t.contents.bytes_contents[0].decode("utf-8", "replace")
            elif t.name == "max_tokens" and t.contents.int_contents:
                max_tokens = int(t.contents.int_contents[0])
            elif t.name == "temperature" and t.contents.fp32_contents:
                temperature = float(t.contents.fp32_contents[0])
        oai = CompletionRequest(
            model=request.model_name,
            prompt=text,
            max_tokens=int(max_tokens) if max_tokens else None,
            temperature=float(temperature) if temperature is not None else None,
            ignore_eos=bool(ignore_eos) if ignore_eos is not None else None,
        )
        preq = pipe.preprocessor.preprocess_completion(oai, text)
        if request.id:
            preq.request_id = request.id
        return pipe, preq

    @staticmethod
    def _text_response(request, text: str, finish: Optional[str]) -> pb.ModelInferResponse:
        resp = pb.ModelInferResponse(
            model_name=request.model_name, model_version="1", id=request.id
        )
        out = resp.outputs.add()
        out.name, out.datatype = "text_output", "BYTES"
        out.shape.append(1)
        out.contents.bytes_contents.append(text.encode())
        if finish:
            resp.parameters["finish_reason"].string_param = finish
        return resp

    async def ModelInfer(self, request, context) -> pb.ModelInferResponse:
        try:
            pipe, preq = self._to_preq(request)
        except ValueError as e:  # over-long prompt / bad params -> clean status
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        if pipe is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"model '{request.model_name}' not found"
            )
        ctx = Context(preq.request_id)
        parts = []
        finish = None
        try:
            async for out in pipe.generate_tokens(preq, ctx):
                if out.text:
                    parts.append(out.text)
                if out.finish_reason is not None:
                    finish = out.finish_reason
        finally:
            ctx.stop_generating()
        return self._text_response(request, "".join(parts), finish)

    async def ModelStreamInfer(
        self, request, context
    ) -> AsyncIterator[pb.ModelStreamInferResponse]:
        try:
            pipe, preq = self._to_preq(request)
        except ValueError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        if pipe is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"model '{request.model_name}' not found"
            )
        ctx = Context(preq.request_id)
        try:
            async for out in pipe.generate_tokens(preq, ctx):
                if out.text or out.finish_reason is not None:
                    yield pb.ModelStreamInferResponse(
                        infer_response=self._text_response(
                            request, out.text or "", out.finish_reason
                        )
                    )
        except Exception as e:  # stream errors ride the error_message field
            log.exception("stream infer failed")
            yield pb.ModelStreamInferResponse(error_message=str(e))
        finally:
            ctx.stop_generating()

    # -- server lifecycle ----------------------------------------------------
    def _handlers(self) -> grpc.GenericRpcHandler:
        unary = grpc.unary_unary_rpc_method_handler
        stream = grpc.unary_stream_rpc_method_handler
        table = {
            "ServerLive": unary(
                self.ServerLive,
                request_deserializer=pb.ServerLiveRequest.FromString,
                response_serializer=pb.ServerLiveResponse.SerializeToString,
            ),
            "ServerReady": unary(
                self.ServerReady,
                request_deserializer=pb.ServerReadyRequest.FromString,
                response_serializer=pb.ServerReadyResponse.SerializeToString,
            ),
            "ModelReady": unary(
                self.ModelReady,
                request_deserializer=pb.ModelReadyRequest.FromString,
                response_serializer=pb.ModelReadyResponse.SerializeToString,
            ),
            "ModelMetadata": unary(
                self.ModelMetadata,
                request_deserializer=pb.ModelMetadataRequest.FromString,
                response_serializer=pb.ModelMetadataResponse.SerializeToString,
            ),
            "ModelInfer": unary(
                self.ModelInfer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=pb.ModelInferResponse.SerializeToString,
            ),
            "ModelStreamInfer": stream(
                self.ModelStreamInfer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=pb.ModelStreamInferResponse.SerializeToString,
            ),
        }
        return grpc.method_handlers_generic_handler(SERVICE_NAME, table)

    async def start(self) -> str:
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((self._handlers(),))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        await self._server.start()
        log.info("KServe gRPC frontend on %s:%d", self.host, self.port)
        return f"{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=1.0)
