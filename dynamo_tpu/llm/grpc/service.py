"""KServe v2 gRPC inference frontend.

Analog of the reference's KServe service (lib/llm/src/grpc/service/kserve.rs):
the same discovered model pipelines the OpenAI HTTP frontend serves, exposed
over the standard v2 inference protocol — text in ("text_input" BYTES tensor),
text out ("text_output"), with generation knobs as request parameters and
token streaming via ModelStreamInfer.

grpc_tools isn't in the image, so the message classes come from `protoc
--python_out` (protos/kserve.proto -> kserve_pb2.py) and the service is
registered with hand-rolled method handlers — ~30 lines that replace the
generated *_pb2_grpc stubs.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional

import grpc

from ...runtime.engine import Context
from ...runtime.logging import get_logger
from ..discovery import ModelManager
from ..protocols.openai import CompletionRequest, new_request_id
from ..protocols.tensor import DTYPES, Tensor, TensorRequest, TensorResponse
from . import kserve_pb2 as pb

import numpy as np

# InferTensorContents field per KServe datatype (BYTES handled separately)
_CONTENTS_FIELD = {
    "BOOL": "bool_contents",
    "INT8": "int_contents", "INT16": "int_contents", "INT32": "int_contents",
    "INT64": "int64_contents",
    "UINT8": "uint_contents", "UINT16": "uint_contents",
    "UINT32": "uint_contents", "UINT64": "uint64_contents",
    # FP16 has NO typed contents field in the KServe v2 spec: conformant
    # clients must ship it via raw_input_contents
    "FP32": "fp32_contents", "FP64": "fp64_contents",
}

log = get_logger("llm.grpc")

SERVICE_NAME = "inference.GRPCInferenceService"


def _param(params, name: str, default=None):
    p = params.get(name)
    if p is None:
        return default
    which = p.WhichOneof("parameter_choice")
    return getattr(p, which) if which else default


class KserveGrpcService:
    def __init__(self, manager: ModelManager, host: str = "0.0.0.0", port: int = 0):
        self.manager = manager
        self.host = host
        self.port = port
        self._server: Optional[grpc.aio.Server] = None

    # -- rpc handlers --------------------------------------------------------
    async def ServerLive(self, request, context) -> pb.ServerLiveResponse:
        return pb.ServerLiveResponse(live=True)

    async def ServerReady(self, request, context) -> pb.ServerReadyResponse:
        return pb.ServerReadyResponse(ready=bool(self.manager.list_models()))

    async def ModelReady(self, request, context) -> pb.ModelReadyResponse:
        pipe = self.manager.get(request.name)
        ready = pipe is not None and bool(pipe.client and pipe.client.instances)
        return pb.ModelReadyResponse(ready=ready)

    async def ModelMetadata(self, request, context) -> pb.ModelMetadataResponse:
        if self.manager.get(request.name) is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"model '{request.name}' not found"
            )
        resp = pb.ModelMetadataResponse(
            name=request.name, versions=["1"], platform="dynamo_tpu"
        )
        inp = resp.inputs.add()
        inp.name, inp.datatype = "text_input", "BYTES"
        inp.shape.append(1)
        out = resp.outputs.add()
        out.name, out.datatype = "text_output", "BYTES"
        out.shape.append(1)
        return resp

    @staticmethod
    def _is_tensor_model(pipe) -> bool:
        return "tensor" in (pipe.card.model_type or [])

    def _to_preq(self, request: pb.ModelInferRequest, pipe=None):
        if pipe is None:
            pipe = self.manager.get(request.model_name)
        if pipe is None:
            return None, None
        text = ""
        input_ids = None
        max_tokens = _param(request.parameters, "max_tokens")
        temperature = _param(request.parameters, "temperature")
        ignore_eos = _param(request.parameters, "ignore_eos")
        for t in request.inputs:
            if t.name == "text_input" and t.contents.bytes_contents:
                text = t.contents.bytes_contents[0].decode("utf-8", "replace")
            elif t.name == "input_ids" and t.contents.int64_contents:
                # pre-tokenized path: token ids skip the tokenizer entirely
                input_ids = [int(v) for v in t.contents.int64_contents]
            elif t.name == "max_tokens" and t.contents.int_contents:
                max_tokens = int(t.contents.int_contents[0])
            elif t.name == "temperature" and t.contents.fp32_contents:
                temperature = float(t.contents.fp32_contents[0])
        if input_ids is not None:
            from ..protocols.common import (
                PreprocessedRequest,
                SamplingOptions,
                StopConditions,
            )

            # the text path gets these from the preprocessor; the
            # pre-tokenized path must enforce them itself so over-long
            # inputs fail with INVALID_ARGUMENT, not a remote engine error
            budget = pipe.card.context_length - len(input_ids)
            if budget <= 0:
                raise ValueError(
                    f"input_ids length {len(input_ids)} exceeds model "
                    f"context {pipe.card.context_length}"
                )
            preq = PreprocessedRequest(
                request_id=request.id or new_request_id(),
                model=request.model_name,
                token_ids=input_ids,
                stop=StopConditions(
                    max_tokens=(
                        min(int(max_tokens), budget) if max_tokens else budget
                    ),
                    ignore_eos=bool(ignore_eos) if ignore_eos is not None else False,
                ),
                sampling=SamplingOptions(
                    temperature=(
                        float(temperature) if temperature is not None else 1.0
                    ),
                ),
            )
            return pipe, preq
        oai = CompletionRequest(
            model=request.model_name,
            prompt=text,
            max_tokens=int(max_tokens) if max_tokens else None,
            temperature=float(temperature) if temperature is not None else None,
            ignore_eos=bool(ignore_eos) if ignore_eos is not None else None,
        )
        preq = pipe.preprocessor.preprocess_completion(oai, text)
        if request.id:
            preq.request_id = request.id
        return pipe, preq

    # -- generic tensor models (llm/protocols/tensor.py) ---------------------
    @staticmethod
    def _pb_to_tensor_request(request: pb.ModelInferRequest) -> TensorRequest:
        tensors = []
        raw = list(request.raw_input_contents)
        if raw and len(raw) != len(request.inputs):
            raise ValueError(
                f"raw_input_contents has {len(raw)} entries for "
                f"{len(request.inputs)} inputs"
            )
        for i, t in enumerate(request.inputs):
            shape = [int(s) for s in t.shape]
            if raw:
                if t.datatype != "BYTES":
                    dt = DTYPES.get(t.datatype)
                    if dt is None:
                        raise ValueError(f"unsupported datatype {t.datatype!r}")
                    want = int(np.prod(shape)) * np.dtype(dt).itemsize
                    if len(raw[i]) != want:
                        raise ValueError(
                            f"tensor {t.name!r}: raw payload {len(raw[i])}B "
                            f"!= shape/dtype size {want}B"
                        )
                tensors.append(Tensor(t.name, t.datatype, shape, raw[i]))
            elif t.datatype == "BYTES":
                tensors.append(Tensor.from_bytes_list(
                    t.name, list(t.contents.bytes_contents), shape
                ))
            else:
                field = _CONTENTS_FIELD.get(t.datatype)
                if field is None:
                    raise ValueError(f"unsupported datatype {t.datatype!r}")
                vals = getattr(t.contents, field)
                arr = np.asarray(list(vals), DTYPES[t.datatype]).reshape(shape)
                tensors.append(Tensor.from_numpy(t.name, arr))
        params = {}
        for name in request.parameters:
            params[name] = _param(request.parameters, name)
        return TensorRequest(
            request_id=request.id or new_request_id(),
            model=request.model_name, tensors=tensors, parameters=params,
        )

    @staticmethod
    def _tensor_to_pb(
        request: pb.ModelInferRequest, tresp: TensorResponse, set_raw: bool
    ) -> pb.ModelInferResponse:
        resp = pb.ModelInferResponse(
            model_name=request.model_name, model_version="1", id=request.id
        )
        for t in tresp.tensors:
            out = resp.outputs.add()
            out.name, out.datatype = t.name, t.datatype
            out.shape.extend(t.shape)
            if set_raw:
                resp.raw_output_contents.append(t.data)
            elif t.datatype == "BYTES":
                out.contents.bytes_contents.extend(t.to_bytes_list())
            else:
                field = _CONTENTS_FIELD[t.datatype]
                getattr(out.contents, field).extend(
                    t.to_numpy().reshape(-1).tolist()
                )
        return resp

    async def _tensor_infer(
        self, pipe, request: pb.ModelInferRequest
    ) -> pb.ModelInferResponse:
        treq = self._pb_to_tensor_request(request)
        ctx = Context(treq.request_id)
        tresp = TensorResponse()
        try:
            async for item in await pipe.client.generate(treq.to_obj(), ctx):
                tresp = TensorResponse.from_obj(item)
        finally:
            ctx.stop_generating()
        if tresp.error:
            raise ValueError(tresp.error)
        return self._tensor_to_pb(
            request, tresp, set_raw=bool(request.raw_input_contents)
        )

    @staticmethod
    def _text_response(request, text: str, finish: Optional[str]) -> pb.ModelInferResponse:
        resp = pb.ModelInferResponse(
            model_name=request.model_name, model_version="1", id=request.id
        )
        out = resp.outputs.add()
        out.name, out.datatype = "text_output", "BYTES"
        out.shape.append(1)
        out.contents.bytes_contents.append(text.encode())
        if finish:
            resp.parameters["finish_reason"].string_param = finish
        return resp

    async def ModelInfer(self, request, context) -> pb.ModelInferResponse:
        pipe0 = self.manager.get(request.model_name)
        if pipe0 is not None and self._is_tensor_model(pipe0):
            # generic tensor model: tensors in, tensors out, no tokenizer
            # (reference grpc/service/tensor.rs)
            try:
                return await self._tensor_infer(pipe0, request)
            except (ValueError, KeyError) as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        try:
            pipe, preq = self._to_preq(request, pipe0)
        except ValueError as e:  # over-long prompt / bad params -> clean status
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        if pipe is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"model '{request.model_name}' not found"
            )
        ctx = Context(preq.request_id)
        parts = []
        finish = None
        try:
            async for out in pipe.generate_tokens(preq, ctx):
                if out.text:
                    parts.append(out.text)
                if out.finish_reason is not None:
                    finish = out.finish_reason
        finally:
            ctx.stop_generating()
        return self._text_response(request, "".join(parts), finish)

    async def ModelStreamInfer(
        self, request, context
    ) -> AsyncIterator[pb.ModelStreamInferResponse]:
        pipe0 = self.manager.get(request.model_name)
        if pipe0 is not None and self._is_tensor_model(pipe0):
            try:
                reply = await self._tensor_infer(pipe0, request)
                yield pb.ModelStreamInferResponse(infer_response=reply)
            except (ValueError, KeyError) as e:
                yield pb.ModelStreamInferResponse(error_message=str(e))
            return
        try:
            pipe, preq = self._to_preq(request, pipe0)
        except ValueError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        if pipe is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"model '{request.model_name}' not found"
            )
        ctx = Context(preq.request_id)
        try:
            async for out in pipe.generate_tokens(preq, ctx):
                if out.text or out.finish_reason is not None:
                    yield pb.ModelStreamInferResponse(
                        infer_response=self._text_response(
                            request, out.text or "", out.finish_reason
                        )
                    )
        except Exception as e:  # stream errors ride the error_message field
            log.exception("stream infer failed")
            yield pb.ModelStreamInferResponse(error_message=str(e))
        finally:
            ctx.stop_generating()

    # -- server lifecycle ----------------------------------------------------
    def _handlers(self) -> grpc.GenericRpcHandler:
        unary = grpc.unary_unary_rpc_method_handler
        stream = grpc.unary_stream_rpc_method_handler
        table = {
            "ServerLive": unary(
                self.ServerLive,
                request_deserializer=pb.ServerLiveRequest.FromString,
                response_serializer=pb.ServerLiveResponse.SerializeToString,
            ),
            "ServerReady": unary(
                self.ServerReady,
                request_deserializer=pb.ServerReadyRequest.FromString,
                response_serializer=pb.ServerReadyResponse.SerializeToString,
            ),
            "ModelReady": unary(
                self.ModelReady,
                request_deserializer=pb.ModelReadyRequest.FromString,
                response_serializer=pb.ModelReadyResponse.SerializeToString,
            ),
            "ModelMetadata": unary(
                self.ModelMetadata,
                request_deserializer=pb.ModelMetadataRequest.FromString,
                response_serializer=pb.ModelMetadataResponse.SerializeToString,
            ),
            "ModelInfer": unary(
                self.ModelInfer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=pb.ModelInferResponse.SerializeToString,
            ),
            "ModelStreamInfer": stream(
                self.ModelStreamInfer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=pb.ModelStreamInferResponse.SerializeToString,
            ),
        }
        return grpc.method_handlers_generic_handler(SERVICE_NAME, table)

    async def start(self) -> str:
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((self._handlers(),))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        await self._server.start()
        log.info("KServe gRPC frontend on %s:%d", self.host, self.port)
        return f"{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=1.0)
