"""Encoder cache: content-addressed LRU of vision embeddings.

Analog of the reference's EncoderCacheManager
(components/src/dynamo/common/memory/encoder_cache_manager.py): maps image
content hashes to encoder output arrays with byte-capacity LRU eviction, so
a repeated image (multi-turn chat, shared system imagery) never re-runs the
vision tower. Single-threaded by design (lives on the engine's event loop),
like the reference.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Optional

import numpy as np

from ..runtime.logging import get_logger

log = get_logger("llm.encoder_cache")


def content_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:32]


class EncoderCacheManager:
    def __init__(self, capacity_bytes: int = 1 << 30):
        self.capacity_bytes = capacity_bytes
        self._data: OrderedDict[str, np.ndarray] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def get(self, key: str) -> Optional[np.ndarray]:
        arr = self._data.get(key)
        if arr is None:
            self.misses += 1
            return None
        self._data.move_to_end(key)
        self.hits += 1
        return arr

    def set(self, key: str, arr: np.ndarray) -> None:
        if key in self._data:
            self._data.move_to_end(key)
            return
        if arr.nbytes > self.capacity_bytes:
            return  # larger than the whole cache: never admit
        while self._bytes + arr.nbytes > self.capacity_bytes and self._data:
            _, old = self._data.popitem(last=False)
            self._bytes -= old.nbytes
        self._data[key] = arr
        self._bytes += arr.nbytes

    def __len__(self) -> int:
        return len(self._data)

    @property
    def used_bytes(self) -> int:
        return self._bytes

    def stats(self) -> dict:
        return {
            "entries": len(self._data),
            "bytes": self._bytes,
            "hits": self.hits,
            "misses": self.misses,
        }
