"""Frontend model discovery: ModelManager + ModelWatcher + routed pipelines.

Analog of the reference's ModelManager (lib/llm/src/discovery/model_manager.rs:64),
ModelWatcher (discovery/watcher.rs:57,112) and the routed-pipeline builder
(lib/llm/src/entrypoint/input/common.rs:173-260). Workers publish
ModelDeploymentCards under ``v1/mdc/...`` tied to their lease; the frontend
watches that prefix and (un)registers per-model pipelines:

    OpenAIPreprocessor -> Migration -> [KvRouter] -> endpoint Client -> worker
"""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator, Dict, List, Optional

import msgpack

from ..kv_router import KvRouter, KvRouterConfig, WorkerWithDpRank
from ..runtime import metrics as M
from ..runtime.component import Client, RouterMode
from ..runtime.discovery.store import EventType
from ..runtime.distributed import DistributedRuntime
from ..runtime.engine import Context
from ..runtime.flight_recorder import get_flight_recorder
from ..runtime.logging import get_logger
from ..runtime.request_plane.tcp import NoResponders
from ..runtime.resilience import OPEN, CircuitBreaker
from ..runtime.tasks import spawn_bg
from ..runtime.tracing import get_tracer
from ..tokens import compute_sequence_hashes
from .migration import Migration
from .model_card import MDC_PREFIX, ModelDeploymentCard
from .preprocessor import (
    ANNOTATION_CACHED_TOKENS,
    ANNOTATION_PREFILL_WORKER_ID,
    ANNOTATION_WORKER_ID,
    OpenAIPreprocessor,
)
from .protocols.common import BackendOutput, PreprocessedRequest

log = get_logger("llm.discovery")


class _RecordedStream:
    """Wraps a worker response stream and reports the worker's outcome to
    its circuit breaker: clean finish -> success; transport loss, an
    ``error`` finish frame, or EOF-without-finish (the signals Migration
    treats as worker death) -> failure. Preserves ``instance_id`` so the
    migration operator can still attribute failures."""

    def __init__(self, stream, record):
        self._stream = stream
        self._record = record
        self._done = False
        self.instance_id = getattr(stream, "instance_id", None)

    def _close(self, ok: bool) -> None:
        if not self._done:
            self._done = True
            self._record(ok)

    def __aiter__(self) -> "_RecordedStream":
        return self

    async def __anext__(self):
        try:
            item = await self._stream.__anext__()
        except StopAsyncIteration:
            # EOF without a finish frame = worker died mid-request
            self._close(False)
            raise
        except (NoResponders, ConnectionError):
            self._close(False)
            raise
        fr = (
            item.get("finish_reason") if isinstance(item, dict)
            else getattr(item, "finish_reason", None)
        )
        if fr is not None:
            # record AT the finish frame: consumers (Migration) return from
            # their async-for right here, so the iterator is never exhausted
            # on the success path
            self._close(fr != "error")
        return item


class ModelPipeline:
    """Everything needed to serve one model from the frontend."""

    def __init__(
        self,
        runtime: DistributedRuntime,
        card: ModelDeploymentCard,
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
        kv_router_config: Optional[KvRouterConfig] = None,
    ):
        self.runtime = runtime
        self.card = card
        self.router_mode = router_mode
        self.kv_router_config = kv_router_config
        self.preprocessor = OpenAIPreprocessor(card)
        self.client: Optional[Client] = None
        self.kv_router: Optional[KvRouter] = None
        self.migration = Migration(self._send, card.migration_limit)
        self.instance_count = 0
        self._known_worker_ids: set = set()
        # per-worker circuit breakers (scope DTPU_CB_WORKER): a flapping
        # worker that keeps dropping streams trips its circuit and routing
        # steers around it (retry-then-migrate) until the reset probe passes.
        # Their metrics go to a detached scope, NOT the runtime registry:
        # worker ids are ephemeral and one series per id ever seen would
        # grow /metrics without bound under autoscaling churn (the per-model
        # frontend breaker stays on /metrics).
        self._worker_breakers: Dict[int, CircuitBreaker] = {}
        self._worker_cb_metrics = M.MetricsScope()
        # router-universe reconcile throttle (_prune_dead_workers): the
        # full sweep is O(fleet), so it runs on instance-count change or
        # every N requests, not per decision
        self._router_sync_tick = 0
        self._router_synced_count = -1
        self._rr = 0  # non-KV fallback round-robin over non-shunned workers
        # disaggregation: set when a prefill pool is registered for this model
        self.prefill_router = None
        # fleet-wide KV reuse (DTPU_GLOBAL_KV): lookup-only directory client
        # + fetch-vs-recompute planner, built in start() when enabled
        self.global_kv = None

    def _worker_cb(self, iid: int) -> CircuitBreaker:
        cb = self._worker_breakers.get(iid)
        if cb is None:
            cb = self._worker_breakers[iid] = CircuitBreaker.from_env(
                "worker", name=f"worker.{iid:016x}",
                failure_threshold=3, failure_rate=0.5, window_s=10.0,
                reset_timeout_s=2.0, metrics=self._worker_cb_metrics,
            )
        return cb

    def _tripped(self, excluded: List[int]) -> List[int]:
        """Workers to steer around: open circuits, unless that would leave
        no candidate at all (then trying a tripped worker beats failing).
        Only workers that ever recorded an outcome have a breaker, so the
        scan is O(breakers), never O(fleet) — a worker with no breaker is
        treated as closed without constructing one (healthy hot path)."""
        assert self.client is not None
        inst = self.client.instances
        # drop breakers for departed workers opportunistically when the
        # table outgrows the fleet (long-lived non-KV frontends under churn
        # would otherwise accumulate them; the KV path also sweeps them in
        # _prune_dead_workers)
        if len(self._worker_breakers) > len(inst):
            for iid in list(self._worker_breakers):
                if iid not in inst:
                    self._worker_breakers.pop(iid, None)
        avoid = [
            iid for iid, cb in self._worker_breakers.items()
            if iid not in excluded and iid in inst and cb.state == OPEN
        ]
        if not avoid:
            return []
        shun_live = sum(1 for iid in set(excluded) if iid in inst)
        # would avoiding empty the pool? (all counts over live instances)
        if len(inst) - shun_live - len(avoid) <= 0:
            return []
        return avoid

    def _draining(self, excluded: List[int]) -> List[int]:
        """Workers whose discovery record advertises a planned reclaim
        (``state=draining``, engine/drain.py): new work AND migration
        retries steer around them — a retry landing on a worker seconds
        from death just migrates twice. Same empty-pool fallback as
        ``_tripped``: when avoiding every draining worker would leave no
        candidate, a draining worker beats no worker (it still serves
        until the deadline)."""
        assert self.client is not None
        inst = self.client.instances
        avoid = [
            iid for iid, rec in inst.items()
            if iid not in excluded and rec.metadata.get("state") == "draining"
        ]
        if not avoid:
            return []
        shun_live = sum(1 for iid in set(excluded) if iid in inst)
        if len(inst) - shun_live - len(avoid) <= 0:
            return []
        return avoid

    async def start(self) -> "ModelPipeline":
        endpoint = (
            self.runtime.namespace(self.card.namespace)
            .component(self.card.component)
            .endpoint(self.card.endpoint)
        )
        self.client = await endpoint.client(
            RouterMode.ROUND_ROBIN if self.router_mode == RouterMode.KV else self.router_mode
        )
        if self.router_mode == RouterMode.KV:
            self.kv_router = await KvRouter(
                self.runtime.event_plane,
                self.card.namespace,
                self.card.component,
                block_size=self.card.kv_block_size,
                config=self.kv_router_config,
                metrics=self.runtime.metrics,
            ).start()
        from ..kvbm.directory import GlobalKvDirectory, directory_enabled

        if directory_enabled():
            # lookup-only client on the shared directory plane: the frontend
            # never publishes (no lease needed), it only resolves misses
            from .prefill_router import GlobalKvFetchPlanner

            directory = GlobalKvDirectory(
                self.runtime.store, f"frontend/{self.card.name}",
                metrics=self.runtime.metrics,
            )
            adv = int(
                getattr(self.card.runtime_config, "kv_bytes_per_block", 0) or 0
            )
            self.global_kv = GlobalKvFetchPlanner(
                directory,
                block_size=self.card.kv_block_size,
                kv_bytes_per_block=adv,
            )
        return self

    async def stop(self) -> None:
        if self.prefill_router is not None:
            await self.prefill_router.stop()
        if self.kv_router is not None:
            await self.kv_router.stop()
        if self.client is not None:
            await self.client.stop()

    # -- routing -------------------------------------------------------------
    def _candidates(self, excluded: List[int]) -> List[WorkerWithDpRank]:
        assert self.client is not None
        cands: List[WorkerWithDpRank] = []
        for iid, inst in self.client.instances.items():
            if iid in excluded:
                continue
            dp = int(inst.metadata.get("data_parallel_size", 1) or 1)
            for r in range(dp):
                cands.append(WorkerWithDpRank(iid, r))
        return cands

    def _prune_dead_workers(self) -> None:
        """Sync the KV router's candidate universe with discovery: departed
        instances are removed, new ones registered (per dp_rank). Routing
        then passes only per-request exclusion sets — the O(K) path — and
        never builds a fleet-sized candidate list per decision.

        The reconcile itself is O(fleet), so it is throttled: it runs when
        the instance count changes and on a coarse request tick, not per
        decision. The sweep walks the router's *registered universe*, not a
        known-set delta — a late metrics event auto-registers workers in
        the scheduler (update_metrics), so a removed worker can be
        resurrected after its one-shot delta removal and must be swept out
        again."""
        if self.kv_router is None or self.client is None:
            return
        inst_map = self.client.instances
        self._router_sync_tick += 1
        if (
            len(inst_map) == self._router_synced_count
            and self._router_sync_tick % 64 != 1
        ):
            return
        live = set(inst_map)
        for w in self.kv_router.scheduler.known_workers():
            if w.worker_id not in live:
                self.kv_router.remove_worker_id(w.worker_id)
                self._worker_breakers.pop(w.worker_id, None)
        for iid in live - self._known_worker_ids:
            inst = inst_map.get(iid)
            dp = int(inst.metadata.get("data_parallel_size", 1) or 1) if inst else 1
            for r in range(dp):
                self.kv_router.register_worker(WorkerWithDpRank(iid, r))
        self._known_worker_ids = live
        self._router_synced_count = len(inst_map)

    def _evacuation_costs(
        self, req: PreprocessedRequest, inst_map, shun: List[int]
    ) -> Optional[Dict[WorkerWithDpRank, float]]:
        """Bandwidth-priced destination costs for an evacuation replay
        (docs/operations.md §13): a request migrating off a draining worker
        carries a reference to its sealed KV in ``kv_transfer`` — charge
        every candidate the time to pull those blocks over its advertised
        wire class (per-wire EWMA, runtime/bandwidth.py), converted to
        block units (the KvScheduler ``extra_costs`` currency), so the
        evacuated KV lands where the wire is fast instead of round-robin.
        None for ordinary requests — the common path pays nothing."""
        kvt = getattr(req, "kv_transfer", None) or {}
        hashes = kvt.get("hashes") or ()
        if not hashes:
            return None
        from ..runtime.bandwidth import get_bandwidth_estimator
        from ..runtime.config import ENV_PREFILL_BLOCK_MS, env_float

        bw = get_bandwidth_estimator()
        bpb = int(kvt.get("bytes_per_block", 0) or 0) or (
            int(getattr(self.card.runtime_config, "kv_bytes_per_block", 0) or 0)
            or 256 * 1024
        )
        move_bytes = len(hashes) * bpb
        block_time_s = env_float(ENV_PREFILL_BLOCK_MS, 10.0) / 1e3
        shun_set = set(shun)
        costs: Dict[WorkerWithDpRank, float] = {}
        for iid, inst in inst_map.items():
            if iid in shun_set:
                continue
            wire = str(inst.metadata.get("kv_wire") or "inline")
            cost = bw.transfer_seconds(wire, move_bytes) / block_time_s
            dp = int(inst.metadata.get("data_parallel_size", 1) or 1)
            for r in range(dp):
                costs[WorkerWithDpRank(iid, r)] = cost
        return costs or None

    async def _send(
        self, req: PreprocessedRequest, context: Context, excluded: List[int]
    ) -> AsyncIterator[Any]:
        assert self.client is not None
        instance_id: Optional[int] = None
        # trace hop: the routing decision gets its own span, and its id
        # REPLACES the traceparent annotation the worker will parent on —
        # one trace then reads frontend -> router -> worker in order
        tracer = get_tracer()
        span = None
        if tracer.enabled:
            span = tracer.span(
                "router.schedule",
                traceparent=req.annotations.get("traceparent"),
                request_id=req.request_id, model=self.card.name,
            )
            span.__enter__()
            req.annotations["traceparent"] = span.traceparent()
        try:
            # per-request exclusions (migration) plus cross-request tripped
            # circuits plus draining (reclaim-notice) workers: all steered
            # around the same way; _draining sees the combined set so its
            # empty-pool fallback accounts for already-shunned workers
            shun = list(excluded) + self._tripped(excluded)
            shun += self._draining(shun)
            # pooled forwards don't touch KV pages: routing them through the KV
            # scheduler would charge phantom blocks to a worker (and pollute the
            # approx prefix view) that complete() on the embed path never frees
            use_kv = self.kv_router is not None and req.annotations.get("op") != "embed"
            overlap_tokens = 0
            if use_kv:
                self._prune_dead_workers()
                inst_map = self.client.instances
                shun_live = sum(1 for iid in set(shun) if iid in inst_map)
                if not inst_map or shun_live >= len(inst_map):
                    # every instance is excluded (dead mid-request): fail this
                    # attempt rather than route back onto a dead worker
                    raise NoResponders(f"no non-excluded instances for {self.card.name}")
                # exclusion-set routing over the router's registered
                # universe (synced above): O(shun) to build, O(topk) to
                # decide — no fleet-sized candidate list per request
                excl = set()
                for iid in shun:
                    inst = inst_map.get(iid)
                    dp = (
                        int(inst.metadata.get("data_parallel_size", 1) or 1)
                        if inst is not None else 1
                    )
                    for r in range(dp):
                        excl.add(WorkerWithDpRank(iid, r))
                decision = self.kv_router.schedule_tokens(
                    req.token_ids, excluded=excl, request_id=req.request_id,
                    extra_costs=self._evacuation_costs(req, inst_map, shun),
                )
                instance_id = decision.worker.worker_id
                overlap_tokens = decision.overlap_blocks * self.card.kv_block_size
                req.annotations[ANNOTATION_CACHED_TOKENS] = overlap_tokens
                req.annotations[ANNOTATION_WORKER_ID] = instance_id
                req.annotations["dp_rank"] = decision.worker.dp_rank
                if span is not None:
                    span.set(
                        mode="kv", worker=f"{instance_id:016x}",
                        dp_rank=decision.worker.dp_rank,
                        overlap_blocks=decision.overlap_blocks,
                        query_blocks=decision.query_blocks,
                        excluded=len(shun),
                    )
            elif shun:
                # non-KV mode: steer away from excluded (dead) + tripped
                # instances, round-robining over the survivors — pinning to
                # alive[0] would dump the tripped worker's whole share onto one
                # neighbor for the open window
                alive = [i for i in self.client.instance_ids() if i not in shun]
                if not alive:
                    raise NoResponders(f"no non-excluded instances for {self.card.name}")
                instance_id = alive[self._rr % len(alive)]
                self._rr += 1
            if span is not None and not use_kv:
                span.set(
                    mode=str(self.router_mode.value)
                    if hasattr(self.router_mode, "value") else str(self.router_mode),
                    worker=(f"{instance_id:016x}" if instance_id is not None
                            else "client-routed"),
                    excluded=len(shun),
                )
            get_flight_recorder().record(
                req.request_id, "routed",
                worker=(f"{instance_id:016x}" if instance_id is not None
                        else "client-routed"),
                overlap_tokens=overlap_tokens, excluded=len(shun),
            )
            try:
                stream = await self.client.generate(req.to_obj(), context, instance_id)
            except (NoResponders, ConnectionError) as e:
                if instance_id is not None and getattr(e, "instance_id", None) is None:
                    e.instance_id = instance_id  # type: ignore[attr-defined]
                iid = getattr(e, "instance_id", None)
                if iid is not None:
                    cb = self._worker_cb(iid)
                    # reserve the half-open probe slot (no-op when closed) so
                    # this outcome counts as the probe result; the breaker
                    # ignores unreserved results in half-open as stale
                    cb.allow()
                    cb.record(False)
                raise
        except Exception as e:
            if span is not None:
                span.status = "ERROR"
                span.set(error=repr(e))
            raise
        finally:
            if span is not None:
                span.__exit__(None, None, None)
        iid = getattr(stream, "instance_id", None)
        if iid is None:
            return stream
        cb = self._worker_cb(iid)
        cb.allow()  # see above: this stream IS the half-open probe
        return _RecordedStream(stream, cb.record)

    def _decode_overlap(self, req: PreprocessedRequest, hashes=None) -> int:
        """Prompt blocks the DECODE pool's radix tree already holds — the
        radix-hit deflection signal (shipping KV the decode side has is
        pure waste). 0 when KV routing is off for this model. ``hashes``
        shares a caller's hash pass (must match this router's block size)."""
        if (
            self.kv_router is None
            or self.client is None
            or not self.client.instances
        ):
            return 0
        cands = self._candidates([])
        try:
            # stateless peek: no load charge, no index update
            return self.kv_router.score_tokens(
                req.token_ids, cands, hashes=hashes
            ).overlap_blocks
        except Exception:
            return 0

    async def generate_tokens(
        self, req: PreprocessedRequest, context: Context
    ) -> AsyncIterator[BackendOutput]:
        """The full internal stream: [prefill hop ->] migration-wrapped routed
        generation. Disaggregation is elastic: with no prefill pool (or on
        prefill failure) the aggregated path serves the request unchanged."""
        offset = 0
        if req.annotations.get("op") == "embed":
            # pooled forwards never split across prefill/decode pools
            async for out in self.migration.generate(req, context):
                yield out
            return
        if self.prefill_router is not None and self.prefill_router.has_workers:
            plan = None
            try:
                # ONE hash pass serves the decode-overlap peek, the plan's
                # scoring and the streamed transfer handshake when both
                # pools share a block size (the normal deployment)
                bs_p = self.prefill_router.card.kv_block_size
                shared_hashes = (
                    compute_sequence_hashes(req.token_ids, bs_p)
                    if self.kv_router is None
                    or self.kv_router.block_size == bs_p
                    else None
                )
                plan = self.prefill_router.plan(
                    req,
                    decode_overlap_blocks=self._decode_overlap(
                        req, shared_hashes
                    ),
                    hashes=shared_hashes,
                )
            except Exception:
                log.exception(
                    "disagg planning failed; taking the sequential prefill path"
                )
            if plan is not None and plan.deflected:
                # prefill deflection: the aggregated path below prefills
                # locally on the decode worker (mixed batching rides the
                # deflected chunk along the decode dispatch)
                pre_out = None
            elif plan is not None and plan.streamed:
                # streamed disagg: fire the prefill clone and dispatch the
                # decode request NOW with a streamed kv_transfer handshake —
                # its block-window pull overlaps the prefill compute instead
                # of serializing behind prefill + full transfer
                self.prefill_router.start_streamed_prefill(req, context, plan)
                bs = self.prefill_router.card.kv_block_size
                req = PreprocessedRequest.from_obj(req.to_obj())
                req.kv_transfer = {
                    "address": plan.transfer_address,
                    "hashes": list(plan.hashes),
                    "num_tokens": plan.query_blocks * bs,
                    "stream": True,
                }
                req.annotations[ANNOTATION_PREFILL_WORKER_ID] = plan.worker_id
                pre_out = None
            else:
                pre_out = await self.prefill_router.run_prefill(req, context, plan)
            if pre_out is not None and pre_out.token_ids:
                merged = dict(req.annotations)
                merged.update(pre_out.annotations)
                if req.stop.max_tokens == 1:
                    pre_out.annotations = merged
                    yield pre_out
                    return
                # first token streams now; decode continues from it
                first_tok = pre_out.token_ids[-1]
                yield BackendOutput(
                    token_ids=list(pre_out.token_ids),
                    cumulative_tokens=1,
                    logprobs=pre_out.logprobs,
                    annotations=merged,
                )
                offset = 1
                req = PreprocessedRequest.from_obj(req.to_obj())
                req.prior_token_ids = [first_tok]
                req.kv_transfer = pre_out.kv_transfer
                if req.kv_transfer:
                    # sequential dispatch: the prefill is COMPLETE, so the
                    # one-shot blocking pull is strictly better here — it
                    # can take the device wire (fastest DCN path), which
                    # the window protocol does not speak. Drop the
                    # announce's stream capability flag.
                    req.kv_transfer = dict(req.kv_transfer)
                    req.kv_transfer.pop("stream", None)
                if req.stop.max_tokens is not None:
                    req.stop.max_tokens -= 1
        if self.global_kv is not None and not req.kv_transfer:
            # fleet-wide KV reuse: the aggregated/deflected path recomputes
            # its whole miss locally — unless some other worker's G2/G3 tier
            # already holds the sealed blocks and fetching them beats the
            # recompute (kvbm/directory.py + ops/costs.fetch_vs_recompute).
            # Planning failure (directory fault, stale entries) just means
            # no plan: the request proceeds exactly as before.
            try:
                bs = self.global_kv.block_size
                hashes = compute_sequence_hashes(req.token_ids, bs)
                fetch = await self.global_kv.plan_fetch(
                    req, hashes,
                    overlap_blocks=self._decode_overlap(req, (
                        hashes if self.kv_router is not None
                        and self.kv_router.block_size == bs else None
                    )),
                )
                if fetch is not None:
                    req = PreprocessedRequest.from_obj(req.to_obj())
                    req.kv_transfer = fetch
            except Exception:
                log.warning(
                    "global kv fetch planning failed; recomputing locally",
                    exc_info=True,
                )
        first = offset == 0
        try:
            async for out in self.migration.generate(req, context):
                if first:
                    first = False
                    merged = dict(req.annotations)
                    merged.update(out.annotations)
                    out.annotations = merged
                if offset:
                    out.cumulative_tokens += offset
                yield out
        finally:
            if self.kv_router is not None:
                self.kv_router.complete(req.request_id)


class ModelManager:
    def __init__(self):
        self._models: Dict[str, ModelPipeline] = {}

    def get(self, model: str) -> Optional[ModelPipeline]:
        return self._models.get(model)

    def add(self, model: str, pipeline: ModelPipeline) -> None:
        self._models[model] = pipeline

    async def remove(self, model: str) -> None:
        p = self._models.pop(model, None)
        if p is not None:
            await p.stop()

    def list_models(self) -> List[str]:
        return sorted(self._models)

    def pipelines(self) -> List[ModelPipeline]:
        return list(self._models.values())


class ModelWatcher:
    def __init__(
        self,
        runtime: DistributedRuntime,
        manager: ModelManager,
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
        kv_router_config: Optional[KvRouterConfig] = None,
    ):
        self.runtime = runtime
        self.manager = manager
        self.router_mode = router_mode
        self.kv_router_config = kv_router_config
        self._task: Optional[asyncio.Task] = None
        self._watcher = None
        # mdc store key -> model name (for DELETE handling)
        self._key_model: Dict[str, str] = {}
        self._model_keys: Dict[str, set] = {}
        # disaggregation: prefill pool cards by model name
        self._prefill_cards: Dict[str, ModelDeploymentCard] = {}
        self._prefill_keys: Dict[str, set] = {}

    async def start(self) -> "ModelWatcher":
        self._watcher = await self.runtime.store.watch(MDC_PREFIX + "/")
        # spawn_bg: a dead model watcher means new models never register
        # and removed ones keep serving — log the failure, don't drop it
        self._task = spawn_bg(self._loop())
        return self

    async def _loop(self) -> None:
        assert self._watcher is not None
        async for ev in self._watcher:
            try:
                if ev.type == EventType.PUT and ev.value is not None:
                    await self._handle_put(ev.key, ev.value)
                elif ev.type == EventType.DELETE:
                    await self._handle_delete(ev.key)
            except Exception:
                log.exception("model watcher event failed (%s)", ev.key)

    async def _handle_put(self, key: str, value: bytes) -> None:
        card = ModelDeploymentCard.from_obj(msgpack.unpackb(value, raw=False))
        from .model_card import MODEL_TYPE_PREFILL

        if MODEL_TYPE_PREFILL in card.model_type:
            self._key_model[key] = card.name
            self._prefill_keys.setdefault(card.name, set()).add(key)
            if card.name not in self._prefill_cards:
                self._prefill_cards[card.name] = card
                log.info("prefill pool for %s appeared", card.name)
            await self._sync_prefill(card.name)
            return
        self._key_model[key] = card.name
        self._model_keys.setdefault(card.name, set()).add(key)
        if self.manager.get(card.name) is None:
            log.info("model %s appeared (card at %s)", card.name, key)
            pipeline = await ModelPipeline(
                self.runtime, card, self.router_mode, self.kv_router_config
            ).start()
            self.manager.add(card.name, pipeline)
        pipe = self.manager.get(card.name)
        if pipe is not None:
            pipe.instance_count = len(self._model_keys[card.name])
        await self._sync_prefill(card.name)

    async def _sync_prefill(self, model: str) -> None:
        """Attach/detach the PrefillRouter as prefill pools come and go."""
        pipe = self.manager.get(model)
        if pipe is None:
            return
        has_pool = bool(self._prefill_keys.get(model))
        if has_pool and pipe.prefill_router is None:
            from .prefill_router import PrefillRouter

            pipe.prefill_router = await PrefillRouter(
                self.runtime,
                self._prefill_cards[model],
                self.kv_router_config if self.router_mode == RouterMode.KV else None,
            ).start()
            log.info("disaggregation enabled for %s", model)
        elif not has_pool and pipe.prefill_router is not None:
            router = pipe.prefill_router
            pipe.prefill_router = None
            await router.stop()
            log.info("disaggregation disabled for %s (prefill pool empty)", model)

    async def _handle_delete(self, key: str) -> None:
        model = self._key_model.pop(key, None)
        if model is None:
            return
        pkeys = self._prefill_keys.get(model)
        if pkeys is not None and key in pkeys:
            pkeys.discard(key)
            if not pkeys:
                self._prefill_cards.pop(model, None)
                self._prefill_keys.pop(model, None)
            await self._sync_prefill(model)
            return
        keys = self._model_keys.get(model, set())
        keys.discard(key)
        pipe = self.manager.get(model)
        if pipe is not None:
            pipe.instance_count = len(keys)
        if not keys:
            log.info("last instance of model %s gone; deregistering", model)
            self._model_keys.pop(model, None)
            await self.manager.remove(model)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        if self._watcher is not None:
            self._watcher.cancel()
        for model in list(self.manager.list_models()):
            await self.manager.remove(model)
