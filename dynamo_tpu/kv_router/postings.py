"""Sharded bucketed prefix->worker postings: the routing prune index.

Analog of the reference's flat postings index (lib/kv-router/src/
flat_hashmap.rs) behind ``ApproxKvIndexer``: alongside the exact
``RadixTree`` holder sets, every indexed block hash keeps a small capped
"postings" list of workers (a bucket, default 8). Answering "which K
workers hold the longest cached prefix of this hash chain" then walks the
chain once and drains postings deepest-first — O(chain + K) — instead of
intersecting full holder sets, which on a fleet-hot prefix is O(fleet)
per block.

Postings are *approximate by construction*: a bucket caps how many
holders of one block are routable via the prefix path (the load path and
exact rescoring keep selection quality, scheduler.py). Ordering is
insertion order — deterministic given a deterministic event stream, which
the sim relies on. On removal a bucket that underflows below half
refills from the node's full holder set in sorted order, so a hot prefix
whose early holders evict stays reachable.

Shards partition the postings by hash bucket (``seq_hash % shards``).
Each shard is an independent map with no cross-shard links, so replicated
frontends can snapshot/merge router state shard-by-shard
(``KvRouter`` sync protocol) and a multi-threaded/process port can place
shards behind separate locks — there is no single hot structure.
"""

from __future__ import annotations

import heapq
from typing import Dict, Iterable, List

from ..tokens import SequenceHash


def shard_of(seq_hash: SequenceHash, num_shards: int) -> int:
    """Stable hash-bucket shard id (SequenceHash is an int; no process-
    seeded ``hash()`` — replicas must agree on the partition)."""
    if num_shards <= 1:
        return 0
    return int(seq_hash) % num_shards


class ShardedPostings:
    def __init__(self, shards: int = 1, bucket: int = 8):
        self.shards = max(1, int(shards))
        self.bucket = max(1, int(bucket))
        # per shard: seq_hash -> insertion-ordered {worker: None} (<= bucket)
        self._maps: List[Dict[SequenceHash, Dict]] = [
            {} for _ in range(self.shards)
        ]

    def _map(self, sh: SequenceHash) -> Dict[SequenceHash, Dict]:
        return self._maps[shard_of(sh, self.shards)]

    # -- maintenance (driven by RadixTree mutations) -------------------------
    def add(self, sh: SequenceHash, worker) -> None:
        m = self._map(sh)
        posted = m.get(sh)
        if posted is None:
            posted = m[sh] = {}
        if worker not in posted and len(posted) < self.bucket:
            posted[worker] = None

    def discard(self, sh: SequenceHash, worker, holders: Iterable) -> None:
        """Remove ``worker`` from the bucket; refill from the node's full
        ``holders`` (sorted, so the refill is deterministic) when the
        bucket underflows below half while un-posted holders remain."""
        m = self._map(sh)
        posted = m.get(sh)
        if posted is None or worker not in posted:
            return
        del posted[worker]
        if len(posted) * 2 < self.bucket:
            # nsmallest keeps the refill deterministic at O(holders log
            # bucket) — a full sort would be O(fleet log fleet) per refill
            # on exactly the fleet-hot blocks this index exists to avoid
            # scanning
            for w in heapq.nsmallest(self.bucket, holders):
                if len(posted) >= self.bucket:
                    break
                if w != worker:
                    posted.setdefault(w, None)
        if not posted:
            del m[sh]

    def drop(self, sh: SequenceHash) -> None:
        self._map(sh).pop(sh, None)

    # -- queries -------------------------------------------------------------
    def posted(self, sh: SequenceHash) -> tuple:
        posted = self._map(sh).get(sh)
        return tuple(posted) if posted else ()

    def shard_sizes(self) -> List[int]:
        return [len(m) for m in self._maps]

    def __len__(self) -> int:
        return sum(len(m) for m in self._maps)
