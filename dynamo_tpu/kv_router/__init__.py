"""KV-cache-aware routing: prefix index + cost-based worker selection."""

from .indexer import ApproxKvIndexer, KvIndexer
from .protocols import (
    KvCacheEvent,
    KvEventKind,
    OverlapScores,
    RouterEvent,
    WorkerMetrics,
    WorkerWithDpRank,
)
from .publisher import KvEventPublisher, WorkerMetricsPublisher, events_topic, metrics_topic
from .radix_tree import RadixTree
from .router import KvRouter
from .scheduler import KvRouterConfig, KvScheduler, SchedulingDecision

__all__ = [
    "ApproxKvIndexer",
    "KvCacheEvent",
    "KvEventKind",
    "KvEventPublisher",
    "KvIndexer",
    "KvRouter",
    "KvRouterConfig",
    "KvScheduler",
    "OverlapScores",
    "RadixTree",
    "RouterEvent",
    "SchedulingDecision",
    "WorkerMetrics",
    "WorkerMetricsPublisher",
    "WorkerWithDpRank",
    "events_topic",
    "metrics_topic",
]
