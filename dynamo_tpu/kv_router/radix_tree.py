"""Prefix index over chained block hashes.

Analog of the reference's RadixTree (lib/kv-router/src/radix_tree.rs:73,
find_matches :154). Because block hashes are *chained* (a block's sequence
hash encodes its whole prefix), the "radix tree" flattens to a map
``sequence_hash -> set of workers holding that block`` plus parent links for
eviction bookkeeping: matching a query prefix is a walk down its hash chain
until no worker holds the next block. This is the same trick the reference's
FlatHashMap alternative index exploits (lib/kv-router/src/flat_hashmap.rs:113).

Two query tiers serve the two-stage routing decision (scheduler.py):

- ``top_prefix_workers`` — the *prune* stage: a capped sharded postings
  index (postings.py) maintained alongside every mutation answers "up to K
  workers holding the longest prefix" in O(chain + K), never touching a
  full holder set.
- ``find_matches`` / ``find_matches_for`` — the *exact* stage:
  contiguous-match scores over all holders (small fleets) or restricted to
  an already-pruned candidate list (O(chain x K)).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set

from ..tokens import SequenceHash
from .postings import ShardedPostings, shard_of
from .protocols import OverlapScores, WorkerWithDpRank


@dataclasses.dataclass
class _Node:
    seq_hash: SequenceHash
    parent: Optional[SequenceHash]
    workers: Set[WorkerWithDpRank] = dataclasses.field(default_factory=set)
    children: Set[SequenceHash] = dataclasses.field(default_factory=set)


class RadixTree:
    def __init__(self, postings_bucket: int = 8, shards: int = 1):
        self._nodes: Dict[SequenceHash, _Node] = {}
        self._worker_blocks: Dict[WorkerWithDpRank, Set[SequenceHash]] = {}
        self.postings = ShardedPostings(shards=shards, bucket=postings_bucket)
        # per-call query instrumentation (pinned by tests): chain nodes
        # touched and holder sets MATERIALIZED by the last find_matches —
        # one intersection per block beyond the first; the first block
        # aliases the node's set read-only, and the old extra per-block
        # ``set(holders)`` copy is gone (so this is matched-1, not ~2x)
        self.last_nodes_visited = 0
        self.last_holder_sets = 0

    # -- mutation -----------------------------------------------------------
    def store(
        self,
        worker: WorkerWithDpRank,
        block_hashes: Iterable[SequenceHash],
        parent_hash: Optional[SequenceHash] = None,
    ) -> None:
        parent = parent_hash
        for sh in block_hashes:
            node = self._nodes.get(sh)
            if node is None:
                node = _Node(sh, parent)
                self._nodes[sh] = node
                if parent is not None and parent in self._nodes:
                    self._nodes[parent].children.add(sh)
            node.workers.add(worker)
            self.postings.add(sh, worker)
            self._worker_blocks.setdefault(worker, set()).add(sh)
            parent = sh

    def remove(self, worker: WorkerWithDpRank, block_hashes: Iterable[SequenceHash]) -> None:
        for sh in block_hashes:
            node = self._nodes.get(sh)
            if node is None:
                continue
            node.workers.discard(worker)
            self.postings.discard(sh, worker, node.workers)
            owned = self._worker_blocks.get(worker)
            if owned is not None:
                owned.discard(sh)
            if not node.workers:
                self._drop_node(sh)

    def _drop_node(self, sh: SequenceHash) -> None:
        node = self._nodes.pop(sh, None)
        if node is None:
            return
        self.postings.drop(sh)
        if node.parent is not None and node.parent in self._nodes:
            self._nodes[node.parent].children.discard(sh)
        # children become orphans; they stay indexed (their own hashes still
        # fully identify their prefix) until their workers remove them

    def remove_worker(self, worker: WorkerWithDpRank) -> None:
        for sh in list(self._worker_blocks.get(worker, ())):
            node = self._nodes.get(sh)
            if node is None:
                continue
            node.workers.discard(worker)
            self.postings.discard(sh, worker, node.workers)
            if not node.workers:
                self._drop_node(sh)
        self._worker_blocks.pop(worker, None)

    def clear_worker(self, worker: WorkerWithDpRank) -> None:
        self.remove_worker(worker)

    # -- query --------------------------------------------------------------
    def find_matches(
        self, block_hashes: List[SequenceHash], early_exit: bool = False
    ) -> OverlapScores:
        """Walk the query's hash chain; count per-worker contiguous matches.

        A worker's score is the number of *leading* blocks of the query it
        holds — only a contiguous prefix saves prefill work.

        The survivor set is never copied: the first block aliases the
        node's holder set read-only, and every later block's ``&`` already
        allocates a fresh set (the per-block ``set(holders)`` copy this
        loop used to make was pure overhead — on a fleet-hot prefix held
        by thousands of workers it was an O(fleet) allocation per block).
        """
        scores: Dict[WorkerWithDpRank, int] = {}
        active: Optional[Set[WorkerWithDpRank]] = None
        matched = 0
        nodes_visited = 0
        holder_sets = 0
        for sh in block_hashes:
            node = self._nodes.get(sh)
            if node is None or not node.workers:
                break
            nodes_visited += 1
            if active is None:
                holders = node.workers  # aliased read-only: no allocation
            else:
                holders = active & node.workers
                holder_sets += 1
            if not holders:
                break
            matched += 1
            for w in holders:
                scores[w] = matched
            active = holders
            if early_exit and len(active) == 1:
                # single candidate: extend its run without set machinery
                (w,) = active
                for sh2 in block_hashes[matched:]:
                    node2 = self._nodes.get(sh2)
                    if node2 is None or w not in node2.workers:
                        break
                    nodes_visited += 1
                    matched += 1
                    scores[w] = matched
                break
        self.last_nodes_visited = nodes_visited
        self.last_holder_sets = holder_sets
        return OverlapScores(scores=scores, matched_blocks=matched)

    def find_matches_for(
        self,
        candidates: Sequence[WorkerWithDpRank],
        block_hashes: List[SequenceHash],
    ) -> OverlapScores:
        """Exact contiguous-match scores restricted to ``candidates``:
        O(chain x |candidates|) membership probes, independent of how many
        other workers hold the prefix. ``matched_blocks`` is the deepest
        contiguous match *among the candidates* (the full-tree depth is
        irrelevant to a decision over this set)."""
        scores: Dict[WorkerWithDpRank, int] = {}
        alive = list(dict.fromkeys(candidates))
        matched = 0
        for sh in block_hashes:
            if not alive:
                break
            node = self._nodes.get(sh)
            if node is None or not node.workers:
                break
            holders = node.workers
            still = [w for w in alive if w in holders]
            if not still:
                break
            matched += 1
            for w in still:
                scores[w] = matched
            alive = still
        return OverlapScores(scores=scores, matched_blocks=matched)

    def top_prefix_workers(
        self, block_hashes: List[SequenceHash], k: int
    ) -> List[WorkerWithDpRank]:
        """Up to ``k`` workers holding the longest indexed prefix of the
        chain, deepest holders first, via the capped postings — O(chain+k),
        no holder-set walks. Approximate in two ways (both repaired by the
        exact rescoring stage): a bucket caps holders per block, and a
        worker posted deep may have evicted an earlier block."""
        if k <= 0 or not block_hashes:
            return []
        depth_hashes: List[SequenceHash] = []
        for sh in block_hashes:
            node = self._nodes.get(sh)
            if node is None or not node.workers:
                break
            depth_hashes.append(sh)
        out: List[WorkerWithDpRank] = []
        seen: Set[WorkerWithDpRank] = set()
        for sh in reversed(depth_hashes):
            for w in self.postings.posted(sh):
                if w not in seen:
                    seen.add(w)
                    out.append(w)
                    if len(out) >= k:
                        return out
        return out

    # -- snapshot -----------------------------------------------------------
    def snapshot(
        self, shard: Optional[int] = None, num_shards: int = 1
    ) -> dict:
        """Serializable tree state (reference: the router state snapshot
        gated by KvRouterConfig's snapshot threshold, kv_router.rs:163-165).
        With ``shard`` set, only nodes in that hash bucket are shipped —
        the per-shard replica-sync pieces (router.py) merge back into the
        identical full tree (postings rebuild incrementally via store)."""
        return {
            "nodes": [
                [n.seq_hash, n.parent, [w.to_obj() for w in sorted(n.workers)]]
                for n in self._nodes.values()
                if shard is None or shard_of(n.seq_hash, num_shards) == shard
            ]
        }

    def merge_snapshot(self, obj: dict) -> None:
        """Add every (node, worker) pair from a snapshot to this tree;
        existing state is kept (see KvIndexer.load_snapshot for why merge)."""
        for seq_hash, parent, workers in obj.get("nodes", []):
            for w in workers:
                self.store(WorkerWithDpRank.from_obj(w), [seq_hash], parent)
        # nodes arrive in arbitrary order; store() can only link a child to a
        # parent that already exists, so re-link in a second pass
        for node in self._nodes.values():
            if node.parent is not None and node.parent in self._nodes:
                self._nodes[node.parent].children.add(node.seq_hash)

    @classmethod
    def from_snapshot(cls, obj: dict) -> "RadixTree":
        tree = cls()
        tree.merge_snapshot(obj)
        return tree

    # -- introspection ------------------------------------------------------
    def worker_block_count(self, worker: WorkerWithDpRank) -> int:
        return len(self._worker_blocks.get(worker, ()))

    def workers(self) -> List[WorkerWithDpRank]:
        return list(self._worker_blocks)

    def __len__(self) -> int:
        return len(self._nodes)
