"""Prefix index over chained block hashes.

Analog of the reference's RadixTree (lib/kv-router/src/radix_tree.rs:73,
find_matches :154). Because block hashes are *chained* (a block's sequence
hash encodes its whole prefix), the "radix tree" flattens to a map
``sequence_hash -> set of workers holding that block`` plus parent links for
eviction bookkeeping: matching a query prefix is a walk down its hash chain
until no worker holds the next block. This is the same trick the reference's
FlatHashMap alternative index exploits (lib/kv-router/src/flat_hashmap.rs:113).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Set

from ..tokens import SequenceHash
from .protocols import OverlapScores, WorkerWithDpRank


@dataclasses.dataclass
class _Node:
    seq_hash: SequenceHash
    parent: Optional[SequenceHash]
    workers: Set[WorkerWithDpRank] = dataclasses.field(default_factory=set)
    children: Set[SequenceHash] = dataclasses.field(default_factory=set)


class RadixTree:
    def __init__(self):
        self._nodes: Dict[SequenceHash, _Node] = {}
        self._worker_blocks: Dict[WorkerWithDpRank, Set[SequenceHash]] = {}

    # -- mutation -----------------------------------------------------------
    def store(
        self,
        worker: WorkerWithDpRank,
        block_hashes: Iterable[SequenceHash],
        parent_hash: Optional[SequenceHash] = None,
    ) -> None:
        parent = parent_hash
        for sh in block_hashes:
            node = self._nodes.get(sh)
            if node is None:
                node = _Node(sh, parent)
                self._nodes[sh] = node
                if parent is not None and parent in self._nodes:
                    self._nodes[parent].children.add(sh)
            node.workers.add(worker)
            self._worker_blocks.setdefault(worker, set()).add(sh)
            parent = sh

    def remove(self, worker: WorkerWithDpRank, block_hashes: Iterable[SequenceHash]) -> None:
        for sh in block_hashes:
            node = self._nodes.get(sh)
            if node is None:
                continue
            node.workers.discard(worker)
            owned = self._worker_blocks.get(worker)
            if owned is not None:
                owned.discard(sh)
            if not node.workers:
                self._drop_node(sh)

    def _drop_node(self, sh: SequenceHash) -> None:
        node = self._nodes.pop(sh, None)
        if node is None:
            return
        if node.parent is not None and node.parent in self._nodes:
            self._nodes[node.parent].children.discard(sh)
        # children become orphans; they stay indexed (their own hashes still
        # fully identify their prefix) until their workers remove them

    def remove_worker(self, worker: WorkerWithDpRank) -> None:
        for sh in list(self._worker_blocks.get(worker, ())):
            node = self._nodes.get(sh)
            if node is None:
                continue
            node.workers.discard(worker)
            if not node.workers:
                self._drop_node(sh)
        self._worker_blocks.pop(worker, None)

    def clear_worker(self, worker: WorkerWithDpRank) -> None:
        self.remove_worker(worker)

    # -- query --------------------------------------------------------------
    def find_matches(
        self, block_hashes: List[SequenceHash], early_exit: bool = False
    ) -> OverlapScores:
        """Walk the query's hash chain; count per-worker contiguous matches.

        A worker's score is the number of *leading* blocks of the query it
        holds — only a contiguous prefix saves prefill work.
        """
        scores: Dict[WorkerWithDpRank, int] = {}
        active: Optional[Set[WorkerWithDpRank]] = None
        matched = 0
        for sh in block_hashes:
            node = self._nodes.get(sh)
            if node is None or not node.workers:
                break
            holders = node.workers if active is None else (active & node.workers)
            if not holders:
                break
            matched += 1
            for w in holders:
                scores[w] = matched
            active = set(holders)
            if early_exit and len(active) == 1:
                # single candidate: extend its run without set machinery
                (w,) = active
                for sh2 in block_hashes[matched:]:
                    node2 = self._nodes.get(sh2)
                    if node2 is None or w not in node2.workers:
                        break
                    matched += 1
                    scores[w] = matched
                break
        return OverlapScores(scores=scores, matched_blocks=matched)

    # -- snapshot -----------------------------------------------------------
    def snapshot(self) -> dict:
        """Serializable full-tree state (reference: the router state snapshot
        gated by KvRouterConfig's snapshot threshold, kv_router.rs:163-165)."""
        return {
            "nodes": [
                [n.seq_hash, n.parent, [w.to_obj() for w in sorted(n.workers)]]
                for n in self._nodes.values()
            ]
        }

    def merge_snapshot(self, obj: dict) -> None:
        """Add every (node, worker) pair from a snapshot to this tree;
        existing state is kept (see KvIndexer.load_snapshot for why merge)."""
        for seq_hash, parent, workers in obj.get("nodes", []):
            for w in workers:
                self.store(WorkerWithDpRank.from_obj(w), [seq_hash], parent)
        # nodes arrive in arbitrary order; store() can only link a child to a
        # parent that already exists, so re-link in a second pass
        for node in self._nodes.values():
            if node.parent is not None and node.parent in self._nodes:
                self._nodes[node.parent].children.add(node.seq_hash)

    @classmethod
    def from_snapshot(cls, obj: dict) -> "RadixTree":
        tree = cls()
        tree.merge_snapshot(obj)
        return tree

    # -- introspection ------------------------------------------------------
    def worker_block_count(self, worker: WorkerWithDpRank) -> int:
        return len(self._worker_blocks.get(worker, ()))

    def workers(self) -> List[WorkerWithDpRank]:
        return list(self._worker_blocks)

    def __len__(self) -> int:
        return len(self._nodes)
