"""KvRouter: ties indexer + scheduler + event-plane subscriber into one
routing service the frontend pipeline (or a standalone router process) uses.

Analog of the reference's KvRouter/KvScheduler service side
(lib/llm/src/kv_router/{kv_router,scheduler,subscriber}.rs). ``schedule()``
takes a tokenized request, hashes it into blocks, queries the prefix index,
and returns a (worker_id, dp_rank, overlap) decision; active-request
bookkeeping feeds the load term while worker metrics are in flight.

Replica sync (config.replica_sync, reference subscriber.rs): every routing
decision/completion is published on ``kv.sync.<ns>.<component>``; peer
routers ingest them so their load (and, in approx mode, prefix) views agree.
A router that starts late sends a snapshot request on the same topic and the
first peer to answer ships its full indexer state + in-flight load table.
"""

from __future__ import annotations

import asyncio
import random
import uuid
from typing import Dict, List, Optional, Sequence

import msgpack

from ..runtime import metrics as M
from ..runtime.event_plane.base import EventPlane, Subscription
from ..runtime.logging import get_logger
from ..tokens import compute_sequence_hashes
from .indexer import ApproxKvIndexer, KvIndexer
from .protocols import RouterEvent, WorkerMetrics, WorkerWithDpRank
from .publisher import events_topic, metrics_topic
from .scheduler import KvRouterConfig, KvScheduler, SchedulingDecision

log = get_logger("kv_router.router")


def sync_topic(namespace: str, component: str) -> str:
    return f"kv.sync.{namespace}.{component}"


class KvRouter:
    def __init__(
        self,
        event_plane: EventPlane,
        namespace: str,
        component: str,
        block_size: int = 16,
        config: Optional[KvRouterConfig] = None,
        seed: Optional[int] = None,
        recorder=None,
        metrics: Optional[M.MetricsScope] = None,
    ):
        self.config = config or KvRouterConfig()
        # optional runtime.recorder.Recorder: captures the ingested KV-event
        # stream as JSONL for offline replay (reference lib/llm/src/recorder.rs
        # feeding benchmarks/router playback)
        self.recorder = recorder
        # prefix-cache effectiveness on /metrics: tokens the chosen worker
        # already holds per routing decision (the reference's kv-hit-rate
        # signal); None = no registry attached (standalone/unit use)
        self._hit_tokens = (
            metrics.counter(
                M.KV_HIT_TOKENS,
                "prompt tokens matched in the chosen worker's prefix cache",
            )
            if metrics is not None else None
        )
        self.block_size = block_size
        self.namespace = namespace
        self.component = component
        self._plane = event_plane
        # seeded rng for the snapshot-answer jitter below: the fleet
        # simulator pins ``seed`` so replica-sync timing is reproducible
        self._rng = random.Random(seed)
        self.scheduler = KvScheduler(self.config, seed=seed)
        self.indexer: KvIndexer | ApproxKvIndexer
        if self.config.use_kv_events:
            self.indexer = KvIndexer(block_size)
        else:
            self.indexer = ApproxKvIndexer(block_size, ttl_s=self.config.approx_ttl_s)
        self._subs: List[Subscription] = []
        self._tasks: List[asyncio.Task] = []
        # request_id -> (worker, blocks) for free() on completion
        self._active: Dict[str, tuple] = {}
        # replica sync state
        self.router_id = uuid.uuid4().hex
        self._remote_active: Dict[tuple, tuple] = {}  # (router, req) -> (worker, blocks)
        self.synced_from_peer = False
        # frees with no matching active entry during the startup window are
        # remembered as tombstones, so a snapshot listing the same request
        # (built before the free) doesn't add phantom in-flight load
        self._free_tombstones: set = set()
        self._tombstone_deadline = 0.0
        # requesters whose snapshot someone already answered (reply dedup)
        self._snapshots_seen: set = set()

    async def start(self) -> "KvRouter":
        if self.config.use_kv_events:
            ev_sub = await self._plane.subscribe(events_topic(self.namespace, self.component))
            self._subs.append(ev_sub)
            self._tasks.append(asyncio.create_task(self._event_loop(ev_sub)))
        m_sub = await self._plane.subscribe(metrics_topic(self.namespace, self.component))
        self._subs.append(m_sub)
        self._tasks.append(asyncio.create_task(self._metrics_loop(m_sub)))
        if self.config.replica_sync:
            s_sub = await self._plane.subscribe(sync_topic(self.namespace, self.component))
            self._subs.append(s_sub)
            self._tasks.append(asyncio.create_task(self._sync_loop(s_sub)))
            self._tombstone_deadline = asyncio.get_running_loop().time() + 5.0
            await self._publish_sync({"kind": "snapshot_request"})
        return self

    async def _event_loop(self, sub: Subscription) -> None:
        assert isinstance(self.indexer, KvIndexer)
        async for _topic, payload in sub:
            try:
                obj = msgpack.unpackb(payload, raw=False)
                ev = RouterEvent.from_obj(obj)
                self.indexer.apply(ev)
                if self.recorder is not None:
                    self.recorder.record({"kind": "kv_event", "event": obj})
            except Exception:
                log.exception("bad router event")

    async def _metrics_loop(self, sub: Subscription) -> None:
        async for _topic, payload in sub:
            try:
                m = WorkerMetrics.from_obj(msgpack.unpackb(payload, raw=False))
                self.scheduler.update_metrics(m)
            except Exception:
                log.exception("bad metrics event")

    # -- replica sync --------------------------------------------------------
    async def _publish_sync(self, obj: dict) -> None:
        obj["router"] = self.router_id
        await self._plane.publish(
            sync_topic(self.namespace, self.component),
            msgpack.packb(obj, use_bin_type=True),
        )

    def _publish_sync_soon(self, obj: dict) -> None:
        """Fire-and-forget from sync code paths (schedule_tokens/complete)."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (unit tests driving the router synchronously)
        t = loop.create_task(self._publish_sync(obj))
        self._tasks.append(t)
        t.add_done_callback(lambda t: self._tasks.remove(t) if t in self._tasks else None)

    async def _sync_loop(self, sub: Subscription) -> None:
        async for _topic, payload in sub:
            try:
                obj = msgpack.unpackb(payload, raw=False)
                if obj.get("router") == self.router_id:
                    continue
                self._apply_sync(obj)
            except Exception:
                log.exception("bad sync event")

    def _apply_sync(self, obj: dict) -> None:
        kind = obj.get("kind")
        if kind == "route":
            worker = WorkerWithDpRank.from_obj(obj["worker"])
            blocks = int(obj["blocks"])
            key = (obj["router"], obj["request_id"])
            self._remote_active[key] = (worker, blocks)
            self.scheduler.add_local_load(worker, blocks)
            if isinstance(self.indexer, ApproxKvIndexer) and obj.get("hashes"):
                self.indexer.process_routed_request(list(obj["hashes"]), worker)
        elif kind == "free":
            entry = self._remote_active.pop((obj["router"], obj["request_id"]), None)
            if entry is not None:
                self.scheduler.sub_local_load(*entry)
            elif (
                not self.synced_from_peer
                and asyncio.get_running_loop().time() < self._tombstone_deadline
            ):
                # a free racing ahead of the snapshot that lists its request:
                # remember it so the snapshot entry is skipped, not leaked
                self._free_tombstones.add((obj["router"], obj["request_id"]))
        elif kind == "snapshot_request":
            self._answer_snapshot_soon(obj["router"])
        elif kind == "snapshot":
            target = obj.get("for")
            self._snapshots_seen.add(target)
            if target != self.router_id or self.synced_from_peer:
                return
            self.synced_from_peer = True
            self.indexer.load_snapshot(obj.get("indexer", {}))
            for rid, req_id, w_obj, blocks in obj.get("active", []):
                worker = WorkerWithDpRank.from_obj(w_obj)
                key = (rid, req_id)
                if rid == self.router_id:
                    # our own route reflected back by a peer's snapshot: the
                    # load already sits in _active, and our future 'free' is
                    # ignored by our own sync loop — adding here would leak
                    continue
                if key in self._free_tombstones or key in self._remote_active:
                    continue
                self._remote_active[key] = (worker, int(blocks))
                self.scheduler.add_local_load(worker, int(blocks))
            self._free_tombstones.clear()
            log.info(
                "router %s synced from peer: %d blocks, %d in-flight",
                self.router_id[:8], len(self.indexer.tree), len(self._remote_active),
            )

    def _answer_snapshot_soon(self, requester: str) -> None:
        """Reply to a snapshot request after a small jittered delay, skipping
        if another peer's answer for the same requester was seen meanwhile —
        without this, every peer ships its full tree for every joiner."""
        if not (len(self.indexer.tree) > 0 or self._active or self._remote_active):
            return
        self._snapshots_seen.discard(requester)

        async def answer() -> None:
            await asyncio.sleep(0.05 + 0.2 * self._rng.random())
            if requester in self._snapshots_seen:
                return
            await self._publish_sync(
                {
                    "kind": "snapshot",
                    "for": requester,
                    "indexer": self.indexer.snapshot(),
                    "active": [
                        [rid, req_id, w.to_obj(), blocks]
                        for (rid, req_id), (w, blocks) in self._remote_active.items()
                    ]
                    + [
                        [self.router_id, req_id, w.to_obj(), blocks]
                        for req_id, (w, blocks) in self._active.items()
                    ],
                }
            )

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        t = loop.create_task(answer())
        self._tasks.append(t)
        t.add_done_callback(lambda t: self._tasks.remove(t) if t in self._tasks else None)

    # -- the routing decision ------------------------------------------------
    def schedule_tokens(
        self,
        token_ids: Sequence[int],
        candidates: Sequence[WorkerWithDpRank],
        request_id: Optional[str] = None,
        cacheable: Optional[bool] = None,
        extra_costs: Optional[Dict[WorkerWithDpRank, float]] = None,
        hashes: Optional[Sequence[int]] = None,
    ) -> SchedulingDecision:
        """Multimodal prompts (image placeholder runs hash identically
        across different images) must not produce overlap estimates or
        enter the approx indexer — the engine never serves their blocks
        from cache. Cacheability is derived from the tokens themselves
        (placeholder sentinel present) unless the caller overrides; the
        LOAD accounting keeps the true block count either way.

        ``hashes`` lets a caller that already hashed the prompt (the
        disagg planner hashes once for scoring AND the transfer handshake)
        skip the re-hash; it must be ``compute_sequence_hashes(token_ids,
        self.block_size)``."""
        if cacheable is None:
            from ..models.vision import IMAGE_TOKEN_ID

            cacheable = IMAGE_TOKEN_ID not in token_ids
        if hashes is None:
            hashes = compute_sequence_hashes(token_ids, self.block_size)
        overlaps = self.indexer.find_matches(hashes if cacheable else [])
        tree_sizes = {c: self.indexer.tree.worker_block_count(c) for c in candidates}
        decision = self.scheduler.select_worker(
            candidates, overlaps, query_blocks=len(hashes),
            tree_sizes=tree_sizes, extra_costs=extra_costs,
        )
        new_blocks = decision.query_blocks - decision.overlap_blocks
        if self._hit_tokens is not None and decision.overlap_blocks > 0:
            self._hit_tokens.inc(decision.overlap_blocks * self.block_size)
        self.scheduler.add_local_load(decision.worker, new_blocks)
        if request_id is not None:
            self._active[request_id] = (decision.worker, new_blocks)
        if isinstance(self.indexer, ApproxKvIndexer) and cacheable:
            self.indexer.process_routed_request(hashes, decision.worker)
        if self.config.replica_sync and request_id is not None:
            msg = {
                "kind": "route",
                "request_id": request_id,
                "worker": decision.worker.to_obj(),
                "blocks": new_blocks,
            }
            if isinstance(self.indexer, ApproxKvIndexer):
                msg["hashes"] = list(hashes)
            self._publish_sync_soon(msg)
        return decision

    def score_tokens(
        self,
        token_ids: Sequence[int],
        candidates: Sequence[WorkerWithDpRank],
        extra_costs: Optional[Dict[WorkerWithDpRank, float]] = None,
        hashes: Optional[Sequence[int]] = None,
    ) -> SchedulingDecision:
        """Stateless pick: same overlap+load scoring as schedule_tokens but
        NO side effects — no optimistic load charge, no in-flight tracking,
        no approx-index update. For observers that only answer "where would
        this go?" (the endpoint picker, deploy/epp.py): they have no
        completion signal, so an optimistic charge could never be released
        and would drift the scheduler into anti-affinity noise. Worker load
        still tracks reality through the published WorkerMetrics. A caller
        that DOES dispatch on the decision follows up with
        :meth:`commit_route`. ``hashes`` skips the re-hash (pass [] for
        uncacheable prompts — overlap is then ignored but the load term
        keeps the true block count via ``token_ids``)."""
        if hashes is None:
            hashes = compute_sequence_hashes(token_ids, self.block_size)
        overlaps = self.indexer.find_matches(hashes)
        tree_sizes = {
            c: self.indexer.tree.worker_block_count(c) for c in candidates
        }
        query_blocks = max(
            len(hashes), len(token_ids) // self.block_size
        )
        return self.scheduler.select_worker(
            candidates, overlaps, query_blocks=query_blocks,
            tree_sizes=tree_sizes, extra_costs=extra_costs,
        )

    def commit_route(
        self, decision: SchedulingDecision, hashes: Sequence[int] = (),
    ) -> None:
        """Apply the routing bookkeeping ``schedule_tokens`` would have
        done for a decision obtained via :meth:`score_tokens`, once the
        caller has actually dispatched on it: optimistic load charge,
        prefix-hit metric, approx-index route record. Plan-then-maybe-
        deflect callers (the disagg planner) score first so an abandoned
        plan leaves zero phantom state."""
        new_blocks = decision.query_blocks - decision.overlap_blocks
        if self._hit_tokens is not None and decision.overlap_blocks > 0:
            self._hit_tokens.inc(decision.overlap_blocks * self.block_size)
        self.scheduler.add_local_load(decision.worker, new_blocks)
        if isinstance(self.indexer, ApproxKvIndexer) and hashes:
            self.indexer.process_routed_request(list(hashes), decision.worker)

    def complete(self, request_id: str) -> None:
        """Request finished: release its optimistic load contribution."""
        entry = self._active.pop(request_id, None)
        if entry is not None:
            worker, blocks = entry
            self.scheduler.sub_local_load(worker, blocks)
            if self.config.replica_sync:
                self._publish_sync_soon({"kind": "free", "request_id": request_id})

    def remove_worker_id(self, worker_id: int) -> None:
        # a dead worker may hold scheduler load without any tree blocks (it
        # was routed to but never published an event), so clear scheduler
        # state for every rank seen in the in-flight tables too
        gone = {w for w in self.indexer.tree.workers() if w.worker_id == worker_id}
        for table in (self._active, self._remote_active):
            gone.update(w for w, _ in table.values() if w.worker_id == worker_id)
        for w in gone:
            self.indexer.remove_worker(w)
            self.scheduler.remove_worker(w)
        self._active = {
            k: v for k, v in self._active.items() if v[0].worker_id != worker_id
        }
        self._remote_active = {
            k: v for k, v in self._remote_active.items() if v[0].worker_id != worker_id
        }

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for s in self._subs:
            s.cancel()
