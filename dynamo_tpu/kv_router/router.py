"""KvRouter: ties indexer + scheduler + event-plane subscriber into one
routing service the frontend pipeline (or a standalone router process) uses.

Analog of the reference's KvRouter/KvScheduler service side
(lib/llm/src/kv_router/{kv_router,scheduler,subscriber}.rs). ``schedule()``
takes a tokenized request, hashes it into blocks, queries the prefix index,
and returns a (worker_id, dp_rank, overlap) decision; active-request
bookkeeping feeds the load term while worker metrics are in flight.

The decision is two-stage at fleet scale (ROADMAP "control-plane scale"):

1. *Prune*: the K workers with the longest cached prefix (capped sharded
   postings, ``RadixTree.top_prefix_workers``) unioned with the K
   least-loaded workers (``KvScheduler.least_loaded`` load buckets) and any
   extra-cost standouts — O(chain + K log W), no fleet scan.
2. *Exact*: the unchanged ``select_worker`` softmax over that pruned set,
   with restricted-but-exact overlap scores (``find_matches_for``), so the
   transfer-cost and SLA terms ride along unmodified.

Pruning engages only above ``2 * topk_candidates`` eligible workers; small
fleets always score exactly, and ``topk_candidates=0`` disables it. Callers
may pass an explicit ``candidates`` list (legacy, O(fleet) to build) or —
the sublinear path — register workers once (``register_worker``) and route
by ``excluded`` set only.

Replica sync (config.replica_sync, reference subscriber.rs): every routing
decision/completion is published on ``kv.sync.<ns>.<component>``; peer
routers ingest them so their load (and, in approx mode, prefix) views agree.
A router that starts late sends a snapshot request on the same topic and the
first peer to answer ships its indexer state + in-flight load table. With
``index_shards > 1`` catch-up is per hash-bucket shard: one request and one
answer per shard, so no peer ever serializes its whole tree in one message
and different shards may be served by different peers.
"""

from __future__ import annotations

import asyncio
import heapq
import random
import uuid
from typing import Dict, List, Optional, Sequence, Set

import msgpack

from ..runtime import metrics as M
from ..runtime.clock import WALL, Clock
from ..runtime.event_plane.base import EventPlane, Subscription
from ..runtime.logging import get_logger
from ..tokens import compute_sequence_hashes
from .indexer import ApproxKvIndexer, KvIndexer
from .protocols import RouterEvent, WorkerMetrics, WorkerWithDpRank
from .publisher import events_topic, metrics_topic
from .scheduler import KvRouterConfig, KvScheduler, SchedulingDecision

log = get_logger("kv_router.router")


def sync_topic(namespace: str, component: str) -> str:
    return f"kv.sync.{namespace}.{component}"


class KvRouter:
    def __init__(
        self,
        event_plane: EventPlane,
        namespace: str,
        component: str,
        block_size: int = 16,
        config: Optional[KvRouterConfig] = None,
        seed: Optional[int] = None,
        recorder=None,
        metrics: Optional[M.MetricsScope] = None,
        clock: Optional[Clock] = None,
    ):
        self.config = config or KvRouterConfig()
        # optional runtime.recorder.Recorder: captures the ingested KV-event
        # stream as JSONL for offline replay (reference lib/llm/src/recorder.rs
        # feeding benchmarks/router playback)
        self.recorder = recorder
        # prefix-cache effectiveness on /metrics: tokens the chosen worker
        # already holds per routing decision (the reference's kv-hit-rate
        # signal); None = no registry attached (standalone/unit use)
        self._hit_tokens = (
            metrics.counter(
                M.KV_HIT_TOKENS,
                "prompt tokens matched in the chosen worker's prefix cache",
            )
            if metrics is not None else None
        )
        self.block_size = block_size
        self.namespace = namespace
        self.component = component
        self._plane = event_plane
        # injected time source: metric staleness, approx TTLs and the
        # snapshot-answer jitter all ride it, so the fleet simulator's
        # virtual clock governs every router timing deterministically
        self._clock = clock if clock is not None else WALL
        # seeded rng for the snapshot-answer jitter below: the fleet
        # simulator pins ``seed`` so replica-sync timing is reproducible
        self._rng = random.Random(seed)
        self.scheduler = KvScheduler(
            self.config, seed=seed, clock=self._clock.time
        )
        self.indexer: KvIndexer | ApproxKvIndexer
        if self.config.use_kv_events:
            self.indexer = KvIndexer(
                block_size,
                shards=self.config.index_shards,
                postings_bucket=self.config.postings_bucket,
            )
        else:
            self.indexer = ApproxKvIndexer(
                block_size,
                ttl_s=self.config.approx_ttl_s,
                shards=self.config.index_shards,
                postings_bucket=self.config.postings_bucket,
                clock=self._clock.time,
            )
        self._subs: List[Subscription] = []
        self._tasks: List[asyncio.Task] = []
        # request_id -> (worker, blocks) for free() on completion
        self._active: Dict[str, tuple] = {}
        # prune-vs-exact decision counters (deterministic; sim reports them)
        self.pruned_decisions = 0
        self.exact_decisions = 0
        # replica sync state
        self.router_id = uuid.uuid4().hex
        self._remote_active: Dict[tuple, tuple] = {}  # (router, req) -> (worker, blocks)
        self.synced_from_peer = False
        self._synced_shards: Set[int] = set()
        # frees with no matching active entry during the startup window are
        # remembered as tombstones, so a snapshot listing the same request
        # (built before the free) doesn't add phantom in-flight load
        self._free_tombstones: set = set()
        self._tombstone_deadline = 0.0
        # (requester, shard) pairs whose snapshot someone already answered
        # (reply dedup; shard None = legacy whole-state snapshots)
        self._snapshots_seen: set = set()

    async def start(self) -> "KvRouter":
        if self.config.use_kv_events:
            ev_sub = await self._plane.subscribe(events_topic(self.namespace, self.component))
            self._subs.append(ev_sub)
            self._tasks.append(asyncio.create_task(self._event_loop(ev_sub)))
        m_sub = await self._plane.subscribe(metrics_topic(self.namespace, self.component))
        self._subs.append(m_sub)
        self._tasks.append(asyncio.create_task(self._metrics_loop(m_sub)))
        if self.config.replica_sync:
            s_sub = await self._plane.subscribe(sync_topic(self.namespace, self.component))
            self._subs.append(s_sub)
            self._tasks.append(asyncio.create_task(self._sync_loop(s_sub)))
            self._tombstone_deadline = asyncio.get_running_loop().time() + 5.0
            shards = max(1, self.config.index_shards)
            if shards > 1:
                # per-shard catch-up: peers answer shard-by-shard, and the
                # in-flight load table rides the shard-0 answer only
                for i in range(shards):
                    await self._publish_sync(
                        {"kind": "snapshot_request", "shard": i,
                         "shards": shards}
                    )
            else:
                await self._publish_sync({"kind": "snapshot_request"})
        return self

    # -- the candidate universe ----------------------------------------------
    def register_worker(self, worker: WorkerWithDpRank) -> None:
        """Add a routing target to the scheduler's universe (idempotent).
        Required for candidate-free routing (``candidates=None``): callers
        register instances as discovery sees them and afterwards pass only
        per-request ``excluded`` sets — O(K) per decision, not O(fleet)."""
        self.scheduler.register_worker(worker)

    async def _event_loop(self, sub: Subscription) -> None:
        assert isinstance(self.indexer, KvIndexer)
        async for _topic, payload in sub:
            try:
                obj = msgpack.unpackb(payload, raw=False)
                ev = RouterEvent.from_obj(obj)
                self.indexer.apply(ev)
                if self.recorder is not None:
                    self.recorder.record({"kind": "kv_event", "event": obj})
            except Exception:
                log.exception("bad router event")

    async def _metrics_loop(self, sub: Subscription) -> None:
        async for _topic, payload in sub:
            try:
                m = WorkerMetrics.from_obj(msgpack.unpackb(payload, raw=False))
                self.scheduler.update_metrics(m)
            except Exception:
                log.exception("bad metrics event")

    # -- replica sync --------------------------------------------------------
    async def _publish_sync(self, obj: dict) -> None:
        obj["router"] = self.router_id
        await self._plane.publish(
            sync_topic(self.namespace, self.component),
            msgpack.packb(obj, use_bin_type=True),
        )

    def _publish_sync_soon(self, obj: dict) -> None:
        """Fire-and-forget from sync code paths (schedule_tokens/complete)."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (unit tests driving the router synchronously)
        t = loop.create_task(self._publish_sync(obj))
        self._tasks.append(t)
        t.add_done_callback(lambda t: self._tasks.remove(t) if t in self._tasks else None)

    async def _sync_loop(self, sub: Subscription) -> None:
        async for _topic, payload in sub:
            try:
                obj = msgpack.unpackb(payload, raw=False)
                if obj.get("router") == self.router_id:
                    continue
                self._apply_sync(obj)
            except Exception:
                log.exception("bad sync event")

    def _apply_sync(self, obj: dict) -> None:
        kind = obj.get("kind")
        if kind == "route":
            worker = WorkerWithDpRank.from_obj(obj["worker"])
            blocks = int(obj["blocks"])
            key = (obj["router"], obj["request_id"])
            # peer re-route (migration retry): release the superseded
            # attempt's charge, mirroring schedule_tokens' own bookkeeping
            prev = self._remote_active.pop(key, None)
            if prev is not None:
                self.scheduler.sub_local_load(*prev)
            self._remote_active[key] = (worker, blocks)
            self.scheduler.add_local_load(worker, blocks)
            if isinstance(self.indexer, ApproxKvIndexer) and obj.get("hashes"):
                self.indexer.process_routed_request(list(obj["hashes"]), worker)
        elif kind == "free":
            entry = self._remote_active.pop((obj["router"], obj["request_id"]), None)
            if entry is not None:
                self.scheduler.sub_local_load(*entry)
            elif (
                not self.synced_from_peer
                and asyncio.get_running_loop().time() < self._tombstone_deadline
            ):
                # a free racing ahead of the snapshot that lists its request:
                # remember it so the snapshot entry is skipped, not leaked
                self._free_tombstones.add((obj["router"], obj["request_id"]))
        elif kind == "snapshot_request":
            self._answer_snapshot_soon(
                obj["router"], obj.get("shard"), obj.get("shards", 1)
            )
        elif kind == "snapshot":
            self._apply_snapshot(obj)

    def _apply_snapshot(self, obj: dict) -> None:
        target = obj.get("for")
        shard = obj.get("shard")  # None = legacy whole-state snapshot
        self._snapshots_seen.add((target, shard))
        if target != self.router_id:
            return
        if shard is None:
            if self.synced_from_peer:
                return
        elif shard in self._synced_shards:
            return
        self._synced_shards.add(shard if shard is not None else 0)
        self.indexer.load_snapshot(obj.get("indexer", {}))
        if shard is None or shard == 0:
            # the in-flight load table is not hash-partitioned: it rides the
            # shard-0 (or legacy whole-state) answer exactly once
            self.synced_from_peer = True
            for rid, req_id, w_obj, blocks in obj.get("active", []):
                worker = WorkerWithDpRank.from_obj(w_obj)
                key = (rid, req_id)
                if rid == self.router_id:
                    # our own route reflected back by a peer's snapshot: the
                    # load already sits in _active, and our future 'free' is
                    # ignored by our own sync loop — adding here would leak
                    continue
                if key in self._free_tombstones or key in self._remote_active:
                    continue
                self._remote_active[key] = (worker, int(blocks))
                self.scheduler.add_local_load(worker, int(blocks))
            self._free_tombstones.clear()
        log.info(
            "router %s synced from peer (shard %s): %d blocks, %d in-flight",
            self.router_id[:8], "all" if shard is None else shard,
            len(self.indexer.tree), len(self._remote_active),
        )

    def _answer_snapshot_soon(
        self, requester: str, shard: Optional[int] = None, num_shards: int = 1
    ) -> None:
        """Reply to a snapshot request after a small jittered delay, skipping
        if another peer's answer for the same (requester, shard) was seen
        meanwhile — without this, every peer ships its full tree for every
        joiner."""
        if not (len(self.indexer.tree) > 0 or self._active or self._remote_active):
            return
        key = (requester, shard)
        self._snapshots_seen.discard(key)

        async def answer() -> None:
            await self._clock.sleep(0.05 + 0.2 * self._rng.random())
            if key in self._snapshots_seen:
                return
            msg = {
                "kind": "snapshot",
                "for": requester,
                "indexer": self.indexer.snapshot(
                    shard=shard, num_shards=num_shards
                ),
            }
            if shard is not None:
                msg["shard"] = shard
                msg["shards"] = num_shards
            if shard is None or shard == 0:
                msg["active"] = [
                    [rid, req_id, w.to_obj(), blocks]
                    for (rid, req_id), (w, blocks) in self._remote_active.items()
                ] + [
                    [self.router_id, req_id, w.to_obj(), blocks]
                    for req_id, (w, blocks) in self._active.items()
                ]
            await self._publish_sync(msg)

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        t = loop.create_task(answer())
        self._tasks.append(t)
        t.add_done_callback(lambda t: self._tasks.remove(t) if t in self._tasks else None)

    # -- the routing decision ------------------------------------------------
    def _decide(
        self,
        candidates: Optional[Sequence[WorkerWithDpRank]],
        excluded,
        extra_costs: Optional[Dict[WorkerWithDpRank, float]],
        match_hashes: Sequence[int],
        query_blocks: int,
        fetchable: Optional[Dict[WorkerWithDpRank, float]] = None,
    ) -> SchedulingDecision:
        """The two-stage selection shared by schedule_tokens/score_tokens:
        prune to ~2-3K candidates when the eligible universe is large, then
        run the exact scorer on whatever survived. ``candidates`` None means
        "every registered worker" (the sublinear path); an explicit list is
        honored exactly (and its members are registered as a side effect so
        the load index covers idle workers on later calls)."""
        sched = self.scheduler
        excl = excluded if excluded else ()
        k = self.config.topk_candidates
        if candidates is not None:
            for c in candidates:
                sched.register_worker(c)
            n = len(candidates)
        else:
            n = sched.worker_count()
        pool: Optional[List[WorkerWithDpRank]] = None
        pruned = False
        if k > 0 and n > 2 * k:
            prefix_c = self.indexer.top_prefix_workers(match_hashes, k)
            load_c = sched.least_loaded(k, excl)
            extras = (
                heapq.nsmallest(
                    k, extra_costs, key=lambda w: (extra_costs[w], w)
                )
                if extra_costs else ()
            )
            member = None if candidates is None else set(candidates)
            pool_d: Dict[WorkerWithDpRank, None] = {}
            for w in (*prefix_c, *load_c, *extras):
                if w in excl:
                    continue
                if member is not None and w not in member:
                    continue
                pool_d[w] = None
            if pool_d:
                pool = list(pool_d)
                pruned = True
        if pool is None:
            base = candidates if candidates is not None else sched.known_workers()
            pool = [w for w in base if w not in excl] if excl else list(base)
            if not pool:
                # exclusion emptied the pool: a shunned worker beats no
                # worker (the discovery/_candidates fallback semantics);
                # callers that must fail instead pre-check their own
                # instance tables
                pool = list(base)
        if not pool:
            raise ValueError("no candidate workers")
        if pruned:
            overlaps = self.indexer.find_matches_for(pool, match_hashes)
            self.pruned_decisions += 1
        else:
            overlaps = self.indexer.find_matches(match_hashes)
            self.exact_decisions += 1
        tree_sizes = {c: self.indexer.tree.worker_block_count(c) for c in pool}
        return sched.select_worker(
            pool, overlaps, query_blocks=query_blocks,
            tree_sizes=tree_sizes, extra_costs=extra_costs,
            fetchable=fetchable,
        )

    def schedule_tokens(
        self,
        token_ids: Sequence[int],
        candidates: Optional[Sequence[WorkerWithDpRank]] = None,
        request_id: Optional[str] = None,
        cacheable: Optional[bool] = None,
        extra_costs: Optional[Dict[WorkerWithDpRank, float]] = None,
        hashes: Optional[Sequence[int]] = None,
        excluded=None,
        fetchable: Optional[Dict[WorkerWithDpRank, float]] = None,
    ) -> SchedulingDecision:
        """Multimodal prompts (image placeholder runs hash identically
        across different images) must not produce overlap estimates or
        enter the approx indexer — the engine never serves their blocks
        from cache. Cacheability is derived from the tokens themselves
        (placeholder sentinel present) unless the caller overrides; the
        LOAD accounting keeps the true block count either way.

        ``hashes`` lets a caller that already hashed the prompt (the
        disagg planner hashes once for scoring AND the transfer handshake)
        skip the re-hash; it must be ``compute_sequence_hashes(token_ids,
        self.block_size)``. ``candidates=None`` routes over every
        registered worker minus ``excluded`` — the O(K) path."""
        if cacheable is None:
            from ..models.vision import IMAGE_TOKEN_ID

            cacheable = IMAGE_TOKEN_ID not in token_ids
        if hashes is None:
            hashes = compute_sequence_hashes(token_ids, self.block_size)
        decision = self._decide(
            candidates, excluded, extra_costs,
            match_hashes=(hashes if cacheable else []),
            query_blocks=len(hashes),
            fetchable=fetchable,
        )
        new_blocks = decision.query_blocks - decision.overlap_blocks
        if self._hit_tokens is not None and decision.overlap_blocks > 0:
            self._hit_tokens.inc(decision.overlap_blocks * self.block_size)
        self.scheduler.add_local_load(decision.worker, new_blocks)
        if request_id is not None:
            # a re-route of the same request (migration retry after worker
            # loss) releases the failed attempt's optimistic charge first —
            # overwriting the entry would leak phantom load onto the dead/
            # flapping worker forever, permanently steering traffic off it
            prev = self._active.pop(request_id, None)
            if prev is not None:
                self.scheduler.sub_local_load(*prev)
            self._active[request_id] = (decision.worker, new_blocks)
        if isinstance(self.indexer, ApproxKvIndexer) and cacheable:
            self.indexer.process_routed_request(hashes, decision.worker)
        if self.config.replica_sync and request_id is not None:
            msg = {
                "kind": "route",
                "request_id": request_id,
                "worker": decision.worker.to_obj(),
                "blocks": new_blocks,
            }
            if isinstance(self.indexer, ApproxKvIndexer):
                msg["hashes"] = list(hashes)
            self._publish_sync_soon(msg)
        return decision

    def score_tokens(
        self,
        token_ids: Sequence[int],
        candidates: Optional[Sequence[WorkerWithDpRank]] = None,
        extra_costs: Optional[Dict[WorkerWithDpRank, float]] = None,
        hashes: Optional[Sequence[int]] = None,
        excluded=None,
        fetchable: Optional[Dict[WorkerWithDpRank, float]] = None,
    ) -> SchedulingDecision:
        """Stateless pick: same overlap+load scoring as schedule_tokens but
        NO side effects — no optimistic load charge, no in-flight tracking,
        no approx-index update. For observers that only answer "where would
        this go?" (the endpoint picker, deploy/epp.py): they have no
        completion signal, so an optimistic charge could never be released
        and would drift the scheduler into anti-affinity noise. Worker load
        still tracks reality through the published WorkerMetrics. A caller
        that DOES dispatch on the decision follows up with
        :meth:`commit_route`. ``hashes`` skips the re-hash (pass [] for
        uncacheable prompts — overlap is then ignored but the load term
        keeps the true block count via ``token_ids``)."""
        if hashes is None:
            hashes = compute_sequence_hashes(token_ids, self.block_size)
        query_blocks = max(
            len(hashes), len(token_ids) // self.block_size
        )
        return self._decide(
            candidates, excluded, extra_costs,
            match_hashes=hashes, query_blocks=query_blocks,
            fetchable=fetchable,
        )

    def commit_route(
        self, decision: SchedulingDecision, hashes: Sequence[int] = (),
    ) -> None:
        """Apply the routing bookkeeping ``schedule_tokens`` would have
        done for a decision obtained via :meth:`score_tokens`, once the
        caller has actually dispatched on it: optimistic load charge,
        prefix-hit metric, approx-index route record. Plan-then-maybe-
        deflect callers (the disagg planner) score first so an abandoned
        plan leaves zero phantom state."""
        new_blocks = decision.query_blocks - decision.overlap_blocks
        if self._hit_tokens is not None and decision.overlap_blocks > 0:
            self._hit_tokens.inc(decision.overlap_blocks * self.block_size)
        self.scheduler.add_local_load(decision.worker, new_blocks)
        if isinstance(self.indexer, ApproxKvIndexer) and hashes:
            self.indexer.process_routed_request(list(hashes), decision.worker)

    def complete(self, request_id: str) -> None:
        """Request finished: release its optimistic load contribution."""
        entry = self._active.pop(request_id, None)
        if entry is not None:
            worker, blocks = entry
            self.scheduler.sub_local_load(worker, blocks)
            if self.config.replica_sync:
                self._publish_sync_soon({"kind": "free", "request_id": request_id})

    def remove_worker_id(self, worker_id: int) -> None:
        # a dead worker may hold scheduler load without any tree blocks (it
        # was routed to but never published an event), so clear scheduler
        # state for every rank seen in the in-flight tables — and the
        # registered universe, so candidate-free routing never re-picks it
        gone = {w for w in self.indexer.tree.workers() if w.worker_id == worker_id}
        gone.update(
            w for w in self.scheduler.known_workers()
            if w.worker_id == worker_id
        )
        for table in (self._active, self._remote_active):
            gone.update(w for w, _ in table.values() if w.worker_id == worker_id)
        for w in gone:
            self.indexer.remove_worker(w)
            self.scheduler.remove_worker(w)
        self._active = {
            k: v for k, v in self._active.items() if v[0].worker_id != worker_id
        }
        self._remote_active = {
            k: v for k, v in self._remote_active.items() if v[0].worker_id != worker_id
        }

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for s in self._subs:
            s.cancel()
