"""KvRouter: ties indexer + scheduler + event-plane subscriber into one
routing service the frontend pipeline (or a standalone router process) uses.

Analog of the reference's KvRouter/KvScheduler service side
(lib/llm/src/kv_router/{kv_router,scheduler,subscriber}.rs). ``schedule()``
takes a tokenized request, hashes it into blocks, queries the prefix index,
and returns a (worker_id, dp_rank, overlap) decision; active-request
bookkeeping feeds the load term while worker metrics are in flight.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Sequence

import msgpack

from ..runtime.event_plane.base import EventPlane, Subscription
from ..runtime.logging import get_logger
from ..tokens import compute_sequence_hashes
from .indexer import ApproxKvIndexer, KvIndexer
from .protocols import RouterEvent, WorkerMetrics, WorkerWithDpRank
from .publisher import events_topic, metrics_topic
from .scheduler import KvRouterConfig, KvScheduler, SchedulingDecision

log = get_logger("kv_router.router")


class KvRouter:
    def __init__(
        self,
        event_plane: EventPlane,
        namespace: str,
        component: str,
        block_size: int = 16,
        config: Optional[KvRouterConfig] = None,
        seed: Optional[int] = None,
    ):
        self.config = config or KvRouterConfig()
        self.block_size = block_size
        self.namespace = namespace
        self.component = component
        self._plane = event_plane
        self.scheduler = KvScheduler(self.config, seed=seed)
        self.indexer: KvIndexer | ApproxKvIndexer
        if self.config.use_kv_events:
            self.indexer = KvIndexer(block_size)
        else:
            self.indexer = ApproxKvIndexer(block_size, ttl_s=self.config.approx_ttl_s)
        self._subs: List[Subscription] = []
        self._tasks: List[asyncio.Task] = []
        # request_id -> (worker, blocks) for free() on completion
        self._active: Dict[str, tuple] = {}

    async def start(self) -> "KvRouter":
        if self.config.use_kv_events:
            ev_sub = await self._plane.subscribe(events_topic(self.namespace, self.component))
            self._subs.append(ev_sub)
            self._tasks.append(asyncio.create_task(self._event_loop(ev_sub)))
        m_sub = await self._plane.subscribe(metrics_topic(self.namespace, self.component))
        self._subs.append(m_sub)
        self._tasks.append(asyncio.create_task(self._metrics_loop(m_sub)))
        return self

    async def _event_loop(self, sub: Subscription) -> None:
        assert isinstance(self.indexer, KvIndexer)
        async for _topic, payload in sub:
            try:
                ev = RouterEvent.from_obj(msgpack.unpackb(payload, raw=False))
                self.indexer.apply(ev)
            except Exception:
                log.exception("bad router event")

    async def _metrics_loop(self, sub: Subscription) -> None:
        async for _topic, payload in sub:
            try:
                m = WorkerMetrics.from_obj(msgpack.unpackb(payload, raw=False))
                self.scheduler.update_metrics(m)
            except Exception:
                log.exception("bad metrics event")

    # -- the routing decision ------------------------------------------------
    def schedule_tokens(
        self,
        token_ids: Sequence[int],
        candidates: Sequence[WorkerWithDpRank],
        request_id: Optional[str] = None,
    ) -> SchedulingDecision:
        hashes = compute_sequence_hashes(token_ids, self.block_size)
        overlaps = self.indexer.find_matches(hashes)
        tree_sizes = {c: self.indexer.tree.worker_block_count(c) for c in candidates}
        decision = self.scheduler.select_worker(
            candidates, overlaps, query_blocks=len(hashes), tree_sizes=tree_sizes
        )
        new_blocks = decision.query_blocks - decision.overlap_blocks
        self.scheduler.add_local_load(decision.worker, new_blocks)
        if request_id is not None:
            self._active[request_id] = (decision.worker, new_blocks)
        if isinstance(self.indexer, ApproxKvIndexer):
            self.indexer.process_routed_request(hashes, decision.worker)
        return decision

    def complete(self, request_id: str) -> None:
        """Request finished: release its optimistic load contribution."""
        entry = self._active.pop(request_id, None)
        if entry is not None:
            worker, blocks = entry
            self.scheduler.sub_local_load(worker, blocks)

    def remove_worker_id(self, worker_id: int) -> None:
        for w in [w for w in self.indexer.tree.workers() if w.worker_id == worker_id]:
            self.indexer.remove_worker(w)
            self.scheduler.remove_worker(w)

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for s in self._subs:
            s.cancel()
