"""Worker-side publishers: KV cache events + load metrics onto the event plane.

Analogs of the reference's KvEventPublisher (lib/llm/src/kv_router/publisher.rs:112)
and WorkerMetricsPublisher (publisher.rs:957). Topic scheme::

    kv.events.<namespace>.<component>     RouterEvent (msgpack)
    kv.metrics.<namespace>.<component>    WorkerMetrics (msgpack)
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Iterable, Optional

import msgpack

from ..runtime.event_plane.base import EventPlane
from ..runtime.logging import get_logger
from ..tokens import SequenceHash
from .protocols import KvCacheEvent, KvEventKind, RouterEvent, WorkerMetrics, WorkerWithDpRank

log = get_logger("kv_router.publisher")


def events_topic(namespace: str, component: str) -> str:
    return f"kv.events.{namespace}.{component}"


def metrics_topic(namespace: str, component: str) -> str:
    return f"kv.metrics.{namespace}.{component}"


class KvEventPublisher:
    def __init__(
        self,
        event_plane: EventPlane,
        namespace: str,
        component: str,
        worker_id: int,
        dp_rank: int = 0,
        block_size: int = 16,
    ):
        self._plane = event_plane
        self._topic = events_topic(namespace, component)
        self.worker = WorkerWithDpRank(worker_id, dp_rank)
        self.block_size = block_size
        self._next_event_id = 1

    async def _publish(self, event: KvCacheEvent) -> None:
        ev = RouterEvent(worker=self.worker, event=event, event_id=self._next_event_id)
        self._next_event_id += 1
        await self._plane.publish(self._topic, msgpack.packb(ev.to_obj(), use_bin_type=True))

    async def stored(
        self, block_hashes: Iterable[SequenceHash], parent_hash: Optional[SequenceHash] = None
    ) -> None:
        await self._publish(
            KvCacheEvent(
                kind=KvEventKind.STORED,
                block_hashes=list(block_hashes),
                parent_hash=parent_hash,
                block_size=self.block_size,
            )
        )

    async def removed(self, block_hashes: Iterable[SequenceHash]) -> None:
        await self._publish(
            KvCacheEvent(kind=KvEventKind.REMOVED, block_hashes=list(block_hashes))
        )

    async def cleared(self) -> None:
        await self._publish(KvCacheEvent(kind=KvEventKind.CLEARED))


class WorkerMetricsPublisher:
    """Periodic load snapshots; drive with publish() or run() background loop."""

    def __init__(
        self,
        event_plane: EventPlane,
        namespace: str,
        component: str,
        worker_id: int,
        dp_rank: int = 0,
        clock: Callable[[], float] = time.time,
        sleep: Callable[[float], "asyncio.Future"] = asyncio.sleep,
    ):
        self._plane = event_plane
        self._topic = metrics_topic(namespace, component)
        self.worker = WorkerWithDpRank(worker_id, dp_rank)
        # metric freshness is judged against the consumer's clock
        # (planner metrics_source, router scheduler): a simulated fleet
        # injects its virtual clock so both sides share one timeline
        self._clock = clock
        # the polling loop paces through this (Clock.sleep under the sim)
        self._sleep = sleep
        self._task: Optional[asyncio.Task] = None

    async def publish(
        self,
        active_decode_blocks: int = 0,
        active_prefill_tokens: int = 0,
        num_requests_waiting: int = 0,
        num_requests_active: int = 0,
        total_blocks: int = 0,
        waiting_prefill_blocks: int = 0,
    ) -> None:
        m = WorkerMetrics(
            worker=self.worker,
            active_decode_blocks=active_decode_blocks,
            active_prefill_tokens=active_prefill_tokens,
            num_requests_waiting=num_requests_waiting,
            num_requests_active=num_requests_active,
            total_blocks=total_blocks,
            waiting_prefill_blocks=waiting_prefill_blocks,
            ts=self._clock(),
        )
        await self._plane.publish(self._topic, msgpack.packb(m.to_obj(), use_bin_type=True))

    def start(self, snapshot_fn, interval_s: float = 1.0) -> None:
        """snapshot_fn() -> dict of publish() kwargs, polled every interval."""

        async def loop() -> None:
            try:
                while True:
                    try:
                        await self.publish(**snapshot_fn())
                    except Exception:
                        log.exception("metrics publish failed")
                    await self._sleep(interval_s)
            except asyncio.CancelledError:
                pass

        self._task = asyncio.create_task(loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
