"""KV-routing wire protocols.

Analog of the reference's router protocols (lib/kv-router/src/protocols.rs:
KvCacheEvent :264, RouterEvent :477, OverlapScores :502, WorkerWithDpRank :93).
Everything here crosses the event plane as msgpack, so the types are plain
dataclasses with dict codecs.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Tuple

from ..tokens import SequenceHash


@dataclasses.dataclass(frozen=True, order=True)
class WorkerWithDpRank:
    """Routing target: a worker instance plus its data-parallel rank.

    Each dp_rank owns an independent KV pool, so the router must track and
    score them separately (reference scheduler loops every dp_rank,
    lib/llm/src/kv_router/scheduler.rs:543-560)."""

    worker_id: int
    dp_rank: int = 0

    def to_obj(self) -> List[int]:
        return [self.worker_id, self.dp_rank]

    @classmethod
    def from_obj(cls, obj) -> "WorkerWithDpRank":
        return cls(int(obj[0]), int(obj[1]))


class KvEventKind(enum.Enum):
    STORED = "stored"
    REMOVED = "removed"
    CLEARED = "cleared"  # worker dropped its whole cache (restart/reset)


@dataclasses.dataclass
class KvCacheEvent:
    """One mutation of a worker's KV cache, in sequence-hash space."""

    kind: KvEventKind
    # STORED: hashes of newly cached blocks, in order, chained from parent_hash
    block_hashes: List[SequenceHash] = dataclasses.field(default_factory=list)
    parent_hash: Optional[SequenceHash] = None
    # tokens-per-block for sanity checks across heterogeneous pools
    block_size: int = 0

    def to_obj(self) -> Dict:
        return {
            "kind": self.kind.value,
            "block_hashes": self.block_hashes,
            "parent_hash": self.parent_hash,
            "block_size": self.block_size,
        }

    @classmethod
    def from_obj(cls, obj: Dict) -> "KvCacheEvent":
        return cls(
            kind=KvEventKind(obj["kind"]),
            block_hashes=list(obj.get("block_hashes", [])),
            parent_hash=obj.get("parent_hash"),
            block_size=obj.get("block_size", 0),
        )


@dataclasses.dataclass
class RouterEvent:
    """KvCacheEvent stamped with its origin (worker, dp_rank) + sequence no."""

    worker: WorkerWithDpRank
    event: KvCacheEvent
    event_id: int = 0

    def to_obj(self) -> Dict:
        return {"worker": self.worker.to_obj(), "event": self.event.to_obj(), "id": self.event_id}

    @classmethod
    def from_obj(cls, obj: Dict) -> "RouterEvent":
        return cls(
            worker=WorkerWithDpRank.from_obj(obj["worker"]),
            event=KvCacheEvent.from_obj(obj["event"]),
            event_id=obj.get("id", 0),
        )


@dataclasses.dataclass
class OverlapScores:
    """find_matches result: matched-block counts per routing target."""

    scores: Dict[WorkerWithDpRank, int] = dataclasses.field(default_factory=dict)
    # how many leading blocks of the query exist *anywhere* (frequency info)
    matched_blocks: int = 0

    def best(self) -> Tuple[Optional[WorkerWithDpRank], int]:
        if not self.scores:
            return None, 0
        worker = max(self.scores, key=lambda w: (self.scores[w], -w.worker_id))
        return worker, self.scores[worker]


@dataclasses.dataclass
class WorkerMetrics:
    """Per-(worker, dp_rank) load snapshot published by workers.

    Analog of the reference's WorkerMetricsPublisher payload
    (lib/llm/src/kv_router/publisher.rs:957 — active_decode_blocks etc.)."""

    worker: WorkerWithDpRank
    active_decode_blocks: int = 0
    active_prefill_tokens: int = 0
    num_requests_waiting: int = 0
    # blocks the worker has ACCEPTED but not yet admitted (its waiting
    # queue, in block units): the scheduler folds these into the load
    # term so a report can supersede the router's optimistic charges
    # without erasing queued work the worker already owns
    waiting_prefill_blocks: int = 0
    # running SEQUENCES (not blocks): the planner's ITL interpolation is
    # keyed on decode concurrency, which blocks overstate by ctx/block_size
    num_requests_active: int = 0
    total_blocks: int = 0
    ts: float = 0.0

    def to_obj(self) -> Dict:
        return {
            "worker": self.worker.to_obj(),
            "decode_blocks": self.active_decode_blocks,
            "prefill_tokens": self.active_prefill_tokens,
            "waiting": self.num_requests_waiting,
            "waiting_blocks": self.waiting_prefill_blocks,
            "active": self.num_requests_active,
            "total_blocks": self.total_blocks,
            "ts": self.ts,
        }

    @classmethod
    def from_obj(cls, obj: Dict) -> "WorkerMetrics":
        return cls(
            worker=WorkerWithDpRank.from_obj(obj["worker"]),
            active_decode_blocks=obj.get("decode_blocks", 0),
            active_prefill_tokens=obj.get("prefill_tokens", 0),
            num_requests_waiting=obj.get("waiting", 0),
            waiting_prefill_blocks=obj.get("waiting_blocks", 0),
            num_requests_active=obj.get("active", 0),
            total_blocks=obj.get("total_blocks", 0),
            ts=obj.get("ts", 0.0),
        )
