"""KV-aware worker selection: softmax over a prefill+decode cost.

Analog of the reference's KvScheduler / DefaultWorkerSelector
(lib/llm/src/kv_router/scheduler.rs:93,511-601):

    logit(w) = overlap_weight * potential_prefill_blocks(w) + decode_blocks(w)

where ``potential_prefill_blocks = query_blocks - overlap_blocks(w)`` (work the
worker would still have to do) and ``decode_blocks`` is its current load. The
*lowest* logit is best; selection samples a softmax over ``-logit / T`` with
temperature T (T=0 -> argmin), tie-breaking toward the worker with the
smallest cached-block footprint to spread the tree.
"""

from __future__ import annotations

import dataclasses
import math
import random
import time
from typing import Dict, Optional, Sequence

from ..runtime.logging import get_logger
from .protocols import OverlapScores, WorkerMetrics, WorkerWithDpRank

log = get_logger("kv_router.scheduler")


@dataclasses.dataclass
class KvRouterConfig:
    """Knobs mirroring the reference's KvRouterConfig
    (lib/llm/src/kv_router/kv_router.rs:139-165)."""

    overlap_score_weight: float = 1.0
    router_temperature: float = 0.0
    use_kv_events: bool = True            # False -> ApproxKvIndexer
    # publish routing decisions / completions on the event plane and ingest
    # peers', so replicated routers share one load + (approx) prefix view;
    # new replicas catch up via a snapshot handshake (reference:
    # lib/llm/src/kv_router/subscriber.rs, kv_router.rs:163-165)
    replica_sync: bool = False
    metrics_stale_after_s: float = 10.0
    approx_ttl_s: float = 120.0


@dataclasses.dataclass
class SchedulingDecision:
    worker: WorkerWithDpRank
    overlap_blocks: int
    query_blocks: int
    logits: Dict[WorkerWithDpRank, float]

    @property
    def cached_tokens(self) -> int:
        return self.overlap_blocks  # caller multiplies by block_size


class KvScheduler:
    def __init__(self, config: Optional[KvRouterConfig] = None, seed: Optional[int] = None):
        self.config = config or KvRouterConfig()
        self._rng = random.Random(seed)
        # live load state, fed by WorkerMetrics events + local bookkeeping
        self._metrics: Dict[WorkerWithDpRank, WorkerMetrics] = {}
        # blocks this router routed but the worker hasn't reported yet
        self._local_decode_blocks: Dict[WorkerWithDpRank, int] = {}

    # -- state feeds ---------------------------------------------------------
    def update_metrics(self, m: WorkerMetrics) -> None:
        # staleness is judged against *our* clock: stamp arrival time rather
        # than trusting the producer's wall clock (cross-host skew would
        # silently disable the load term)
        m.ts = time.time()
        self._metrics[m.worker] = m
        # worker's own report supersedes our optimistic local estimate
        self._local_decode_blocks[m.worker] = 0

    def add_local_load(self, worker: WorkerWithDpRank, blocks: int) -> None:
        self._local_decode_blocks[worker] = self._local_decode_blocks.get(worker, 0) + blocks

    def sub_local_load(self, worker: WorkerWithDpRank, blocks: int) -> None:
        self._local_decode_blocks[worker] = max(
            0, self._local_decode_blocks.get(worker, 0) - blocks
        )

    def remove_worker(self, worker: WorkerWithDpRank) -> None:
        self._metrics.pop(worker, None)
        self._local_decode_blocks.pop(worker, None)

    def decode_blocks(self, worker: WorkerWithDpRank) -> int:
        m = self._metrics.get(worker)
        reported = 0
        if m is not None and (
            self.config.metrics_stale_after_s <= 0
            or time.time() - m.ts < self.config.metrics_stale_after_s
        ):
            reported = m.active_decode_blocks
        return reported + self._local_decode_blocks.get(worker, 0)

    # -- selection -----------------------------------------------------------
    def select_worker(
        self,
        candidates: Sequence[WorkerWithDpRank],
        overlaps: OverlapScores,
        query_blocks: int,
        tree_sizes: Optional[Dict[WorkerWithDpRank, int]] = None,
        extra_costs: Optional[Dict[WorkerWithDpRank, float]] = None,
    ) -> SchedulingDecision:
        """``extra_costs`` adds a per-candidate cost in BLOCK units to the
        logit — the transfer-cost-aware term (NetKV-style): disagg routing
        passes each prefill candidate's estimated wire time for the KV it
        would have to ship, normalized by the per-block prefill time, so a
        candidate behind a slow wire loses to one a device hop away even at
        equal queue depth."""
        if not candidates:
            raise ValueError("no candidate workers")
        w = self.config.overlap_score_weight
        logits: Dict[WorkerWithDpRank, float] = {}
        for cand in candidates:
            overlap = overlaps.scores.get(cand, 0)
            potential_prefill = max(0, query_blocks - overlap)
            logits[cand] = (
                w * potential_prefill + self.decode_blocks(cand)
                + (extra_costs.get(cand, 0.0) if extra_costs else 0.0)
            )

        chosen = self._sample(logits, tree_sizes or {})
        return SchedulingDecision(
            worker=chosen,
            overlap_blocks=overlaps.scores.get(chosen, 0),
            query_blocks=query_blocks,
            logits=logits,
        )

    def _sample(
        self, logits: Dict[WorkerWithDpRank, float], tree_sizes: Dict[WorkerWithDpRank, int]
    ) -> WorkerWithDpRank:
        temp = self.config.router_temperature
        items = sorted(logits.items(), key=lambda kv: (kv[1], tree_sizes.get(kv[0], 0), kv[0]))
        if temp <= 0.0:
            best_logit = items[0][1]
            best = [wk for wk, lg in items if lg == best_logit]
            if len(best) == 1:
                return best[0]
            # tie-break: fewest cached blocks spreads load across the fleet
            return min(best, key=lambda wk: (tree_sizes.get(wk, 0), wk))
        # softmax over negative cost (lower cost -> higher probability)
        mx = max(-lg / temp for _, lg in items)
        weights = [math.exp(-lg / temp - mx) for _, lg in items]
        total = sum(weights)
        r = self._rng.random() * total
        acc = 0.0
        for (wk, _), wt in zip(items, weights):
            acc += wt
            if r <= acc:
                return wk
        return items[-1][0]
