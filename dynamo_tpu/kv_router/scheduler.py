"""KV-aware worker selection: softmax over a prefill+decode cost.

Analog of the reference's KvScheduler / DefaultWorkerSelector
(lib/llm/src/kv_router/scheduler.rs:93,511-601):

    logit(w) = overlap_weight * potential_prefill_blocks(w) + decode_blocks(w)

where ``potential_prefill_blocks = query_blocks - overlap_blocks(w)`` (work the
worker would still have to do) and ``decode_blocks`` is its current load. The
*lowest* logit is best; selection samples a softmax over ``-logit / T`` with
temperature T (T=0 -> argmin), tie-breaking toward the worker with the
smallest cached-block footprint to spread the tree.

Scale: ``select_worker`` stays the *exact* scorer; at fleet scale the router
(router.py) calls it on a pruned candidate set instead of every worker. The
scheduler's half of pruning lives here: a registry of known routing targets
plus a load-ordered bucket index answering "the K least-loaded workers" in
O(K log B) without scanning the fleet.
"""

from __future__ import annotations

import dataclasses
import heapq
import math
import os
import random
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..runtime.config import ENV_ROUTER_REPLICA_SYNC, env_bool
from ..runtime.logging import get_logger
from .protocols import OverlapScores, WorkerMetrics, WorkerWithDpRank

log = get_logger("kv_router.scheduler")

# removed-worker tombstones retained against straggler metric reports; far
# above any live fleet's churn window, tiny either way
_TOMBSTONE_CAP = 65536


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


@dataclasses.dataclass
class KvRouterConfig:
    """Knobs mirroring the reference's KvRouterConfig
    (lib/llm/src/kv_router/kv_router.rs:139-165)."""

    overlap_score_weight: float = 1.0
    router_temperature: float = 0.0
    use_kv_events: bool = True            # False -> ApproxKvIndexer
    # publish routing decisions / completions on the event plane and ingest
    # peers', so replicated routers share one load + (approx) prefix view;
    # new replicas catch up via a snapshot handshake (reference:
    # lib/llm/src/kv_router/subscriber.rs, kv_router.rs:163-165)
    replica_sync: bool = dataclasses.field(
        default_factory=lambda: env_bool(ENV_ROUTER_REPLICA_SYNC, False)
    )
    metrics_stale_after_s: float = 10.0
    approx_ttl_s: float = 120.0
    # -- two-stage decision knobs (docs/operations.md "Router scale") -------
    # top-K candidate pruning: union of the K longest-prefix workers
    # (postings index), the K least-loaded workers (load buckets) and any
    # extra-cost standouts is scored exactly; 0 disables pruning. Pruning
    # only engages above 2*K eligible workers, so small fleets are always
    # exact.
    topk_candidates: int = dataclasses.field(
        default_factory=lambda: _env_int("DTPU_ROUTER_TOPK", 16)
    )
    # hash-bucket shards of the postings index + the replica-sync snapshot
    # protocol (one shard = legacy whole-state snapshots)
    index_shards: int = dataclasses.field(
        default_factory=lambda: _env_int("DTPU_ROUTER_SHARDS", 1)
    )
    # capped per-block postings size (postings.py)
    postings_bucket: int = dataclasses.field(
        default_factory=lambda: _env_int("DTPU_ROUTER_POSTINGS_BUCKET", 8)
    )


@dataclasses.dataclass
class SchedulingDecision:
    worker: WorkerWithDpRank
    overlap_blocks: int
    query_blocks: int
    logits: Dict[WorkerWithDpRank, float]

    @property
    def cached_tokens(self) -> int:
        return self.overlap_blocks  # caller multiplies by block_size


class _LoadIndex:
    """Load-ordered worker buckets: ``least(k)`` yields the K lowest-load
    workers in O(K + touched-buckets log B). Buckets are keyed by the
    integer load value; a lazy min-heap orders non-empty bucket keys
    (stale/duplicate keys are dropped on pop). Iteration inside a bucket
    is insertion-ordered — deterministic given a deterministic update
    stream, which the fleet sim's byte-identical reports rely on."""

    __slots__ = ("_load", "_buckets", "_heap")

    def __init__(self):
        self._load: Dict[WorkerWithDpRank, int] = {}
        self._buckets: Dict[int, Dict[WorkerWithDpRank, None]] = {}
        self._heap: List[int] = []

    def set(self, w: WorkerWithDpRank, load: int) -> None:
        load = int(load)
        cur = self._load.get(w)
        if cur == load:
            return
        if cur is not None:
            b = self._buckets.get(cur)
            if b is not None:
                b.pop(w, None)
        self._load[w] = load
        b = self._buckets.get(load)
        if b is None:
            b = self._buckets[load] = {}
            heapq.heappush(self._heap, load)
        b[w] = None

    def remove(self, w: WorkerWithDpRank) -> None:
        cur = self._load.pop(w, None)
        if cur is not None:
            b = self._buckets.get(cur)
            if b is not None:
                b.pop(w, None)

    def least(self, k: int, excluded=()) -> List[WorkerWithDpRank]:
        out: List[WorkerWithDpRank] = []
        popped: List[int] = []
        seen_keys: set = set()
        while self._heap and len(out) < k:
            key = heapq.heappop(self._heap)
            if key in seen_keys:
                continue  # duplicate heap entry (bucket re-created): drop
            seen_keys.add(key)
            b = self._buckets.get(key)
            if not b:
                # empty bucket: drop the key AND the bucket dict for good
                self._buckets.pop(key, None)
                continue
            popped.append(key)
            for w in b:
                if w in excluded:
                    continue
                out.append(w)
                if len(out) >= k:
                    break
        for key in popped:
            heapq.heappush(self._heap, key)
        return out

    def __len__(self) -> int:
        return len(self._load)


class KvScheduler:
    def __init__(
        self,
        config: Optional[KvRouterConfig] = None,
        seed: Optional[int] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.config = config or KvRouterConfig()
        self._rng = random.Random(seed)
        # metric-staleness judgments ride the injected clock so the fleet
        # simulator's virtual time governs them deterministically
        self._clock = clock
        # live load state, fed by WorkerMetrics events + local bookkeeping
        self._metrics: Dict[WorkerWithDpRank, WorkerMetrics] = {}
        # blocks this router routed but the worker hasn't reported yet
        self._local_decode_blocks: Dict[WorkerWithDpRank, int] = {}
        # every routing target ever registered/observed (insertion-ordered:
        # the candidate universe when callers route by exclusion), plus the
        # load-bucket index answering least_loaded without a fleet scan
        self._workers: Dict[WorkerWithDpRank, None] = {}
        self._loads = _LoadIndex()
        # tombstones: workers explicitly removed (discovery departure,
        # retire, reclaim). A straggler metrics report arriving after the
        # removal must NOT resurrect the worker as a routing candidate —
        # a draining engine keeps publishing until it stops, and a ghost
        # that re-registers at zero-ish load wins the least-loaded prune
        # exactly while real workers honestly report deep queues. Only an
        # explicit re-register (discovery says it's back) clears the mark.
        # Insertion-ordered and bounded: a long-lived router under fleet
        # churn trims the oldest tombstones (a publisher that still lingers
        # months later is not a real failure mode).
        self._removed: Dict[WorkerWithDpRank, None] = {}

    # -- state feeds ---------------------------------------------------------
    def register_worker(self, worker: WorkerWithDpRank) -> None:
        """Make ``worker`` part of the candidate universe (idempotent).
        Discovery/fleet layers call this as instances appear so idle
        workers are reachable through the least-loaded prune path before
        they ever publish metrics or serve a request."""
        self._removed.pop(worker, None)
        if worker not in self._workers:
            self._workers[worker] = None
            self._loads.set(worker, self._raw_load(worker))

    def _raw_load(self, worker: WorkerWithDpRank) -> int:
        """Index load: last reported decode blocks + optimistic local. The
        index deliberately skips the staleness check ``decode_blocks``
        applies — it orders *candidates for exact rescoring*, which then
        prices staleness exactly."""
        m = self._metrics.get(worker)
        reported = (
            m.active_decode_blocks + m.waiting_prefill_blocks
            if m is not None else 0
        )
        return reported + self._local_decode_blocks.get(worker, 0)

    def update_metrics(self, m: WorkerMetrics) -> None:
        if m.worker in self._removed:
            # late report from a removed worker: drop it wholesale
            return
        # staleness is judged against *our* clock: stamp arrival time rather
        # than trusting the producer's wall clock (cross-host skew would
        # silently disable the load term)
        m.ts = self._clock()
        self._metrics[m.worker] = m
        # worker's own report supersedes our optimistic local estimate —
        # it covers BOTH admitted work (active_decode_blocks) and its
        # still-queued backlog (waiting_prefill_blocks), so zeroing the
        # local charges never hides accepted-but-waiting requests
        self._local_decode_blocks[m.worker] = 0
        self._workers.setdefault(m.worker, None)
        self._loads.set(
            m.worker, m.active_decode_blocks + m.waiting_prefill_blocks
        )

    def add_local_load(self, worker: WorkerWithDpRank, blocks: int) -> None:
        if worker in self._removed:
            # a charge can race the removal (decision in flight while
            # discovery retires the worker): never resurrect the candidate
            return
        self._local_decode_blocks[worker] = self._local_decode_blocks.get(worker, 0) + blocks
        self._workers.setdefault(worker, None)
        self._loads.set(worker, self._raw_load(worker))

    def sub_local_load(self, worker: WorkerWithDpRank, blocks: int) -> None:
        if worker not in self._workers:
            # late release for a removed worker (an in-flight request
            # completing after remove_worker): drop the residue instead of
            # resurrecting a dead worker as a zero-load routing candidate
            self._local_decode_blocks.pop(worker, None)
            return
        self._local_decode_blocks[worker] = max(
            0, self._local_decode_blocks.get(worker, 0) - blocks
        )
        self._loads.set(worker, self._raw_load(worker))

    def remove_worker(self, worker: WorkerWithDpRank) -> None:
        self._metrics.pop(worker, None)
        self._local_decode_blocks.pop(worker, None)
        self._workers.pop(worker, None)
        self._loads.remove(worker)
        self._removed[worker] = None
        while len(self._removed) > _TOMBSTONE_CAP:
            self._removed.pop(next(iter(self._removed)))

    def decode_blocks(self, worker: WorkerWithDpRank) -> int:
        m = self._metrics.get(worker)
        reported = 0
        if m is not None and (
            self.config.metrics_stale_after_s <= 0
            or self._clock() - m.ts < self.config.metrics_stale_after_s
        ):
            reported = m.active_decode_blocks + m.waiting_prefill_blocks
        return reported + self._local_decode_blocks.get(worker, 0)

    # -- the prune-stage feeds (router.py) -----------------------------------
    def worker_count(self) -> int:
        return len(self._workers)

    def known_workers(self) -> List[WorkerWithDpRank]:
        return list(self._workers)

    def least_loaded(self, k: int, excluded=()) -> List[WorkerWithDpRank]:
        return self._loads.least(k, excluded)

    # -- selection -----------------------------------------------------------
    def select_worker(
        self,
        candidates: Sequence[WorkerWithDpRank],
        overlaps: OverlapScores,
        query_blocks: int,
        tree_sizes: Optional[Dict[WorkerWithDpRank, int]] = None,
        extra_costs: Optional[Dict[WorkerWithDpRank, float]] = None,
        fetchable: Optional[Dict[WorkerWithDpRank, float]] = None,
    ) -> SchedulingDecision:
        """``extra_costs`` adds a per-candidate cost in BLOCK units to the
        logit — the transfer-cost-aware term (NetKV-style): disagg routing
        passes each prefill candidate's estimated wire time for the KV it
        would have to ship, normalized by the per-block prefill time, so a
        candidate behind a slow wire loses to one a device hop away even at
        equal queue depth.

        ``fetchable`` is the directory-aware term (kvbm/directory.py): per
        candidate, how many of the query's blocks it could onboard from a
        peer's G2/G3 tier cheaper than recomputing — in EFFECTIVE block
        units, i.e. already discounted by the fetch/recompute cost ratio
        (ops/costs.fetch_vs_recompute), so a fleet-hot prefix shrinks a
        cold worker's potential-prefill term without ever counting a
        fetched block as free."""
        if not candidates:
            raise ValueError("no candidate workers")
        w = self.config.overlap_score_weight
        logits: Dict[WorkerWithDpRank, float] = {}
        for cand in candidates:
            overlap = overlaps.scores.get(cand, 0)
            potential_prefill = max(0, query_blocks - overlap)
            if fetchable:
                # a block can't be both locally cached and discounted again:
                # the fetchable term only shrinks what overlap left behind
                potential_prefill = max(
                    0.0, potential_prefill - fetchable.get(cand, 0.0)
                )
            logits[cand] = (
                w * potential_prefill + self.decode_blocks(cand)
                + (extra_costs.get(cand, 0.0) if extra_costs else 0.0)
            )

        chosen = self._sample(logits, tree_sizes or {})
        return SchedulingDecision(
            worker=chosen,
            overlap_blocks=overlaps.scores.get(chosen, 0),
            query_blocks=query_blocks,
            logits=logits,
        )

    def _sample(
        self, logits: Dict[WorkerWithDpRank, float], tree_sizes: Dict[WorkerWithDpRank, int]
    ) -> WorkerWithDpRank:
        temp = self.config.router_temperature
        items = sorted(logits.items(), key=lambda kv: (kv[1], tree_sizes.get(kv[0], 0), kv[0]))
        if temp <= 0.0:
            best_logit = items[0][1]
            best = [wk for wk, lg in items if lg == best_logit]
            if len(best) == 1:
                return best[0]
            # tie-break: fewest cached blocks spreads load across the fleet
            return min(best, key=lambda wk: (tree_sizes.get(wk, 0), wk))
        # softmax over negative cost (lower cost -> higher probability)
        mx = max(-lg / temp for _, lg in items)
        weights = [math.exp(-lg / temp - mx) for _, lg in items]
        total = sum(weights)
        r = self._rng.random() * total
        acc = 0.0
        for (wk, _), wt in zip(items, weights):
            acc += wt
            if r <= acc:
                return wk
        return items[-1][0]
