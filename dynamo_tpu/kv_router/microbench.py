"""Deterministic CPU micro-bench of the routing decision (BENCH detail.router).

A seeded synthetic prefix tree + fleet at a few sizes, scored through the
real ``KvRouter`` decision path twice — pruned (configured top-K) and exact
(top-K forced to 0, the linear scan) — so every BENCH run carries a router
decisions/s datapoint and the pruned-vs-exact candidate counts, with no
device and no event loop. State construction is a pure function of the
seed; the timings are wall-clock like every other bench number.
"""

from __future__ import annotations

import random
import time
from typing import Dict, Sequence

from ..tokens import compute_sequence_hashes
from .protocols import KvCacheEvent, KvEventKind, RouterEvent, WorkerWithDpRank
from .router import KvRouter
from .scheduler import KvRouterConfig


def _build_router(
    n_workers: int, seed: int, topk: int, block_size: int = 16,
    groups: int = 32, blocks_per_group: int = 16, holders_per_group: int = 24,
) -> tuple:
    """A router over a synthetic fleet: every worker carries a random load,
    each prefix group's hash chain is held by a seeded worker subset — fed
    through the real event-stream ``KvIndexer.apply`` path."""
    from ..runtime.event_plane.base import InProcEventPlane

    rng = random.Random(seed * 1000003 + n_workers)
    router = KvRouter(
        InProcEventPlane(), "bench", "router", block_size=block_size,
        config=KvRouterConfig(topk_candidates=topk), seed=seed,
    )
    workers = [WorkerWithDpRank(i) for i in range(n_workers)]
    for w in workers:
        router.register_worker(w)
        load = rng.randrange(0, 64)
        if load:
            router.scheduler.add_local_load(w, load)
    group_tokens = []
    eid = 0
    for g in range(groups):
        tokens = [(g * 977 + j * 13) % 1021 for j in range(blocks_per_group * block_size)]
        group_tokens.append(tokens)
        hashes = compute_sequence_hashes(tokens, block_size)
        for w in rng.sample(workers, min(holders_per_group, n_workers)):
            eid += 1
            router.indexer.apply(RouterEvent(
                w, KvCacheEvent(KvEventKind.STORED, list(hashes), None, block_size),
                eid,
            ))
    return router, group_tokens, rng


def _queries(group_tokens, rng: random.Random, n: int, block_size: int):
    """Trace-shaped probe prompts: a hot-group prefix plus a unique tail,
    and a share of fully cold prompts."""
    out = []
    for i in range(n):
        if rng.random() < 0.2:
            out.append([rng.randrange(1021) for _ in range(12 * block_size)])
        else:
            base = group_tokens[rng.randrange(len(group_tokens))]
            tail = [rng.randrange(1021) for _ in range(4 * block_size)]
            out.append(list(base[: 8 * block_size]) + tail)
    return out


def router_microbench(
    sizes: Sequence[int] = (256, 2048, 8192),
    decisions: int = 200,
    seed: int = 0,
    topk: int = 16,
) -> Dict:
    """The BENCH ``detail.router`` record: per fleet size, decisions/s and
    mean scored-candidate count for the pruned path vs the exact scan."""
    out: Dict = {"topk": topk, "decisions": decisions, "sizes": {}}
    for n in sizes:
        router, group_tokens, rng = _build_router(n, seed, topk)
        prompts = _queries(group_tokens, rng, decisions, router.block_size)

        def run(k: int) -> Dict:
            saved = router.config.topk_candidates
            router.config.topk_candidates = k
            try:
                for toks in prompts[: min(20, len(prompts))]:
                    router.score_tokens(toks)  # warm
                scored = 0
                t0 = time.perf_counter()
                for toks in prompts:
                    scored += len(router.score_tokens(toks).logits)
                dt = time.perf_counter() - t0
            finally:
                router.config.topk_candidates = saved
            return {
                "decisions_per_s": round(len(prompts) / max(dt, 1e-9), 1),
                "mean_candidates_scored": round(scored / max(len(prompts), 1), 1),
            }

        out["sizes"][str(n)] = {
            "pruned": run(topk),
            "exact": run(0),
        }
    return out
