"""KV indexers: event-driven (exact) and approximate (TTL-simulated).

Analogs of the reference's KvIndexer (lib/kv-router/src/indexer.rs:453) and
ApproxKvIndexer with its TTL PruneManager (lib/kv-router/src/approx.rs).

Both are built on the same RadixTree, so both expose the two-stage query
surface the router's pruned decision path needs (radix_tree.py):
``top_prefix_workers`` (capped postings, O(chain+K)) for the prune stage
and ``find_matches_for`` (restricted exact scores) for the rescore stage.
Snapshots are shard-addressable (``shard``/``num_shards``) so replica sync
can ship router state one hash bucket at a time (router.py).
"""

from __future__ import annotations

import heapq
import time
from typing import Callable, Dict, List, Optional

from ..runtime.logging import get_logger
from ..tokens import SequenceHash
from .postings import shard_of
from .protocols import KvEventKind, OverlapScores, RouterEvent, WorkerWithDpRank
from .radix_tree import RadixTree

log = get_logger("kv_router.indexer")


class KvIndexer:
    """Exact prefix index built from worker KV-cache events."""

    def __init__(
        self,
        block_size: int = 16,
        shards: int = 1,
        postings_bucket: int = 8,
    ):
        self.block_size = block_size
        self.tree = RadixTree(postings_bucket=postings_bucket, shards=shards)
        self.shards = max(1, shards)
        self._last_event_id: Dict[WorkerWithDpRank, int] = {}
        self.events_applied = 0
        self.events_dropped = 0

    def apply(self, ev: RouterEvent) -> None:
        last = self._last_event_id.get(ev.worker)
        if ev.event_id and last is not None and ev.event_id <= last:
            self.events_dropped += 1  # replay/duplicate
            return
        if ev.event_id:
            self._last_event_id[ev.worker] = ev.event_id
        kind = ev.event.kind
        if kind == KvEventKind.STORED:
            if ev.event.block_size and ev.event.block_size != self.block_size:
                log.warning(
                    "worker %s block_size %d != router %d; ignoring event",
                    ev.worker, ev.event.block_size, self.block_size,
                )
                self.events_dropped += 1
                return
            self.tree.store(ev.worker, ev.event.block_hashes, ev.event.parent_hash)
        elif kind == KvEventKind.REMOVED:
            self.tree.remove(ev.worker, ev.event.block_hashes)
        elif kind == KvEventKind.CLEARED:
            self.tree.clear_worker(ev.worker)
        self.events_applied += 1

    def find_matches(self, block_hashes: List[SequenceHash]) -> OverlapScores:
        return self.tree.find_matches(block_hashes)

    def find_matches_for(self, candidates, block_hashes) -> OverlapScores:
        return self.tree.find_matches_for(candidates, block_hashes)

    def top_prefix_workers(self, block_hashes, k: int):
        return self.tree.top_prefix_workers(block_hashes, k)

    def remove_worker(self, worker: WorkerWithDpRank) -> None:
        self.tree.remove_worker(worker)
        self._last_event_id.pop(worker, None)

    def remove_worker_id(self, worker_id: int) -> None:
        for w in [w for w in self.tree.workers() if w.worker_id == worker_id]:
            self.remove_worker(w)

    def block_count(self) -> int:
        return len(self.tree)

    def snapshot(
        self, shard: Optional[int] = None, num_shards: int = 1
    ) -> dict:
        """Full state, or one hash-bucket shard of it. Event-id high-water
        marks ride every shard piece (they are per-worker, not per-hash)
        and merge idempotently via max."""
        return {
            "tree": self.tree.snapshot(shard=shard, num_shards=num_shards),
            "last_event_id": [
                [w.to_obj(), eid] for w, eid in self._last_event_id.items()
            ],
        }

    def load_snapshot(self, obj: dict) -> None:
        """MERGE a peer's snapshot into local state (new-replica catch-up).

        Merging — not replacing — means KV events applied live while the
        snapshot was in flight are never wiped (events and sync ride separate
        topics with no cross-topic ordering), and per-shard pieces compose:
        merging every shard of a peer equals merging its whole-tree
        snapshot. The cost is soft: a block the worker REMOVED between
        snapshot-build and arrival is resurrected until the worker's next
        removal/clear — a stale routing hint, not a correctness loss.
        Event-id high-water marks take the max per worker so the replay
        guard stays tight."""
        self.tree.merge_snapshot(obj.get("tree", {}))
        for w_obj, eid in obj.get("last_event_id", []):
            w = WorkerWithDpRank.from_obj(w_obj)
            self._last_event_id[w] = max(self._last_event_id.get(w, 0), int(eid))


class ApproxKvIndexer:
    """Eventless fallback: the router *assumes* whatever it routed is cached.

    On each routed request, insert its block hashes for the chosen worker with
    a TTL; a lazy min-heap prune expires entries (reference PruneManager,
    lib/kv-router/src/approx.rs). Accuracy degrades under eviction pressure,
    but no worker cooperation is required. TTL expiry rides the injected
    ``clock`` so the fleet simulator's virtual time governs pruning.
    """

    def __init__(
        self,
        block_size: int = 16,
        ttl_s: float = 120.0,
        shards: int = 1,
        postings_bucket: int = 8,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.block_size = block_size
        self.ttl_s = ttl_s
        self.tree = RadixTree(postings_bucket=postings_bucket, shards=shards)
        self.shards = max(1, shards)
        self._clock = clock
        # (expiry_time, worker, seq_hash)
        self._expiry_heap: List = []
        self._expiry: Dict = {}  # (worker, seq_hash) -> latest expiry

    def process_routed_request(
        self, block_hashes: List[SequenceHash], worker: WorkerWithDpRank,
        now: Optional[float] = None,
    ) -> None:
        now = self._clock() if now is None else now
        expiry = now + self.ttl_s
        self.tree.store(worker, block_hashes, None)
        for sh in block_hashes:
            self._expiry[(worker, sh)] = expiry
            heapq.heappush(self._expiry_heap, (expiry, worker, sh))
        self._prune(now)

    def find_matches(
        self, block_hashes: List[SequenceHash], now: Optional[float] = None
    ) -> OverlapScores:
        self._prune(self._clock() if now is None else now)
        return self.tree.find_matches(block_hashes)

    def find_matches_for(
        self, candidates, block_hashes, now: Optional[float] = None
    ) -> OverlapScores:
        self._prune(self._clock() if now is None else now)
        return self.tree.find_matches_for(candidates, block_hashes)

    def top_prefix_workers(
        self, block_hashes, k: int, now: Optional[float] = None
    ):
        self._prune(self._clock() if now is None else now)
        return self.tree.top_prefix_workers(block_hashes, k)

    def remove_worker(self, worker: WorkerWithDpRank) -> None:
        self.tree.remove_worker(worker)
        self._expiry = {k: v for k, v in self._expiry.items() if k[0] != worker}

    def snapshot(
        self, shard: Optional[int] = None, num_shards: int = 1
    ) -> dict:
        now = self._clock()
        return {
            "ttl": [
                [w.to_obj(), sh, max(0.0, exp - now)]
                for (w, sh), exp in self._expiry.items()
                if shard is None or shard_of(sh, num_shards) == shard
            ]
        }

    def load_snapshot(self, obj: dict) -> None:
        now = self._clock()
        for w_obj, sh, remaining in obj.get("ttl", []):
            w = WorkerWithDpRank.from_obj(w_obj)
            expiry = now + float(remaining)
            # never shorten a fresher TTL learned from live route sync while
            # the snapshot was in flight (stale heap entries are skipped by
            # _prune's current-expiry check)
            if expiry <= self._expiry.get((w, sh), 0.0):
                continue
            self.tree.store(w, [sh], None)
            self._expiry[(w, sh)] = expiry
            heapq.heappush(self._expiry_heap, (expiry, w, sh))

    def _prune(self, now: float) -> None:
        while self._expiry_heap and self._expiry_heap[0][0] <= now:
            expiry, worker, sh = heapq.heappop(self._expiry_heap)
            current = self._expiry.get((worker, sh))
            if current is None or current > expiry:
                continue  # stale heap entry: re-inserted later with fresh TTL
            del self._expiry[(worker, sh)]
            self.tree.remove(worker, [sh])
