"""Pluggable logits processors, redesigned for fused on-device sampling.

Analog of the reference's ``dynamo.logits_processing`` (lib/bindings/python/
src/dynamo/logits_processing/base.py): there, a processor is a host callback
mutating one sequence's logits per step — viable when the engine round-trips
logits to Python, impossible inside a fused XLA decode scan. The TPU-native
contract instead:

- a processor is a **jittable pure function** ``fn(logits, state) -> logits``
  over the whole batch (``logits: [B, V] f32``); ``state`` exposes on-device
  context (``output_counts [B, V]``, ``steps [B]``, ``seq_lens [B]``);
- processors are registered at ENGINE BUILD (static set — XLA traces them
  once into the prefill/decode programs);
- requests opt in per processor by name (annotation
  ``logits_processors: [names...]``); the engine turns that into a [B] mask
  per processor and applies ``where(mask, fn(logits), logits)``, with the
  whole thing behind one ``lax.cond`` so batches that use no processors pay
  nothing.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Protocol, runtime_checkable

import jax
import jax.numpy as jnp


@runtime_checkable
class BaseLogitsProcessor(Protocol):
    """``fn(logits [B, V] f32, state dict) -> logits`` — pure and jittable.

    ``state`` keys: ``output_counts`` [B, V] int32, ``steps`` [B] int32
    (tokens produced so far), ``seq_lens`` [B] int32."""

    def __call__(self, logits: jax.Array, state: Dict[str, jax.Array]) -> jax.Array:
        ...


def apply_processors(
    processors,                 # ((name, fn), ...) static
    masks: jax.Array,           # [B, n_procs] bool — per-slot opt-in
    logits: jax.Array,          # [B, V] f32
    state: Dict[str, jax.Array],
) -> jax.Array:
    """Apply each enabled processor to its subscribing slots only."""
    for k, (_name, fn) in enumerate(processors):
        m = masks[:, k]

        def on(l, m=m, fn=fn):
            return jnp.where(m[:, None], fn(l, state), l)

        logits = jax.lax.cond(jnp.any(m), on, lambda l: l, logits)
    return logits


# ---------------------------------------------------------------------------
# example processors (reference examples/{temperature,hello_world}.py)
# ---------------------------------------------------------------------------


def temperature_processor(temperature: float) -> Callable:
    """Extra temperature scaling ahead of the sampler (examples/temperature.py)."""
    if temperature <= 0:
        raise ValueError("temperature must be positive")

    def fn(logits: jax.Array, state: Dict[str, jax.Array]) -> jax.Array:
        return logits / temperature

    return fn


def ban_tokens_processor(token_ids) -> Callable:
    """Hard-mask a fixed token set (the classic bad-words filter)."""
    ids = jnp.asarray(list(token_ids), jnp.int32)

    def fn(logits: jax.Array, state: Dict[str, jax.Array]) -> jax.Array:
        return logits.at[:, ids].set(-1e30)

    return fn


def repetition_window_processor(penalty: float) -> Callable:
    """Down-weight every token already generated (uses on-device counts —
    context the reference's host callback gets via input_ids)."""

    def fn(logits: jax.Array, state: Dict[str, jax.Array]) -> jax.Array:
        seen = state["output_counts"] > 0
        return jnp.where(seen, logits - penalty, logits)

    return fn
