"""Trace and synthetic load generation: arrival processes, SLA replay, and a
planner-in-the-loop simulator.

Reference analogs: ``benchmarks/sin_load_generator`` (sinusoidal request
rate), ``benchmarks/burstgpt_loadgen`` (trace replay with bursty arrivals),
``prefix_data_generator`` (controlled shared-prefix share), and the router
prefix-ratio benchmark's workload synthesis. Where the reference validates
its planner with manual aiperf sweeps, ``planner_sim`` closes the loop in
one process: generated load drives a mocker fleet whose snapshots feed a
real PoolPlanner, whose decisions resize the fleet — so planner heuristics
(correction factors, the queue bump) are validated against load shapes
instead of being constants taken on faith.

All latencies are SIMULATED-clock quantities (mocker sim_ts). Arrival pacing
and poll loops run on an injectable ``Clock`` (sim/clock.py): the default
WALL clock paces in wall time scaled by speedup_ratio (live use), while the
fleet simulator injects a VirtualClock so the same replay runs jitter-free
on virtual time (host asyncio jitter is amplified by speedup_ratio and was
measurably flaking the overload assertions on slow CI hosts).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import math
import random
from typing import Callable, List, Optional, Sequence

from ..llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from ..mocker.engine import MockEngineArgs, MockerEngine
from ..planner.core import LoadSnapshot, PoolPlanner
from ..runtime.engine import Context
from ..runtime.logging import get_logger
from ..runtime.clock import WALL, Clock
from ..runtime.slo import attainment

log = get_logger("profiler.loadgen")


@dataclasses.dataclass
class TraceItem:
    """One request of a workload trace."""

    t: float                 # arrival time (seconds from trace start)
    isl: int                 # input sequence length (tokens)
    osl: int                 # output sequence length (tokens)
    group: int = 0           # prefix group (members share a prompt prefix)


# --------------------------------------------------------------------------
# arrival processes
# --------------------------------------------------------------------------


def poisson_trace(
    n: int, rate: float, isl: int = 256, osl: int = 64,
    num_groups: int = 8, seed: int = 0,
) -> List[TraceItem]:
    """Memoryless arrivals at ``rate`` req/s."""
    rng = random.Random(seed)
    t = 0.0
    out = []
    for _ in range(n):
        t += rng.expovariate(rate)
        out.append(TraceItem(t, isl, osl, rng.randrange(num_groups)))
    return out


def sinusoidal_trace(
    duration_s: float, mean_rate: float, amplitude: float, period_s: float,
    isl: int = 256, osl: int = 64, num_groups: int = 8, seed: int = 0,
) -> List[TraceItem]:
    """Diurnal-style rate: ``mean_rate * (1 + amplitude*sin(2πt/period))``,
    realized as a thinned Poisson process (reference sin_load_generator)."""
    rng = random.Random(seed)
    peak = mean_rate * (1 + abs(amplitude))
    t = 0.0
    out = []
    while t < duration_s:
        t += rng.expovariate(peak)
        rate = mean_rate * (1 + amplitude * math.sin(2 * math.pi * t / period_s))
        if rng.random() < max(rate, 0.0) / peak:  # thinning
            out.append(TraceItem(t, isl, osl, rng.randrange(num_groups)))
    return out


def bursty_trace(
    duration_s: float, base_rate: float, burst_rate: float,
    burst_len_s: float, cycle_s: float,
    isl: int = 256, osl: int = 64, num_groups: int = 8, seed: int = 0,
) -> List[TraceItem]:
    """On/off bursts (burstgpt-style): ``burst_rate`` for ``burst_len_s`` at
    the top of every ``cycle_s``, ``base_rate`` otherwise."""
    rng = random.Random(seed)
    t = 0.0
    out = []
    while t < duration_s:
        in_burst = (t % cycle_s) < burst_len_s
        rate = burst_rate if in_burst else base_rate
        t += rng.expovariate(max(rate, 1e-9))
        out.append(TraceItem(t, isl, osl, rng.randrange(num_groups)))
    return out


def save_trace(path: str, trace: Sequence[TraceItem]) -> None:
    with open(path, "w") as f:
        for it in trace:
            f.write(json.dumps(dataclasses.asdict(it)) + "\n")


def load_trace(path: str) -> List[TraceItem]:
    out = []
    with open(path) as f:
        for line in f:
            if line.strip():
                obj = json.loads(line)
                out.append(TraceItem(
                    t=float(obj["t"]), isl=int(obj["isl"]),
                    osl=int(obj["osl"]), group=int(obj.get("group", 0)),
                ))
    return out


def prefix_prompt(item: TraceItem, idx: int, share: float, vocab: int = 512) -> List[int]:
    """Prompt with the first ``share`` fraction shared by the whole group
    (prefix_data_generator concept: controllable cache-hit opportunity)."""
    shared_len = int(item.isl * share)
    g = item.group
    shared = [(g * 131 + j * 3) % vocab for j in range(shared_len)]
    unique = [(g * 131 + idx * 101 + j * 7 + 1) % vocab
              for j in range(item.isl - shared_len)]
    return shared + unique


# --------------------------------------------------------------------------
# SLA replay
# --------------------------------------------------------------------------


@dataclasses.dataclass
class SlaReport:
    completed: int
    ttft_attainment: float     # fraction of requests with TTFT <= target
    itl_attainment: float      # fraction of ITL gaps <= target
    ttft_p95_s: float
    itl_p95_s: float
    cache_hit_ratio: float
    sim_busy_s: float


def sla_report_obj(rep: "SlaReport", workers: int) -> dict:
    """The `python -m dynamo_tpu.profiler replay` JSON line — shaped here
    next to the attainment math so the CLI has no inline SLA expressions
    (tests/test_slo.py pins the bytes)."""
    return {
        "requests": rep.completed,
        "workers": workers,
        "ttft_attainment": round(rep.ttft_attainment, 4),
        "itl_attainment": round(rep.itl_attainment, 4),
        "ttft_p95_s": round(rep.ttft_p95_s, 4),
        "itl_p95_s": round(rep.itl_p95_s, 4),
        "cache_hit_ratio": round(rep.cache_hit_ratio, 4),
    }


def pct(xs: List[float], p: float) -> float:
    """Nearest-rank percentile (ceil(p*n)-1), shared with fleet_bench."""
    xs = sorted(xs)
    if not xs:
        return 0.0
    return xs[min(len(xs) - 1, max(0, math.ceil(p * len(xs)) - 1))]


async def replay(
    trace: Sequence[TraceItem],
    engines: List[MockerEngine],
    ttft_target_s: float,
    itl_target_s: float,
    prefix_share: float = 0.5,
    speedup: float = 1.0,
    route_fn: Optional[Callable[[int, List[int]], int]] = None,
    on_arrival: Optional[Callable[[TraceItem], None]] = None,
    clock: Optional[Clock] = None,
) -> SlaReport:
    """Replay ``trace`` against a mocker fleet at arrival-time pacing
    (``clock`` seconds — wall by default — divided by ``speedup``),
    reporting SLA attainment measured on the engines' simulated clocks.
    ``route_fn(idx, tokens)`` picks the worker (default round-robin over
    the CURRENT fleet, so a resize mid-replay shifts traffic — what
    planner_sim exercises)."""
    clock = clock or WALL
    ttfts: List[float] = []
    itls: List[float] = []
    cached = [0]
    inputs = [0]
    tasks = []

    async def one(idx: int, item: TraceItem) -> None:
        tokens = prefix_prompt(item, idx, prefix_share)
        widx = (route_fn(idx, tokens) if route_fn is not None
                else idx % max(len(engines), 1))
        eng = engines[widx % len(engines)]
        req = PreprocessedRequest(
            request_id=f"lg-{idx}", model="loadgen", token_ids=tokens,
            stop=StopConditions(max_tokens=item.osl, min_tokens=item.osl,
                                ignore_eos=True),
            sampling=SamplingOptions(temperature=0.0),
        )
        t0 = eng.sim_time
        t_prev: Optional[float] = None
        async for out in eng.generate(req, Context()):
            if not out.token_ids:
                continue
            ts = out.annotations.get("sim_ts", eng.sim_time)
            if t_prev is None:
                ttfts.append(ts - t0)
                cached[0] += out.annotations.get("cached_tokens", 0)
                inputs[0] += out.annotations.get("input_tokens", 0)
            else:
                itls.append(ts - t_prev)
            t_prev = ts

    t_prev_arrival = 0.0
    for idx, item in enumerate(trace):
        dt = (item.t - t_prev_arrival) / speedup
        t_prev_arrival = item.t
        if dt > 0:
            await clock.sleep(dt)
        if on_arrival is not None:
            on_arrival(item)
        tasks.append(asyncio.create_task(one(idx, item)))
    await asyncio.gather(*tasks)
    return SlaReport(
        completed=len(trace),
        # attainment math lives in runtime/slo.py (one source of truth with
        # the serving-path accountant); the JSON this feeds is pinned
        # byte-identical by tests/test_slo.py
        ttft_attainment=attainment(ttfts, ttft_target_s),
        itl_attainment=attainment(itls, itl_target_s),
        ttft_p95_s=pct(ttfts, 0.95),
        itl_p95_s=pct(itls, 0.95),
        cache_hit_ratio=cached[0] / max(inputs[0], 1),
        sim_busy_s=sum(e.sim_time for e in engines),
    )


# --------------------------------------------------------------------------
# planner-in-the-loop simulation
# --------------------------------------------------------------------------


class FleetConnector:
    """Planner connector that resizes an in-process mocker fleet."""

    def __init__(
        self,
        engines: List[MockerEngine],
        make_engine: Callable[[], MockerEngine],
        clock: Optional[Clock] = None,
    ):
        self.engines = engines
        self.make_engine = make_engine
        self.clock = clock or WALL
        self.drain_tasks: List[asyncio.Task] = []

    async def get_replicas(self, component: str) -> int:
        return len(self.engines)

    async def set_replicas(self, component: str, n: int) -> None:
        while len(self.engines) < n:
            self.engines.append(self.make_engine())
        while len(self.engines) > n > 0:
            # drain, don't kill: popping stops new routing immediately; the
            # engine is stopped once its in-flight requests finish
            self.drain_tasks.append(
                asyncio.create_task(self._drain_stop(self.engines.pop()))
            )

    async def _drain_stop(self, engine: MockerEngine) -> None:
        while True:
            s = engine.snapshot()
            if not s["waiting"] and not s["running"]:
                break
            await self.clock.sleep(0.05)
        engine.stop()


@dataclasses.dataclass
class PlannerSimResult:
    report: SlaReport
    replica_timeline: List[int]        # fleet size per planner tick
    correction_timeline: List[float]   # correction factor per tick


async def planner_sim(
    trace: Sequence[TraceItem],
    planner_factory: Callable[[FleetConnector], PoolPlanner],
    engine_args: Optional[MockEngineArgs] = None,
    initial_replicas: int = 1,
    tick_s: float = 0.25,
    speedup: float = 20.0,
    ttft_target_s: float = 0.5,
    itl_target_s: float = 0.05,
    prefix_share: float = 0.3,
    clock: Optional[Clock] = None,
) -> PlannerSimResult:
    """Closed loop: replay ``trace`` while a real PoolPlanner observes fleet
    snapshots every ``tick_s`` clock-seconds and resizes the fleet through a
    FleetConnector. Returns the SLA report plus the replica/correction
    timelines for convergence assertions."""
    clock = clock or WALL
    args = engine_args or MockEngineArgs(
        emit_sim_ts=True, speedup_ratio=speedup, num_blocks=512,
    )

    def make_engine() -> MockerEngine:
        return MockerEngine(dataclasses.replace(args), clock=clock)

    engines = [make_engine() for _ in range(initial_replicas)]
    conn = FleetConnector(engines, make_engine, clock=clock)
    planner = planner_factory(conn)

    arrivals: List[float] = []   # clock arrival stamps (for rate calc)
    isls: List[int] = []
    replica_timeline: List[int] = []
    correction_timeline: List[float] = []

    def on_arrival(item: TraceItem) -> None:
        arrivals.append(clock.time())
        isls.append(item.isl)

    rr = [0]

    def route(idx: int, tokens: List[int]) -> int:
        rr[0] = (rr[0] + 1) % max(len(engines), 1)
        return rr[0]

    stop = asyncio.Event()

    async def planner_loop() -> None:
        window_start = clock.time()
        seen = 0
        while not stop.is_set():
            try:
                await asyncio.wait_for(stop.wait(), tick_s)
            except asyncio.TimeoutError:
                pass
            now = clock.time()
            new = arrivals[seen:]
            seen = len(arrivals)
            window = max(now - window_start, 1e-6)
            window_start = now
            # rates are in SIMULATED seconds (wall * speedup)
            rate = len(new) / (window * speedup)
            snaps = [e.snapshot() for e in engines]
            snapshot = LoadSnapshot(
                request_rate=rate,
                avg_isl=(sum(isls) / len(isls)) if isls else 0.0,
                num_waiting=sum(s["waiting"] for s in snaps),
                active_seqs=sum(s["running"] for s in snaps),
            )
            planner.observe(rate)
            try:
                await planner.plan_and_apply(snapshot)
            except Exception:
                log.exception("planner tick failed")
            replica_timeline.append(len(engines))
            correction_timeline.append(getattr(planner, "correction", 1.0))

    ptask = asyncio.create_task(planner_loop())
    try:
        report = await replay(
            trace, engines, ttft_target_s, itl_target_s,
            prefix_share=prefix_share, speedup=speedup,
            route_fn=route, on_arrival=on_arrival, clock=clock,
        )
    finally:
        stop.set()
        await ptask
        if conn.drain_tasks:
            await asyncio.gather(*conn.drain_tasks, return_exceptions=True)
        for e in engines:
            e.stop()
    return PlannerSimResult(report, replica_timeline, correction_timeline)
