"""Profiling sweeps over a live engine.

Analog of the reference's SLA profiler (benchmarks/profiler/profile_sla.py:
138 — sweep deployments across parallelism/batch configs, persist the
measured rates, interpolate in the planner) collapsed to the single-worker
measurements the planner's PerfInterpolator consumes:

- prefill: tokens/sec one worker sustains at each input length (measured
  from time-to-first-token of cold prompts);
- decode: aggregate tokens/sec at each concurrent-sequence count (measured
  from steady-state token production after the first token).

Works against any AsyncEngine — the real TpuEngine on hardware, or the
MockerEngine for control-plane tests — and doubles as the calibration
source for the mocker's linear timing model (perf_model.rs analog).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Dict, List, Sequence, Tuple

from ..llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from ..runtime.engine import Context
from ..runtime.logging import get_logger

log = get_logger("profiler")


@dataclasses.dataclass
class ProfileResult:
    """Measured single-worker capacities (the planner's interpolation feed)."""

    prefill_points: List[Tuple[float, float]] = dataclasses.field(default_factory=list)
    decode_points: List[Tuple[float, float]] = dataclasses.field(default_factory=list)
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_obj(self) -> Dict[str, Any]:
        return {
            "prefill_points": [list(p) for p in self.prefill_points],
            "decode_points": [list(p) for p in self.decode_points],
            "meta": self.meta,
        }

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "ProfileResult":
        return cls(
            prefill_points=[tuple(p) for p in obj.get("prefill_points", [])],
            decode_points=[tuple(p) for p in obj.get("decode_points", [])],
            meta=obj.get("meta", {}),
        )

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_obj(), f, indent=2)

    @classmethod
    def load(cls, path: str) -> "ProfileResult":
        with open(path) as f:
            return cls.from_obj(json.load(f))


def _preq(rid: str, tokens: Sequence[int], max_tokens: int) -> PreprocessedRequest:
    return PreprocessedRequest(
        request_id=rid, model="profile", token_ids=list(tokens),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling=SamplingOptions(temperature=0.0),
    )


async def profile_engine(
    engine,
    isl_list: Sequence[int] = (128, 512, 1024),
    osl: int = 64,
    batch_list: Sequence[int] = (1, 2, 4, 8),
    reps: int = 2,
    seed: int = 0,
    vocab: int = 250,
) -> ProfileResult:
    """Sweep one engine. Prompts are derived from (seed, sweep point, rep) so
    every measurement is a cold prefix — prefix-cache hits would inflate the
    numbers."""
    import asyncio

    result = ProfileResult(meta={"osl": osl, "reps": reps, "ts": time.time()})
    uniq = [seed * 7919]

    def prompt(n: int) -> List[int]:
        uniq[0] += 1
        base = uniq[0]
        return [(base * 131 + j * 7) % vocab for j in range(n)]

    # --- warmup: hit every prefill bucket + the decode program once, so
    # XLA compile time (30-90s cold on TPU) never lands in a measurement ---
    for isl in sorted(set(isl_list)):
        req = _preq(f"warm-{isl}", prompt(isl), max_tokens=4)
        async for _ in engine.generate(req, Context()):
            pass

    # --- prefill: TTFT of a single cold request per ISL ---
    for isl in isl_list:
        ttfts = []
        for r in range(reps):
            req = _preq(f"pf-{isl}-{r}", prompt(isl), max_tokens=1)
            t0 = time.monotonic()
            async for out in engine.generate(req, Context()):
                if out.token_ids:
                    ttfts.append(time.monotonic() - t0)
                    break
        best = min(ttfts)
        result.prefill_points.append((float(isl), isl / best))
        log.info("prefill isl=%d: ttft=%.4fs -> %.0f tok/s", isl, best, isl / best)

    # --- decode: steady tokens/s at each concurrency ---
    isl0 = min(isl_list)
    result.meta["decode_isl"] = isl0
    for batch in batch_list:
        async def one(i: int, t_first: list, t_last: list, counts: list):
            req = _preq(f"dc-{batch}-{i}", prompt(isl0), max_tokens=osl)
            n = 0
            async for out in engine.generate(req, Context()):
                now = time.monotonic()
                if n == 0 and out.token_ids:
                    t_first.append(now)
                n += len(out.token_ids)
                t_last.append(now)
            counts.append(n)

        t_first: list = []
        t_last: list = []
        counts: list = []
        await asyncio.gather(*[one(i, t_first, t_last, counts) for i in range(batch)])
        total = sum(counts) - len(counts)  # exclude each stream's first token
        window = max(t_last) - min(t_first)
        rate = total / window if window > 0 else 0.0
        result.decode_points.append((float(batch), rate))
        log.info("decode batch=%d: %.0f tok/s", batch, rate)
    return result


def profile_to_npz(profile: ProfileResult, path: str, block_size: int = 16):
    """Export a measured profile as the mocker's interpolated timing grid
    (mocker/perf_model.py NPZ schema; reference perf_model.rs loads the
    profiler's NPZ the same way).

    prefill: (isl, rate) points -> chunk latency curve. decode: the sweep
    measures aggregate rate per concurrency; each concurrency's step time
    becomes one grid row, with the kv-blocks axis anchored at the sweep's
    mean context (a single column — bilinear degrades to 1-D cleanly)."""
    import numpy as np

    from ..mocker.perf_model import InterpolatedPerfModel

    isl = np.array([p[0] for p in profile.prefill_points], np.float64)
    pre_s = isl / np.maximum(
        np.array([p[1] for p in profile.prefill_points], np.float64), 1e-9
    )
    seqs = np.array([p[0] for p in profile.decode_points], np.float64)
    step_s = seqs / np.maximum(
        np.array([p[1] for p in profile.decode_points], np.float64), 1e-9
    )
    ctx = profile.meta.get("decode_isl", 0) + profile.meta.get("osl", 64) / 2
    blocks = np.array([max(1.0, ctx / block_size)], np.float64)
    model = InterpolatedPerfModel(
        prefill_isl=isl, prefill_s=pre_s,
        decode_seqs=seqs, decode_blocks=blocks,
        decode_s=step_s[:, None],
    )
    model.save(path)
    return model


def calibrate_mocker_args(profile: ProfileResult, args=None):
    """Fit the mocker's linear timing model to a measured profile
    (perf_model.rs analog: the simulator reproduces real timing).

    prefill: time(isl) = base + per_token * isl, least-squares over the
    measured (isl, rate) points. decode: step time at concurrency b is
    base_total(b) = b / rate(b) ~= decode_base + slope * b (the per-sequence
    attention cost folds into the slope)."""
    import numpy as np

    from ..mocker.engine import MockEngineArgs

    args = args or MockEngineArgs()
    if profile.prefill_points:
        isl = np.array([p[0] for p in profile.prefill_points])
        t = isl / np.array([max(p[1], 1e-9) for p in profile.prefill_points])
        A = np.stack([np.ones_like(isl), isl], axis=1)
        coef, *_ = np.linalg.lstsq(A, t, rcond=None)
        base, per_token = float(max(coef[0], 0.0)), float(max(coef[1], 0.0))
        args = dataclasses.replace(
            args, prefill_base_s=base, prefill_per_token_s=per_token
        )
    if profile.decode_points:
        b = np.array([p[0] for p in profile.decode_points])
        step = b / np.array([max(p[1], 1e-9) for p in profile.decode_points])
        A = np.stack([np.ones_like(b), b], axis=1)
        coef, *_ = np.linalg.lstsq(A, step, rcond=None)
        base = float(max(coef[0], 1e-6))
        # the per-batch slope approximates KV traffic per active sequence
        per_seq = float(max(coef[1], 0.0))
        blocks_per_seq = max(
            1.0,
            (profile.meta.get("decode_isl", 0) + profile.meta.get("osl", 64) / 2)
            / args.block_size,
        )
        args = dataclasses.replace(
            args,
            decode_base_s=base,
            decode_per_kv_block_s=per_seq / blocks_per_seq,
        )
    return args
