"""python -m dynamo_tpu.profiler — measure a worker's capacity envelope,
or replay a load trace for SLA attainment.

Analog of the reference's `profile_sla.py` entrypoint: sweeps (isl, batch)
on a real engine (or the mocker), writes a profile JSON the planner loads
via `--profile` / PerfInterpolator.from_profile and the mocker loads for
timing calibration.

Trace replay (reference burstgpt/sin loadgens + aiperf wrapper):

    python -m dynamo_tpu.profiler replay --shape sin --duration 60 --rate 20
    python -m dynamo_tpu.profiler replay --trace trace.jsonl --workers 4

prints one JSON line of SLA attainment (profiler/loadgen.py) measured on a
mocker fleet's simulated clocks.
"""

import argparse
import asyncio
import json
import sys

from dynamo_tpu.profiler.sweep import calibrate_mocker_args, profile_engine


async def _replay_main(argv) -> None:
    p = argparse.ArgumentParser("dynamo_tpu.profiler replay")
    p.add_argument("--trace", default=None, help="JSONL trace to replay "
                   "(default: synthesize from --shape)")
    p.add_argument("--shape", default="sin", choices=["sin", "burst", "poisson"])
    p.add_argument("--duration", type=float, default=60.0)
    p.add_argument("--rate", type=float, default=20.0)
    p.add_argument("--amplitude", type=float, default=0.8)
    p.add_argument("--period", type=float, default=30.0)
    p.add_argument("--burst-rate", type=float, default=80.0)
    p.add_argument("--burst-len", type=float, default=3.0)
    p.add_argument("--isl", type=int, default=256)
    p.add_argument("--osl", type=int, default=64)
    p.add_argument("--prefix-share", type=float, default=0.5)
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--speedup", type=float, default=20.0)
    p.add_argument("--ttft", type=float, default=0.5, help="TTFT SLA (s)")
    p.add_argument("--itl", type=float, default=0.05, help="ITL SLA (s)")
    args = p.parse_args(argv)

    from dynamo_tpu.mocker.engine import MockEngineArgs, MockerEngine
    from dynamo_tpu.profiler import loadgen

    if args.trace:
        trace = loadgen.load_trace(args.trace)
    elif args.shape == "sin":
        trace = loadgen.sinusoidal_trace(
            args.duration, args.rate, args.amplitude, args.period,
            isl=args.isl, osl=args.osl,
        )
    elif args.shape == "burst":
        trace = loadgen.bursty_trace(
            args.duration, args.rate, args.burst_rate, args.burst_len,
            args.period, isl=args.isl, osl=args.osl,
        )
    else:
        trace = loadgen.poisson_trace(
            int(args.duration * args.rate), args.rate,
            isl=args.isl, osl=args.osl,
        )
    engines = [
        MockerEngine(MockEngineArgs(
            emit_sim_ts=True, speedup_ratio=args.speedup,
        ))
        for _ in range(args.workers)
    ]
    try:
        rep = await loadgen.replay(
            trace, engines, args.ttft, args.itl,
            prefix_share=args.prefix_share, speedup=args.speedup,
        )
    finally:
        for e in engines:
            e.stop()
    # one source of truth for SLA math + report shape (profiler/loadgen.py
    # -> runtime/slo.py); byte-identical output pinned by tests/test_slo.py
    print(json.dumps(loadgen.sla_report_obj(rep, args.workers)))


def parse_args():
    p = argparse.ArgumentParser(
        "dynamo_tpu.profiler",
        epilog="subcommand: 'python -m dynamo_tpu.profiler replay ...' "
        "replays a load trace (sin/burst/poisson or a JSONL file) against "
        "a mocker fleet and prints SLA attainment; see 'replay --help'.",
    )
    p.add_argument("--engine", default="tpu", choices=["tpu", "mocker"])
    p.add_argument("--pp-bubble", action="store_true",
                   help="instead of a capacity sweep, measure the PP decode "
                        "schedules (M=1 cond-skip vs microbatched; "
                        "fleet_bench.pp_bubble_bench) and exit")
    p.add_argument("--pp", type=int, default=2,
                   help="pipeline width for --pp-bubble")
    p.add_argument("--preset", default="tiny")
    p.add_argument("--model-path", default=None)
    p.add_argument("--platform", default=None, choices=["cpu", "tpu", "axon"])
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--isl", default="128,512,1024")
    p.add_argument("--osl", type=int, default=64)
    p.add_argument("--batch", default="1,2,4,8")
    p.add_argument("--reps", type=int, default=2)
    p.add_argument("--num-blocks", type=int, default=4096)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-context", type=int, default=2048)
    p.add_argument("--out", default="profile.json")
    p.add_argument("--print-mocker-args", action="store_true",
                   help="also print calibrated mocker timing constants")
    return p.parse_args()


async def main() -> None:
    args = parse_args()
    if args.pp_bubble:
        import json
        import os

        if args.platform == "cpu":
            # the accelerator-free path needs pp virtual devices BEFORE the
            # backend initializes (same trick as tests/conftest.py)
            flags = os.environ.get("XLA_FLAGS", "")
            if "host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count={args.pp}"
                ).strip()
        if args.platform:
            import jax

            jax.config.update("jax_platforms", args.platform)
        from dynamo_tpu.profiler.fleet_bench import pp_bubble_bench

        print(json.dumps(pp_bubble_bench(pp=args.pp), indent=2))
        return
    isl_list = [int(x) for x in args.isl.split(",")]
    batch_list = [int(x) for x in args.batch.split(",")]

    if args.engine == "mocker":
        from dynamo_tpu.mocker.engine import MockEngineArgs, MockerEngine

        engine = MockerEngine(
            MockEngineArgs(num_blocks=args.num_blocks, block_size=args.block_size)
        )
        stopper = getattr(engine, "stop", lambda: None)
    else:
        if args.platform:
            import jax

            jax.config.update("jax_platforms", args.platform)
        from dynamo_tpu.engine.__main__ import PRESETS
        from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
        from dynamo_tpu.engine.weights import config_from_hf, load_params

        params = None
        if args.model_path:
            mcfg = config_from_hf(args.model_path)
            params = load_params(args.model_path, mcfg)
        else:
            mcfg = PRESETS[args.preset]()
        bs = args.block_size
        ctx = ((args.max_context + bs - 1) // bs) * bs
        buckets = tuple(
            b for b in (64, 128, 256, 512, 1024, 2048, 4096, 8192) if b < ctx
        ) + (ctx,)
        engine = TpuEngine(
            TpuEngineConfig(
                model=mcfg, num_blocks=args.num_blocks, block_size=bs,
                max_batch_size=max(batch_list), max_context=ctx,
                prefill_buckets=buckets, tp=args.tp,
            ),
            params=params,
        )
        stopper = engine.stop

    try:
        result = await profile_engine(
            engine, isl_list=isl_list, osl=args.osl,
            batch_list=batch_list, reps=args.reps,
        )
    finally:
        stopper()
    result.meta["engine"] = args.engine
    result.meta["preset"] = args.preset
    result.save(args.out)
    print(json.dumps(result.to_obj()))
    if args.print_mocker_args:
        cal = calibrate_mocker_args(result)
        print(
            f"mocker timing: prefill {cal.prefill_base_s:.4f}s + "
            f"{cal.prefill_per_token_s * 1e6:.2f}us/tok; decode "
            f"{cal.decode_base_s * 1e3:.2f}ms + "
            f"{cal.decode_per_kv_block_s * 1e6:.3f}us/kv-block",
        )


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "replay":
        asyncio.run(_replay_main(sys.argv[2:]))
    else:
        asyncio.run(main())
