"""python -m dynamo_tpu.profiler — measure a worker's capacity envelope.

Analog of the reference's `profile_sla.py` entrypoint: sweeps (isl, batch)
on a real engine (or the mocker), writes a profile JSON the planner loads
via `--profile` / PerfInterpolator.from_profile and the mocker loads for
timing calibration.
"""

import argparse
import asyncio
import json

from dynamo_tpu.profiler.sweep import calibrate_mocker_args, profile_engine


def parse_args():
    p = argparse.ArgumentParser("dynamo_tpu.profiler")
    p.add_argument("--engine", default="tpu", choices=["tpu", "mocker"])
    p.add_argument("--preset", default="tiny")
    p.add_argument("--model-path", default=None)
    p.add_argument("--platform", default=None, choices=["cpu", "tpu", "axon"])
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--isl", default="128,512,1024")
    p.add_argument("--osl", type=int, default=64)
    p.add_argument("--batch", default="1,2,4,8")
    p.add_argument("--reps", type=int, default=2)
    p.add_argument("--num-blocks", type=int, default=4096)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-context", type=int, default=2048)
    p.add_argument("--out", default="profile.json")
    p.add_argument("--print-mocker-args", action="store_true",
                   help="also print calibrated mocker timing constants")
    return p.parse_args()


async def main() -> None:
    args = parse_args()
    isl_list = [int(x) for x in args.isl.split(",")]
    batch_list = [int(x) for x in args.batch.split(",")]

    if args.engine == "mocker":
        from dynamo_tpu.mocker.engine import MockEngineArgs, MockerEngine

        engine = MockerEngine(
            MockEngineArgs(num_blocks=args.num_blocks, block_size=args.block_size)
        )
        stopper = getattr(engine, "stop", lambda: None)
    else:
        if args.platform:
            import jax

            jax.config.update("jax_platforms", args.platform)
        from dynamo_tpu.engine.__main__ import PRESETS
        from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
        from dynamo_tpu.engine.weights import config_from_hf, load_params

        params = None
        if args.model_path:
            mcfg = config_from_hf(args.model_path)
            params = load_params(args.model_path, mcfg)
        else:
            mcfg = PRESETS[args.preset]()
        bs = args.block_size
        ctx = ((args.max_context + bs - 1) // bs) * bs
        buckets = tuple(
            b for b in (64, 128, 256, 512, 1024, 2048, 4096, 8192) if b < ctx
        ) + (ctx,)
        engine = TpuEngine(
            TpuEngineConfig(
                model=mcfg, num_blocks=args.num_blocks, block_size=bs,
                max_batch_size=max(batch_list), max_context=ctx,
                prefill_buckets=buckets, tp=args.tp,
            ),
            params=params,
        )
        stopper = engine.stop

    try:
        result = await profile_engine(
            engine, isl_list=isl_list, osl=args.osl,
            batch_list=batch_list, reps=args.reps,
        )
    finally:
        stopper()
    result.meta["engine"] = args.engine
    result.meta["preset"] = args.preset
    result.save(args.out)
    print(json.dumps(result.to_obj()))
    if args.print_mocker_args:
        cal = calibrate_mocker_args(result)
        print(
            f"mocker timing: prefill {cal.prefill_base_s:.4f}s + "
            f"{cal.prefill_per_token_s * 1e6:.2f}us/tok; decode "
            f"{cal.decode_base_s * 1e3:.2f}ms + "
            f"{cal.decode_per_kv_block_s * 1e6:.3f}us/kv-block",
        )


if __name__ == "__main__":
    asyncio.run(main())
