"""Fleet-level benchmarks over the mocker: routing and disaggregation wins.

Analog of the reference's router benchmark harness
(benchmarks/router/prefix_ratio_benchmark.py — synthetic workloads with a
controlled shared-prefix ratio, KV-aware routing vs round-robin) and its
disagg-vs-agg comparisons (docs/design_docs/architecture.md:87-91): both run
on the accelerator-free mocker so the *control plane* cost model (prefix
reuse, prefill/decode interference) is what is measured.

All latencies are measured on the mocker's **simulated clock**
(MockEngineArgs.emit_sim_ts): wall-clock asyncio jitter is amplified by
speedup_ratio and would otherwise drown the signal; simulated TTFT/ITL are
deterministic engine-model quantities.

Used by bench.py to report fleet metrics alongside the single-chip number.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Tuple

from ..kv_router import (
    KvEventPublisher,
    KvRouter,
    KvRouterConfig,
    WorkerWithDpRank,
)
from ..llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from ..mocker.engine import MockEngineArgs, MockerEngine
from ..runtime.engine import Context
from ..runtime.event_plane.base import InProcEventPlane
from ..runtime.clock import WALL, Clock


def _prompt(group: int, i: int, prompt_len: int, shared_len: int) -> List[int]:
    """Group members share the first ``shared_len`` tokens exactly (thin
    adapter over loadgen.prefix_prompt, the one shared-prefix generator)."""
    from .loadgen import TraceItem, prefix_prompt

    item = TraceItem(t=0.0, isl=prompt_len, osl=0, group=group)
    return prefix_prompt(item, i, share=shared_len / max(prompt_len, 1))


def _req(rid: str, tokens: List[int], max_tokens: int) -> PreprocessedRequest:
    return PreprocessedRequest(
        request_id=rid, model="bench", token_ids=tokens,
        stop=StopConditions(max_tokens=max_tokens, min_tokens=max_tokens, ignore_eos=True),
        sampling=SamplingOptions(temperature=0.0),
    )


def _pct(xs: List[float], p: float) -> float:
    from .loadgen import pct

    return pct(xs, p)


def _stats(ttfts: List[float], itls: List[float], cached: int, inputs: int) -> Dict[str, float]:
    ttfts, itls = sorted(ttfts), sorted(itls)
    return {
        "ttft_mean_ms": round(sum(ttfts) / max(len(ttfts), 1) * 1e3, 2),
        "ttft_p95_ms": round(_pct(ttfts, 0.95) * 1e3, 2),
        "itl_mean_ms": round(sum(itls) / max(len(itls), 1) * 1e3, 2),
        "itl_p95_ms": round(_pct(itls, 0.95) * 1e3, 2),
        "cache_hit_ratio": round(cached / max(inputs, 1), 4),
    }


async def _drive(
    engines: List[MockerEngine],
    workload: List[Tuple[str, List[int]]],
    route_fn,
    osl: int,
    concurrency: int = 16,
    done_fn=None,
) -> Dict[str, float]:
    """Run requests, picking the worker with ``route_fn(rid, tokens)`` at
    dispatch time (so KV-aware routing sees earlier requests' cache events).
    TTFT/ITL come from the engines' simulated clocks."""
    sem = asyncio.Semaphore(concurrency)
    ttfts: List[float] = []
    itls: List[float] = []
    cached = [0]
    inputs = [0]

    async def one(rid: str, tokens: List[int]):
        async with sem:
            widx = route_fn(rid, tokens)
            eng = engines[widx]
            req = _req(rid, tokens, osl)
            t0 = eng.sim_time
            t_prev: Optional[float] = None
            async for out in eng.generate(req, Context()):
                if not out.token_ids:
                    continue
                ts = out.annotations.get("sim_ts", eng.sim_time)
                if t_prev is None:
                    ttfts.append(ts - t0)
                    cached[0] += out.annotations.get("cached_tokens", 0)
                    inputs[0] += out.annotations.get("input_tokens", 0)
                else:
                    itls.append(ts - t_prev)
                t_prev = ts
            if done_fn is not None:
                done_fn(rid)

    await asyncio.gather(*[one(rid, toks) for rid, toks in workload])
    stats = _stats(ttfts, itls, cached[0], inputs[0])
    stats["engine_busy_s"] = round(sum(e.sim_time for e in engines), 3)
    return stats


async def router_prefix_bench(
    num_workers: int = 8,
    num_groups: int = 8,
    requests_per_group: int = 8,
    prompt_len: int = 2048,
    prefix_ratio: float = 0.75,
    osl: int = 8,
    block_size: int = 16,
    speedup: float = 100.0,
) -> Dict[str, object]:
    """KV-aware routing vs round-robin on a shared-prefix workload.

    Groups of requests share ``prefix_ratio`` of their prompt; KV routing
    lands same-group requests on the worker already holding the prefix
    (prefill cost ~ uncached tokens in the mocker's timing model), while
    round-robin scatters them and recomputes."""
    import random as _random

    shared_len = (int(prompt_len * prefix_ratio) // block_size) * block_size
    # deterministic shuffle: arrival order is uncorrelated with group, so
    # neither policy gets accidental group affinity from submit order
    workload = [
        (f"g{g}-r{i}", _prompt(g, i, prompt_len, shared_len))
        for i in range(requests_per_group)
        for g in range(num_groups)
    ]
    _random.Random(42).shuffle(workload)

    async def run_mode(kv_aware: bool) -> Dict[str, float]:
        plane = InProcEventPlane()
        args = MockEngineArgs(
            block_size=block_size, num_blocks=16384, speedup_ratio=speedup,
            emit_sim_ts=True,
        )
        engines = []
        for w in range(num_workers):
            pub = KvEventPublisher(
                plane, "bench", "backend", worker_id=w + 1, block_size=block_size
            )
            engines.append(MockerEngine(args, kv_publisher=pub))
        router = await KvRouter(
            plane, "bench", "backend", block_size=block_size,
            config=KvRouterConfig(),
        ).start()
        cands = [WorkerWithDpRank(w + 1, 0) for w in range(num_workers)]
        rr_cursor = [0]

        def route(rid: str, tokens: List[int]) -> int:
            if kv_aware:
                d = router.schedule_tokens(tokens, cands, request_id=rid)
                return d.worker.worker_id - 1
            rr_cursor[0] += 1
            return (rr_cursor[0] - 1) % num_workers

        def done(rid: str) -> None:
            if kv_aware:
                router.complete(rid)

        try:
            stats = await _drive(
                engines, workload, route, osl, concurrency=8, done_fn=done
            )
        finally:
            for e in engines:
                e.stop()
            await router.stop()
            await plane.close()
        return stats

    kv = await run_mode(True)
    rr = await run_mode(False)
    return {
        "workload": {
            "workers": num_workers,
            "requests": len(workload),
            "prompt_len": prompt_len,
            "prefix_ratio": prefix_ratio,
            "osl": osl,
        },
        "kv_routing": kv,
        "round_robin": rr,
        "ttft_speedup": round(
            rr["ttft_mean_ms"] / max(kv["ttft_mean_ms"], 1e-9), 3
        ),
        "cache_hit_gain": round(
            kv["cache_hit_ratio"] - rr["cache_hit_ratio"], 4
        ),
    }


async def disagg_vs_agg_bench(
    num_decodes: int = 8,
    num_prefills: int = 24,
    prompt_len: int = 4096,
    osl: int = 256,
    block_size: int = 16,
    speedup: float = 100.0,
    clock: Optional[Clock] = None,
) -> Dict[str, object]:
    """Decode ITL under a prefill-heavy load: aggregated vs disaggregated.

    The scenario the reference's disagg design targets
    (docs/design_docs/disagg_serving.md): long decodes are in flight while a
    stream of long prompts arrives. Aggregated, every arriving prefill chunk
    inflates the shared engine step, spiking the decoders' ITL; with a
    dedicated prefill worker (decode side sees the KV as transferred —
    the mocker analog of the NIXL pull), decode steps stay pure."""
    from ..tokens import TokenBlockSequence

    clock = clock or WALL

    args = MockEngineArgs(
        block_size=block_size, num_blocks=32768, speedup_ratio=speedup,
        emit_sim_ts=True,
    )
    decode_reqs = [
        (f"dec{i}", _prompt(1000 + i, i, 256, 0)) for i in range(num_decodes)
    ]
    prefill_reqs = [
        (f"pre{i}", _prompt(2000 + i, i, prompt_len, 0)) for i in range(num_prefills)
    ]

    async def run(disagg: bool) -> Dict[str, float]:
        decode_eng = MockerEngine(args)
        prefill_eng = MockerEngine(args) if disagg else decode_eng
        itls: List[float] = []
        pre_ttfts: List[float] = []

        async def one_decode(rid: str, tokens: List[int]):
            t_prev: Optional[float] = None
            async for out in decode_eng.generate(_req(rid, tokens, osl), Context()):
                if not out.token_ids:
                    continue
                ts = out.annotations.get("sim_ts", 0.0)
                if t_prev is not None:
                    itls.append(ts - t_prev)
                t_prev = ts

        async def one_prefill(rid: str, tokens: List[int]):
            t0 = prefill_eng.sim_time
            # prefill request: one token (reference disagg max_tokens=1)
            async for out in prefill_eng.generate(_req(rid, tokens, 1), Context()):
                if out.token_ids:
                    pre_ttfts.append(out.annotations.get("sim_ts", 0.0) - t0)
            if disagg:
                # simulated KV transfer: decode side now holds the prefix
                hashes = TokenBlockSequence(tokens, block_size).sequence_hashes()
                decode_eng.kv.acquire(hashes)
                decode_eng.kv.release(hashes)

        async def prefill_stream():
            # paced arrivals so prefills overlap the whole decode phase;
            # gather (not poll) so a failed/token-less task can never hang
            # the bench
            tasks = []
            for rid, toks in prefill_reqs:
                await clock.sleep(0.002)
                tasks.append(asyncio.ensure_future(one_prefill(rid, toks)))
            await asyncio.gather(*tasks, return_exceptions=True)

        try:
            await asyncio.gather(
                *[one_decode(rid, toks) for rid, toks in decode_reqs],
                prefill_stream(),
            )
        finally:
            decode_eng.stop()
            prefill_eng.stop()
        itls.sort()
        pre_ttfts.sort()
        return {
            "decode_itl_mean_ms": round(sum(itls) / max(len(itls), 1) * 1e3, 3),
            "decode_itl_p95_ms": round(_pct(itls, 0.95) * 1e3, 3),
            "decode_itl_max_ms": round((itls[-1] if itls else 0.0) * 1e3, 3),
            "prefill_ttft_mean_ms": round(
                sum(pre_ttfts) / max(len(pre_ttfts), 1) * 1e3, 2
            ),
        }

    agg = await run(False)
    dis = await run(True)
    return {
        "workload": {
            "decodes": num_decodes,
            "prefills": num_prefills,
            "prompt_len": prompt_len,
            "osl": osl,
        },
        "aggregated": agg,
        "disaggregated": dis,
        "itl_p95_improvement": round(
            agg["decode_itl_p95_ms"] / max(dis["decode_itl_p95_ms"], 1e-9), 3
        ),
    }


def pp_bubble_bench(
    pp: int = 2, batch: int = 8, steps: int = 6, layers: int = 4,
) -> Dict[str, float]:
    """Measure both pipeline-parallel decode schedules: wall time per step
    at M = 1 (default; invalid ticks lax.cond-skipped, one real stage
    execution per rank — the weight-bandwidth-bound regime's best) vs
    M = pp (GPipe bubble amortization for compute-bound/large-batch
    regimes). The FLOP-model ratio pp*B : (2pp-1)*B/pp is reported so the
    measurement can be compared against the compute-bound prediction; in
    the weight-bound regime the observed ratio inverts (more ticks = more
    weight reads), which is exactly why M = 1 is the default."""
    import os

    import jax
    import numpy as np

    from ..models import llama
    from ..parallel import pp_serving
    from ..parallel.pipeline import make_pp_mesh

    if batch % pp:
        return {"error": f"batch {batch} must divide by pp {pp} for the "
                         "microbatched schedule to engage"}
    devs = jax.devices()
    if len(devs) < pp:
        return {"error": f"need {pp} devices, have {len(devs)}"}
    mesh = make_pp_mesh(pp=pp, tp=1, devices=devs[:pp])
    # shapes large enough that stage compute dominates dispatch overhead
    mcfg = llama.LlamaConfig(
        vocab_size=4096, hidden_size=1024, num_layers=layers, num_heads=8,
        num_kv_heads=4, head_dim=128, intermediate_size=4096,
    )
    params = pp_serving.place_serving_params(
        mesh, llama.init_params(jax.random.PRNGKey(0), mcfg)
    )
    nb, bs = 64, 4
    k, v = pp_serving.init_pp_caches(
        mesh, layers, nb, bs, mcfg.num_kv_heads, mcfg.head_dim, mcfg.dtype
    )
    import jax.numpy as jnp

    tokens = jnp.arange(batch, dtype=jnp.int32)
    positions = jnp.full((batch,), 3, jnp.int32)
    tables = jnp.tile(jnp.arange(1, 9, dtype=jnp.int32), (batch, 1))
    lens = jnp.full((batch,), 4, jnp.int32)
    wb = jnp.arange(1, batch + 1, dtype=jnp.int32)
    wo = jnp.full((batch,), 3, jnp.int32)

    def timed(mb_env: str) -> float:
        prior = os.environ.get("DTPU_PP_MICROBATCHES")
        prior_skip = os.environ.pop("DTPU_PP_COND_SKIP", None)  # pin cond-skip
        os.environ["DTPU_PP_MICROBATCHES"] = mb_env
        try:
            fwd = jax.jit(pp_serving.make_pp_decode_forward(mesh, mcfg, pp, 1))
            h, k2, v2 = fwd(params, k, v, tokens, positions, tables, lens, wb, wo)
            np.asarray(h)  # compile + settle
            t0 = time.perf_counter()
            kk, vv = k, v
            for _ in range(steps):
                h, kk, vv = fwd(
                    params, kk, vv, tokens, positions, tables, lens, wb, wo
                )
            np.asarray(h)
            return (time.perf_counter() - t0) / steps
        finally:
            if prior is None:
                os.environ.pop("DTPU_PP_MICROBATCHES", None)
            else:
                os.environ["DTPU_PP_MICROBATCHES"] = prior
            if prior_skip is not None:
                os.environ["DTPU_PP_COND_SKIP"] = prior_skip

    t_m1 = timed("1")
    t_mpp = timed(str(pp))
    model_ratio = (pp * batch) / ((2 * pp - 1) * batch / pp)
    return {
        "pp": pp, "batch": batch,
        "step_ms_m1_cond_skip": round(t_m1 * 1e3, 3),
        "step_ms_microbatched": round(t_mpp * 1e3, 3),
        "m1_over_mpp": round(t_m1 / max(t_mpp, 1e-9), 3),
        "flop_model_mpp_speedup_if_compute_bound": round(model_ratio, 3),
    }
