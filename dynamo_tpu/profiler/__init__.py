"""SLA profiler: measured (isl, batch) sweeps feeding the planner's capacity
model and the mocker's timing calibration (reference:
benchmarks/profiler/profile_sla.py:138; lib/mocker/src/perf_model.rs)."""

from .sweep import ProfileResult, calibrate_mocker_args, profile_engine

__all__ = ["ProfileResult", "calibrate_mocker_args", "profile_engine"]
