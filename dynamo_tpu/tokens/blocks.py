"""Chained block hashing over token id sequences.

Hash design: ``seq_hash[i] = H(seq_hash[i-1] || tokens[i])`` with a 64-bit
stable digest (blake2b/8), optionally salted by an "extra key" (lora id,
multimodal content hash) the way the reference mixes extra state into its
``PositionalSequenceHash`` (lib/tokens/src/blocks.rs:59). Stability across
processes and hosts matters: routers and workers must agree on hashes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import struct
from typing import Iterable, List, Optional, Sequence

BlockHash = int      # hash of one block's tokens alone
SequenceHash = int   # chained hash: identifies block *in its prefix context*

_U64 = struct.Struct("<Q")


def _digest64(payload: bytes) -> int:
    return _U64.unpack(hashlib.blake2b(payload, digest_size=8).digest())[0]


def compute_block_hash(tokens: Sequence[int], extra_key: Optional[bytes] = None) -> BlockHash:
    payload = b"".join(_U64.pack(t & 0xFFFFFFFFFFFFFFFF) for t in tokens)
    if extra_key:
        payload += b"\x00" + extra_key
    return _digest64(payload)


def chain_hash(parent: Optional[SequenceHash], block_hash: BlockHash) -> SequenceHash:
    if parent is None:
        return _digest64(b"root" + _U64.pack(block_hash))
    return _digest64(_U64.pack(parent) + _U64.pack(block_hash))


def compute_sequence_hashes(
    tokens: Sequence[int],
    block_size: int,
    extra_key: Optional[bytes] = None,
) -> List[SequenceHash]:
    """Sequence hashes for every *complete* block of ``tokens``."""
    out: List[SequenceHash] = []
    parent: Optional[SequenceHash] = None
    for start in range(0, len(tokens) - len(tokens) % block_size, block_size):
        bh = compute_block_hash(tokens[start : start + block_size], extra_key)
        parent = chain_hash(parent, bh)
        out.append(parent)
    return out


@dataclasses.dataclass(frozen=True)
class TokenBlock:
    tokens: tuple
    block_hash: BlockHash
    sequence_hash: SequenceHash
    parent_hash: Optional[SequenceHash]
    position: int  # block index within the sequence


class TokenBlockSequence:
    """A token id sequence chunked into hashed blocks + a mutable partial tail.

    Supports incremental append (decode loop grows the sequence one token at a
    time and new blocks seal as they fill), mirroring the reference's
    TokenBlockSequence (lib/tokens/src/lib.rs).
    """

    def __init__(
        self,
        tokens: Iterable[int] = (),
        block_size: int = 16,
        extra_key: Optional[bytes] = None,
    ):
        if block_size <= 0:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self.block_size = block_size
        self.extra_key = extra_key
        self.blocks: List[TokenBlock] = []
        self._tail: List[int] = []
        self.extend(tokens)

    # -- growth -------------------------------------------------------------
    def append(self, token: int) -> Optional[TokenBlock]:
        """Add one token; returns the newly sealed block if one completed."""
        self._tail.append(token)
        if len(self._tail) == self.block_size:
            return self._seal()
        return None

    def extend(self, tokens: Iterable[int]) -> List[TokenBlock]:
        sealed = []
        for t in tokens:
            b = self.append(t)
            if b is not None:
                sealed.append(b)
        return sealed

    def _seal(self) -> TokenBlock:
        parent = self.blocks[-1].sequence_hash if self.blocks else None
        bh = compute_block_hash(self._tail, self.extra_key)
        sh = chain_hash(parent, bh)
        block = TokenBlock(
            tokens=tuple(self._tail),
            block_hash=bh,
            sequence_hash=sh,
            parent_hash=parent,
            position=len(self.blocks),
        )
        self.blocks.append(block)
        self._tail = []
        return block

    # -- views --------------------------------------------------------------
    @property
    def tail_tokens(self) -> List[int]:
        return list(self._tail)

    def sequence_hashes(self) -> List[SequenceHash]:
        return [b.sequence_hash for b in self.blocks]

    def tokens(self) -> List[int]:
        out: List[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self._tail)
        return out

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self._tail)

    def num_blocks(self) -> int:
        return len(self.blocks)
