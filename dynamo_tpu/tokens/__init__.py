"""Token sequences and content-addressed KV block hashing.

Analog of the reference's tokens crate (lib/tokens/src/blocks.rs:35-59,
lib/tokens/src/lib.rs): a prompt's token ids are chunked into fixed-size
blocks; each block gets a *sequence hash* chained from its parent so that two
requests sharing a prefix produce identical hash chains — the foundation of
prefix-aware KV routing and block reuse.
"""

from .blocks import (
    BlockHash,
    SequenceHash,
    TokenBlock,
    TokenBlockSequence,
    compute_block_hash,
    compute_sequence_hashes,
)

__all__ = [
    "BlockHash",
    "SequenceHash",
    "TokenBlock",
    "TokenBlockSequence",
    "compute_block_hash",
    "compute_sequence_hashes",
]
