"""python -m dynamo_tpu.deploy — render a graph spec to k8s manifests.

    python -m dynamo_tpu.deploy render deploy/examples/agg-serving.yaml
    python -m dynamo_tpu.deploy render spec.yaml -o manifests/
"""

import argparse
import os
import sys

import yaml

from dynamo_tpu.deploy.render import GraphSpec, render, render_yaml


def main() -> None:
    p = argparse.ArgumentParser("dynamo_tpu.deploy")
    sub = p.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("render", help="graph spec -> k8s YAML")
    r.add_argument("spec")
    r.add_argument("-o", "--out-dir", default=None,
                   help="write one file per object (default: stdout stream)")
    args = p.parse_args()

    graph = GraphSpec.load(args.spec)
    if args.out_dir is None:
        sys.stdout.write(render_yaml(graph))
        return
    os.makedirs(args.out_dir, exist_ok=True)
    for obj in render(graph):
        name = f"{obj['kind'].lower()}-{obj['metadata']['name']}.yaml"
        with open(os.path.join(args.out_dir, name), "w") as f:
            yaml.safe_dump(obj, f, sort_keys=False)
        print(os.path.join(args.out_dir, name))


if __name__ == "__main__":
    main()
