"""python -m dynamo_tpu.deploy — render a graph spec, or reconcile it.

    python -m dynamo_tpu.deploy render deploy/examples/agg-serving.yaml
    python -m dynamo_tpu.deploy render spec.yaml -o manifests/
    python -m dynamo_tpu.deploy controller spec.yaml --store file --store-path /tmp/s
    python -m dynamo_tpu.deploy controller spec.yaml --backend kube --kube-url http://...

`controller` runs the operator's reconcile loop against one of two backends:
local (deploy/controller.py) spawns/kills worker OS processes; kube
(deploy/kube.py) creates/patches/garbage-collects Deployments and
StatefulSets through the kubernetes API. Both overlay live planner scale
targets, hot-reload the spec, and write status back to the store.
"""

import argparse
import asyncio
import os
import signal as _signal
import sys

import yaml

from dynamo_tpu.deploy.render import GraphSpec, render, render_yaml


async def _run_controller(args) -> None:
    from dynamo_tpu.runtime.discovery.store import make_store

    store = make_store(args.store, args.store_path)
    graph = GraphSpec.load(args.spec)
    if args.backend == "kube":
        from dynamo_tpu.deploy.kube import KubeClient, KubeGraphController

        ctl = KubeGraphController(
            KubeClient(args.kube_url, args.kube_token),
            store, graph,
            namespace=args.namespace,
            interval_s=args.interval,
            spec_path=args.spec,
        ).start()
    else:
        from dynamo_tpu.deploy.controller import GraphController, default_runner

        ctl = GraphController(
            store, graph,
            runner=default_runner(args.store, args.store_path),
            namespace=args.namespace,
            interval_s=args.interval,
            spec_path=args.spec,
        ).start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for s in (_signal.SIGINT, _signal.SIGTERM):
        loop.add_signal_handler(s, stop.set)
    print(f"CONTROLLER_READY {graph.name}", flush=True)
    await stop.wait()
    await ctl.stop()
    await store.close()


async def _run_epp(args) -> None:
    from dynamo_tpu.deploy.epp import EndpointPicker
    from dynamo_tpu.runtime import DistributedRuntime, RouterMode, RuntimeConfig

    rt = await DistributedRuntime(
        RuntimeConfig.from_env(store=args.store, store_path=args.store_path)
    ).start()
    picker = EndpointPicker(
        rt, host=args.host, port=args.port,
        router_mode=RouterMode(args.router_mode),
    )
    await picker.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for s in (_signal.SIGINT, _signal.SIGTERM):
        loop.add_signal_handler(s, stop.set)
    print(f"EPP_READY {args.host}:{picker.port}", flush=True)
    await stop.wait()
    await picker.stop()
    await rt.shutdown()


def main() -> None:
    p = argparse.ArgumentParser("dynamo_tpu.deploy")
    sub = p.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("render", help="graph spec -> k8s YAML")
    r.add_argument("spec")
    r.add_argument("-o", "--out-dir", default=None,
                   help="write one file per object (default: stdout stream)")
    c = sub.add_parser(
        "controller",
        help="reconcile the spec (local OS processes, or Deployments "
        "through the kube API with --backend kube)",
    )
    c.add_argument("spec")
    c.add_argument("--store", default="file")
    c.add_argument("--store-path", default="/tmp/dtpu_store")
    c.add_argument("--namespace", default="dynamo")
    c.add_argument("--interval", type=float, default=1.0)
    c.add_argument("--backend", default="local", choices=["local", "kube"],
                   help="local: reconcile OS processes; kube: reconcile "
                   "Deployments/StatefulSets through the kube API "
                   "(deploy/kube.py)")
    c.add_argument("--kube-url", default=None,
                   help="kube API base URL (default: in-cluster config)")
    c.add_argument("--kube-token", default=None)
    e = sub.add_parser(
        "epp", help="endpoint picker for inference gateways (deploy/epp.py)"
    )
    e.add_argument("--store", default="file")
    e.add_argument("--store-path", default="/tmp/dtpu_store")
    e.add_argument("--host", default="0.0.0.0")
    e.add_argument("--port", type=int, default=9200)
    e.add_argument("--router-mode", default="kv", choices=["kv", "round-robin"])
    args = p.parse_args()

    if args.cmd == "controller":
        asyncio.run(_run_controller(args))
        return
    if args.cmd == "epp":
        asyncio.run(_run_epp(args))
        return

    graph = GraphSpec.load(args.spec)
    if args.out_dir is None:
        sys.stdout.write(render_yaml(graph))
        return
    os.makedirs(args.out_dir, exist_ok=True)
    for obj in render(graph):
        name = f"{obj['kind'].lower()}-{obj['metadata']['name']}.yaml"
        with open(os.path.join(args.out_dir, name), "w") as f:
            yaml.safe_dump(obj, f, sort_keys=False)
        print(os.path.join(args.out_dir, name))


if __name__ == "__main__":
    main()
