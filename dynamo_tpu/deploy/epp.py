"""Endpoint picker (EPP): the inference-gateway extension, TPU-native.

Analog of the reference's Gateway API Inference Extension endpoint picker
(deploy/inference-gateway/epp + pkg/plugins/dynamo_kv_scorer): an external
gateway asks "which backend should this request go to?" and the picker
answers using the SAME KV-router scoring the frontend uses — prefix-cache
overlap from live KV events plus load — so gateway-routed traffic lands on
the worker already holding the prompt's KV.

Where the reference plugs into Envoy ext-proc via a C API into the Rust
router, this picker is a small HTTP service over the framework's own
discovery + KvRouter:

    POST /pick {"model": m, "text": ... | "token_ids": [...]}
      -> {"address", "instance_id", "dp_rank", "overlap_blocks"}
    GET  /models     -> served models
    GET  /health

The gateway forwards the request to `address` itself (the picker never
proxies payloads — exactly the EPP contract).

    python -m dynamo_tpu.deploy epp --store file --store-path $S --port 9200
"""

from __future__ import annotations

from typing import Optional

from aiohttp import web

from ..llm.discovery import ModelManager, ModelWatcher
from ..runtime import DistributedRuntime, RouterMode
from ..runtime.logging import get_logger

log = get_logger("deploy.epp")


class EndpointPicker:
    def __init__(
        self,
        runtime: DistributedRuntime,
        host: str = "0.0.0.0",
        port: int = 9200,
        router_mode: RouterMode = RouterMode.KV,
    ):
        self.runtime = runtime
        self.manager = ModelManager()
        self.router_mode = router_mode
        self.host = host
        self.port = port
        self._watcher: Optional[ModelWatcher] = None
        self._runner: Optional[web.AppRunner] = None

    async def start(self) -> str:
        self._watcher = await ModelWatcher(
            self.runtime, self.manager, self.router_mode
        ).start()
        app = web.Application()
        app.router.add_post("/pick", self.pick)
        app.router.add_get("/models", self.models)
        app.router.add_get("/health", self.health)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore
        log.info("endpoint picker on %s:%d", self.host, self.port)
        return f"{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        if self._watcher is not None:
            await self._watcher.stop()

    # ---------------------------------------------------------------- handlers
    async def health(self, request: web.Request) -> web.Response:
        return web.json_response({
            "status": "healthy", "models": self.manager.list_models(),
        })

    async def models(self, request: web.Request) -> web.Response:
        return web.json_response({"models": self.manager.list_models()})

    async def pick(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid JSON"}, status=400)
        model = body.get("model")
        pipe = self.manager.get(model) if model else None
        if pipe is None or pipe.client is None:
            return web.json_response(
                {"error": f"model {model!r} not found"}, status=404
            )
        token_ids = body.get("token_ids")
        if token_ids is None and body.get("text"):
            token_ids = pipe.preprocessor.tokenizer.encode(body["text"])
        token_ids = [int(t) for t in (token_ids or [])]
        try:
            # /pick returns instance ids as 16-hex strings; accept them (or
            # plain ints) back in `excluded`
            excluded = [
                int(x, 16) if isinstance(x, str) else int(x)
                for x in body.get("excluded", [])
            ]
        except (TypeError, ValueError) as e:
            return web.json_response(
                {"error": f"bad excluded entry: {e}"}, status=400
            )

        pipe._prune_dead_workers()  # ghost state must not skew scoring
        cands = pipe._candidates(excluded)
        if not cands:
            return web.json_response({"error": "no live workers"}, status=503)
        if pipe.kv_router is not None and token_ids:
            # stateless scoring: the gateway routes (and finishes) requests
            # itself, so the picker never charges in-flight load it could
            # not release
            decision = pipe.kv_router.score_tokens(token_ids, cands)
            worker_id = decision.worker.worker_id
            dp_rank = decision.worker.dp_rank
            overlap = decision.overlap_blocks
        else:
            # no KV signal: plain round robin over live instances
            ids = sorted({c.worker_id for c in cands})
            worker_id = ids[getattr(self, "_rr", 0) % len(ids)]
            self._rr = getattr(self, "_rr", 0) + 1
            dp_rank, overlap = 0, 0
        inst = pipe.client.instances.get(worker_id)
        if inst is None:
            return web.json_response({"error": "picked worker vanished"}, status=503)
        return web.json_response({
            "address": inst.address,
            "instance_id": f"{worker_id:016x}",
            "dp_rank": dp_rank,
            "overlap_blocks": overlap,
            "transport": inst.transport,
        })
