"""Kubernetes-native graph controller: reconcile rendered manifests in-cluster.

The reference runs a Go operator (deploy/operator/internal/controller/
dynamographdeployment_controller.go) that watches DynamoGraphDeployment CRs
and drives Deployments/StatefulSets through the kube API, with the planner
scaling via a kubernetes connector patching replicas
(components/src/dynamo/planner/kubernetes_connector.py:48,333). This module
is that control loop for the TPU stack: the SAME GraphSpec deploy/render.py
renders offline is applied, watched, and scaled against a real (or mocked)
kube API server — level-triggered, replicas overlaid with live planner scale
targets from the discovery store.

No kubernetes client dependency: the API surface used (list/get/create/
merge-patch/delete/watch) is a handful of well-documented HTTP endpoints,
and owning the client keeps the controller runnable against the in-repo
mock API server (tests/kube_mock.py) exactly the way the etcd gateway
backend is tested.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import aiohttp

from ..planner.connectors import target_key
from ..runtime.discovery.store import KVStore
from ..runtime.logging import get_logger
from .controller import status_key
from .render import GraphSpec, render

log = get_logger("deploy.kube")

_PLURALS = {
    "Deployment": "deployments",
    "StatefulSet": "statefulsets",
    "Service": "services",
}

# in-cluster service-account paths (used when base_url/token not given)
_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def _api_path(api_version: str, namespace: str, plural: str) -> str:
    root = "/api" if "/" not in api_version else "/apis"
    return f"{root}/{api_version}/namespaces/{namespace}/{plural}"


class KubeClient:
    """Minimal async kube API client: exactly the verbs the controller needs."""

    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        session: Optional[aiohttp.ClientSession] = None,
    ):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise ValueError(
                    "no kube API: pass base_url or run in-cluster "
                    "(KUBERNETES_SERVICE_HOST unset)"
                )
            base_url = f"https://{host}:{port}"
            token_path = os.path.join(_SA_DIR, "token")
            if token is None and os.path.exists(token_path):
                token = open(token_path).read().strip()
        self.base_url = base_url.rstrip("/")
        self._token = token
        self._session = session
        self._own_session = session is None

    async def _http(self) -> aiohttp.ClientSession:
        if self._session is None:
            headers = {}
            if self._token:
                headers["Authorization"] = f"Bearer {self._token}"
            self._session = aiohttp.ClientSession(
                headers=headers,
                connector=aiohttp.TCPConnector(ssl=False),
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None and self._own_session:
            await self._session.close()
        self._session = None

    # -------------------------------------------------------------- verbs
    async def list(
        self, api_version: str, namespace: str, plural: str,
        label_selector: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        http = await self._http()
        params = {}
        if label_selector:
            params["labelSelector"] = label_selector
        async with http.get(
            self.base_url + _api_path(api_version, namespace, plural),
            params=params,
        ) as r:
            r.raise_for_status()
            return (await r.json()).get("items", [])

    async def get(
        self, api_version: str, namespace: str, plural: str, name: str
    ) -> Optional[Dict[str, Any]]:
        http = await self._http()
        async with http.get(
            f"{self.base_url}{_api_path(api_version, namespace, plural)}/{name}"
        ) as r:
            if r.status == 404:
                return None
            r.raise_for_status()
            return await r.json()

    async def create(
        self, api_version: str, namespace: str, plural: str, obj: Dict[str, Any]
    ) -> Dict[str, Any]:
        http = await self._http()
        async with http.post(
            self.base_url + _api_path(api_version, namespace, plural), json=obj
        ) as r:
            r.raise_for_status()
            return await r.json()

    async def patch(
        self, api_version: str, namespace: str, plural: str, name: str,
        patch: Dict[str, Any],
    ) -> Dict[str, Any]:
        http = await self._http()
        async with http.patch(
            f"{self.base_url}{_api_path(api_version, namespace, plural)}/{name}",
            data=json.dumps(patch),
            headers={"Content-Type": "application/merge-patch+json"},
        ) as r:
            r.raise_for_status()
            return await r.json()

    async def delete(
        self, api_version: str, namespace: str, plural: str, name: str
    ) -> None:
        http = await self._http()
        async with http.delete(
            f"{self.base_url}{_api_path(api_version, namespace, plural)}/{name}"
        ) as r:
            if r.status != 404:
                r.raise_for_status()

    async def watch(
        self, api_version: str, namespace: str, plural: str,
        label_selector: Optional[str] = None,
        resource_version: Optional[str] = None,
    ) -> AsyncIterator[Dict[str, Any]]:
        """Yield watch events ({type, object}) until the server closes the
        stream (normal kube behavior — callers re-list + re-watch)."""
        http = await self._http()
        params: Dict[str, str] = {"watch": "true"}
        if label_selector:
            params["labelSelector"] = label_selector
        if resource_version:
            params["resourceVersion"] = resource_version
        async with http.get(
            self.base_url + _api_path(api_version, namespace, plural),
            params=params,
            timeout=aiohttp.ClientTimeout(total=None, sock_read=None),
        ) as r:
            r.raise_for_status()
            buf = b""
            async for chunk in r.content.iter_any():
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if line.strip():
                        yield json.loads(line)


def _obj_key(obj: Dict[str, Any]) -> Tuple[str, str]:
    return obj["kind"], obj["metadata"]["name"]


class KubeGraphController:
    """Level-triggered reconcile of a GraphSpec against the kube API.

    Desired state = deploy/render.py manifests with replicas overlaid by the
    planner's live scale targets (``v1/scale/{ns}/{service}`` store keys —
    the same contract the local-process GraphController serves, so the
    planner is oblivious to which backend runs the graph). Observed state =
    the cluster's objects labeled ``app.kubernetes.io/part-of=<graph>``.
    Reconciliation creates missing objects, merge-patches replicas drift,
    garbage-collects objects for services removed from the spec, and writes
    a status object (per-service desired/ready from Deployment status) back
    to the discovery store.
    """

    def __init__(
        self,
        kube: KubeClient,
        store: KVStore,
        graph: GraphSpec,
        namespace: str = "dynamo",
        interval_s: float = 2.0,
        spec_path: Optional[str] = None,
    ):
        self.kube = kube
        self.store = store
        self.graph = graph
        self.namespace = namespace  # DISCOVERY namespace (scale/status keys)
        self.interval_s = interval_s
        self.spec_path = spec_path
        self._spec_mtime = os.path.getmtime(spec_path) if spec_path else 0.0
        self._task: Optional[asyncio.Task] = None
        self._watch_tasks: List[asyncio.Task] = []
        self._poke = asyncio.Event()

    # ------------------------------------------------------------- desired
    async def _desired_objects(self) -> List[Dict[str, Any]]:
        objs = render(self.graph)
        for svc in self.graph.services:
            target = await self.store.get_obj(
                target_key(self.namespace, svc.name)
            )
            if not target or "target" not in target:
                continue
            want = max(0, int(target["target"]))
            name = f"{self.graph.name}-{svc.name}"
            for obj in objs:
                if (
                    obj["kind"] in ("Deployment", "StatefulSet")
                    and obj["metadata"]["name"] == name
                ):
                    obj["spec"]["replicas"] = want
        return objs

    def _maybe_reload_spec(self) -> None:
        if not self.spec_path:
            return
        try:
            mtime = os.path.getmtime(self.spec_path)
        except OSError:
            return
        if mtime != self._spec_mtime:
            self._spec_mtime = mtime
            try:
                self.graph = GraphSpec.load(self.spec_path)
                log.info("spec reloaded from %s", self.spec_path)
            except Exception:
                log.exception("bad spec update ignored (keeping last good)")

    # ----------------------------------------------------------- reconcile
    async def reconcile_once(self) -> Dict[str, Any]:
        self._maybe_reload_spec()
        kns = self.graph.namespace
        desired = await self._desired_objects()
        selector = f"app.kubernetes.io/part-of={self.graph.name}"

        observed: Dict[Tuple[str, str], Dict[str, Any]] = {}
        for kind, plural in _PLURALS.items():
            api = "v1" if kind == "Service" else "apps/v1"
            for obj in await self.kube.list(api, kns, plural, selector):
                obj.setdefault("kind", kind)
                observed[_obj_key(obj)] = obj

        status: Dict[str, Any] = {"services": {}, "ts": time.time(), "backend": "kube"}
        for obj in desired:
            kind = obj["kind"]
            plural = _PLURALS[kind]
            api = "v1" if kind == "Service" else "apps/v1"
            name = obj["metadata"]["name"]
            live = observed.pop((kind, name), None)
            if live is None:
                log.info("create %s/%s", plural, name)
                live = await self.kube.create(api, kns, plural, obj)
            elif kind in ("Deployment", "StatefulSet"):
                want = obj["spec"]["replicas"]
                have = live.get("spec", {}).get("replicas")
                if want != have:
                    log.info("scale %s/%s: %s -> %s", plural, name, have, want)
                    live = await self.kube.patch(
                        api, kns, plural, name, {"spec": {"replicas": want}}
                    )
            if kind in ("Deployment", "StatefulSet"):
                svc_name = name[len(self.graph.name) + 1 :]
                status["services"][svc_name] = {
                    "desired": obj["spec"]["replicas"],
                    "ready": int(
                        (live.get("status") or {}).get("readyReplicas") or 0
                    ),
                }
        # GC: anything still in `observed` is labeled ours but not desired
        for (kind, name), _obj in observed.items():
            plural = _PLURALS[kind]
            api = "v1" if kind == "Service" else "apps/v1"
            log.info("gc %s/%s", plural, name)
            await self.kube.delete(api, kns, plural, name)

        try:
            await self.store.put_obj(
                status_key(self.namespace, self.graph.name), status
            )
        except Exception:
            log.exception("status write failed")
        return status

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "KubeGraphController":
        async def loop() -> None:
            try:
                while True:
                    try:
                        await self.reconcile_once()
                    except Exception:
                        log.exception("kube reconcile failed")
                    self._poke.clear()
                    try:
                        await asyncio.wait_for(
                            self._poke.wait(), self.interval_s
                        )
                    except asyncio.TimeoutError:
                        pass
            except asyncio.CancelledError:
                pass

        async def watch(plural: str) -> None:
            """Event-triggered reconcile: any change to our workloads pokes
            the loop immediately (kube watch streams end periodically; just
            re-watch — the reconcile itself is level-triggered). API hiccups
            back off through the shared policy (scope kube.watch): jittered,
            growing with consecutive failures, reset on a delivering stream."""
            from ..runtime.resilience import retry_policy

            policy = retry_policy(
                "kube.watch", max_attempts=2, base_delay_s=0.5, max_delay_s=10.0,
            )
            selector = f"app.kubernetes.io/part-of={self.graph.name}"
            prev_delay = None
            try:
                while True:
                    try:
                        async for _ev in self.kube.watch(
                            "apps/v1", self.graph.namespace, plural, selector
                        ):
                            self._poke.set()
                            prev_delay = None
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        prev_delay = policy.next_delay(prev_delay)
                        await asyncio.sleep(prev_delay)
            except asyncio.CancelledError:
                pass

        self._task = asyncio.create_task(loop())
        self._watch_tasks = [
            asyncio.create_task(watch(p))
            for p in ("deployments", "statefulsets")
        ]
        return self

    async def stop(self) -> None:
        for t in [self._task] + list(self._watch_tasks or []):
            if t is not None:
                t.cancel()
        await self.kube.close()
