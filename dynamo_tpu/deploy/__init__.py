"""Deploy layer: DynamoGraphDeployment-style specs rendered to TPU-ready
Kubernetes manifests (reference deploy/operator/)."""

from .render import GraphSpec, ServiceSpec, render, render_service, render_yaml

__all__ = ["GraphSpec", "ServiceSpec", "render", "render_service", "render_yaml"]
