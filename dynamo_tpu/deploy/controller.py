"""GraphController: the operator's reconcile loop, scaled to one host.

Analog of the reference's DynamoGraphDeployment controller
(deploy/operator/internal/controller/dynamographdeployment_controller.go):
a level-triggered loop that drives ACTUAL worker processes toward DESIRED
state, where desired = the rendered graph spec (deploy/render.py GraphSpec)
overlaid with live scale targets written by the planner (the
VirtualConnector's ``v1/scale/{ns}/{component}`` keys — the reference
planner patches the CRD's replicas the same way).

What reconciliation covers, mirroring the Go controller's behavior:
  - spawn/kill to match replicas (scale subresource);
  - restart crashed processes (pod restart policy);
  - hot-reload of the spec file (CRD update events);
  - a status object written back to the store (status subresource):
    per-service desired/ready plus controller conditions.

Processes are real OS processes (mocker / engine / frontend workers built
from the ServiceSpec); on k8s the same spec renders to Deployments via
deploy/render.py — the controller is what makes the single-host (and CI)
story reconcile for real instead of pretending.
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional

from ..planner.connectors import target_key
from ..runtime.discovery.store import KVStore
from ..runtime.faults import FAULTS
from ..runtime.logging import get_logger
from ..runtime.resilience import RetryPolicy
from .render import GraphSpec, ServiceSpec

log = get_logger("deploy.controller")


def status_key(namespace: str, graph: str) -> str:
    return f"v1/controller/{namespace}/{graph}/status"


def default_runner(store_kind: str, store_path: str):
    """ServiceSpec -> argv for one local worker process of that service."""

    def run(svc: ServiceSpec, index: int) -> List[str]:
        base = [sys.executable, "-m"]
        store = ["--store", store_kind, "--store-path", store_path]
        if svc.kind == "frontend":
            return base + ["dynamo_tpu.frontend"] + store + list(svc.args)
        if svc.kind == "router":
            return base + ["dynamo_tpu.router"] + store + list(svc.args)
        if svc.kind == "kvbm":
            return base + ["dynamo_tpu.kvbm"] + list(svc.args)
        # worker: a real engine when a model/preset is pinned, else mocker
        if svc.preset or svc.model:
            cmd = base + ["dynamo_tpu.engine"] + store + [
                "--component", svc.name, "--tp", str(svc.tp),
                "--sp", str(svc.sp), "--dp", str(svc.dp),
            ]
            if svc.preset:
                cmd += ["--preset", svc.preset]
            if svc.model:
                # spec `model` is a checkpoint reference (local dir or hub
                # org/name) — the weights to LOAD, served under that name.
                # --model alone would only rename a random-init preset.
                cmd += ["--model-path", svc.model, "--model", svc.model]
            if svc.disagg:
                cmd += ["--disagg", svc.disagg]
            return cmd + list(svc.args)
        return base + ["dynamo_tpu.mocker"] + store + [
            "--component", svc.name,
        ] + list(svc.args)

    return run


@dataclasses.dataclass
class _Proc:
    popen: subprocess.Popen
    started: float
    restarts: int = 0


@dataclasses.dataclass
class _CrashState:
    """Per-service crash-loop bookkeeping, kept in ONE record so the
    quiet-horizon reset cannot desynchronize the backoff chain."""

    streak: int = 0
    last_delay: Optional[float] = None   # jitter chain carry
    restart_after: float = 0.0
    last_crash_at: float = 0.0


class GraphController:
    def __init__(
        self,
        store: KVStore,
        graph: GraphSpec,
        runner: Callable[[ServiceSpec, int], List[str]],
        namespace: str = "dynamo",
        interval_s: float = 1.0,
        spec_path: Optional[str] = None,
        restart_backoff_s: float = 1.0,
        env: Optional[Dict[str, str]] = None,
    ):
        self.store = store
        self.graph = graph
        self.runner = runner
        self.namespace = namespace
        self.interval_s = interval_s
        self.spec_path = spec_path
        self.restart_backoff_s = restart_backoff_s
        self.env = env
        self._procs: Dict[str, List[_Proc]] = {}
        # scale-down victims: SIGTERM'd, awaiting exit; escalated to SIGKILL
        # past their grace deadline and reaped (wait) so nothing zombies
        self._stopping: List[tuple] = []  # (_Proc, kill_deadline)
        self._stop_grace_s = 10.0
        # crash-looping services back off through the shared policy (scope
        # controller.restart; DTPU_RETRY_CONTROLLER_RESTART): consecutive
        # crashes stretch the delay exponentially (decorrelated jitter, the
        # k8s CrashLoopBackOff analog) instead of the old fixed interval.
        # restart_backoff_s stays the base so existing configs keep meaning.
        self._restart_policy = RetryPolicy.from_env(
            "controller.restart",
            base_delay_s=restart_backoff_s,
            max_delay_s=max(30.0, restart_backoff_s),
        )
        self._crash: Dict[str, _CrashState] = {}
        self._spec_mtime = (
            os.path.getmtime(spec_path) if spec_path else 0.0
        )
        self._task: Optional[asyncio.Task] = None
        self.restarts_total = 0

    # ------------------------------------------------------------ desired
    async def _desired(self, svc: ServiceSpec) -> int:
        """Spec replicas, overridden by a live planner scale target."""
        obj = await self.store.get_obj(target_key(self.namespace, svc.name))
        if obj and "target" in obj:
            return max(0, int(obj["target"]))
        return svc.replicas

    def _maybe_reload_spec(self) -> None:
        if not self.spec_path:
            return
        try:
            mtime = os.path.getmtime(self.spec_path)
        except OSError:
            return
        if mtime != self._spec_mtime:
            self._spec_mtime = mtime
            try:
                self.graph = GraphSpec.load(self.spec_path)
                log.info("spec reloaded from %s", self.spec_path)
            except Exception:
                log.exception("bad spec update ignored (keeping last good)")

    # ---------------------------------------------------------- reconcile
    def _drain_stopping(self) -> None:
        """Reap terminated scale-down victims; SIGKILL stragglers."""
        still: List[tuple] = []
        for p, deadline in self._stopping:
            if p.popen.poll() is not None:
                p.popen.wait()  # reap
                continue
            if time.time() >= deadline:
                log.warning("pid %d ignored SIGTERM; killing", p.popen.pid)
                p.popen.kill()
            still.append((p, deadline))
        self._stopping = still

    async def reconcile_once(self) -> Dict[str, Any]:
        self._maybe_reload_spec()
        self._drain_stopping()
        status: Dict[str, Any] = {"services": {}, "ts": time.time()}
        # garbage-collect services removed by a spec update (the k8s
        # controller deletes their Deployments the same way)
        live_names = {svc.name for svc in self.graph.services}
        for name in list(self._procs):
            if name not in live_names:
                for p in self._procs.pop(name):
                    if p.popen.poll() is None:
                        log.info("service %s removed: stopping pid %d",
                                 name, p.popen.pid)
                        p.popen.send_signal(signal.SIGTERM)
                        self._stopping.append(
                            (p, time.time() + self._stop_grace_s)
                        )
        for svc in self.graph.services:
            desired = await self._desired(svc)
            procs = self._procs.setdefault(svc.name, [])
            # reap exits; a crash (nonzero before teardown) counts toward
            # the restart condition and is backed off, not hot-looped
            alive: List[_Proc] = []
            for p in procs:
                if p.popen.poll() is None:
                    alive.append(p)
                else:
                    rc = p.popen.returncode
                    if rc != 0:
                        cs = self._crash.setdefault(svc.name, _CrashState())
                        cs.streak += 1
                        cs.last_delay = self._restart_policy.next_delay(
                            cs.last_delay
                        )
                        log.warning(
                            "%s worker pid %d crashed rc=%s (streak %d, "
                            "restart in %.1fs)",
                            svc.name, p.popen.pid, rc, cs.streak,
                            cs.last_delay,
                        )
                        cs.restart_after = time.time() + cs.last_delay
                        cs.last_crash_at = time.time()
                        self.restarts_total += 1
            procs[:] = alive
            # the crash loop resets only after a genuinely quiet stretch —
            # long enough that a crash-after-warmup cycle (crash period >
            # the backoff itself) cannot re-zero the streak every lap and
            # defeat the exponential escalation
            quiet_horizon = max(30.0, 4.0 * self.restart_backoff_s)
            cs = self._crash.get(svc.name)
            if alive and cs is not None and (
                time.time() - cs.last_crash_at > quiet_horizon
            ):
                del self._crash[svc.name]
                cs = None
            backoff_until = cs.restart_after if cs is not None else 0.0
            while len(procs) < desired and time.time() >= backoff_until:
                FAULTS.inject("controller.spawn")
                cmd = self.runner(svc, len(procs))
                log.info("spawn %s[%d]: %s", svc.name, len(procs), " ".join(cmd))
                procs.append(_Proc(
                    subprocess.Popen(
                        cmd,
                        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                        env={**os.environ, **(self.env or {})},
                    ),
                    started=time.time(),
                ))
            while len(procs) > desired:
                p = procs.pop()
                log.info("scale down %s: stopping pid %d", svc.name, p.popen.pid)
                p.popen.send_signal(signal.SIGTERM)
                self._stopping.append((p, time.time() + self._stop_grace_s))
            status["services"][svc.name] = {
                "desired": desired,
                "ready": len(procs),
            }
        try:
            await self.store.put_obj(
                status_key(self.namespace, self.graph.name), status
            )
        except Exception:
            log.exception("status write failed")
        return status

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "GraphController":
        async def loop() -> None:
            try:
                while True:
                    try:
                        await self.reconcile_once()
                    except Exception:
                        log.exception("reconcile failed")
                    await asyncio.sleep(self.interval_s)
            except asyncio.CancelledError:
                pass

        self._task = asyncio.create_task(loop())
        return self

    async def stop(self, graceful_s: float = 5.0) -> None:
        if self._task is not None:
            self._task.cancel()
        everyone = [p for procs in self._procs.values() for p in procs]
        everyone += [p for p, _ in self._stopping]
        for p in everyone:
            if p.popen.poll() is None:
                p.popen.send_signal(signal.SIGTERM)
        deadline = time.time() + graceful_s
        for p in everyone:
            while p.popen.poll() is None and time.time() < deadline:
                await asyncio.sleep(0.1)
            if p.popen.poll() is None:
                p.popen.kill()
            p.popen.wait()
