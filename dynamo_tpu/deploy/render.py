"""DynamoGraphDeployment -> Kubernetes manifests, TPU-first.

Analog of the reference's operator CRD + controllers (deploy/operator/api/
v1alpha1/dynamographdeployment_types.go: a graph spec whose ``services`` map
declares frontends/routers/workers) collapsed to an offline renderer: one
graph YAML in, ready-to-apply Kubernetes YAML out. Where the reference
reconciles CRs in-cluster, this emits the same objects for `kubectl apply` /
GitOps — no controller process to operate, and the output is inspectable.

TPU-first specifics baked into worker rendering (GKE TPU scheduling):
- ``google.com/tpu`` resource requests sized tp*sp per worker;
- nodeSelector ``cloud.google.com/gke-tpu-accelerator`` +
  ``gke-tpu-topology`` derived from the requested chip count/generation;
- workers are a StatefulSet (stable identity for the discovery lease),
  frontends/routers are Deployments behind Services;
- every pod shares one netstore (discovery) Service and, optionally, a G4
  block-store Service.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import yaml

# chips -> (accelerator, topology) for single-host v5e slices
_V5E_TOPO = {1: "1x1", 4: "2x2", 8: "2x4"}


@dataclasses.dataclass
class ServiceSpec:
    """One entry of spec.services (DynamoComponentDeploymentSharedSpec analog)."""

    name: str
    kind: str                       # frontend | router | worker | netstore | kvbm
    replicas: int = 1
    image: str = "dynamo-tpu:latest"
    args: List[str] = dataclasses.field(default_factory=list)
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    port: Optional[int] = None
    # worker-only
    tp: int = 1
    sp: int = 1
    dp: int = 1
    tpu_generation: str = "v5e"
    model: Optional[str] = None
    preset: Optional[str] = None
    disagg: Optional[str] = None    # prefill | decode


@dataclasses.dataclass
class GraphSpec:
    name: str
    namespace: str = "default"
    services: List[ServiceSpec] = dataclasses.field(default_factory=list)
    envs: Dict[str, str] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "GraphSpec":
        services = []
        for name, svc in (obj.get("services") or {}).items():
            known = {f.name for f in dataclasses.fields(ServiceSpec)}
            svc = dict(svc)
            kind = svc.pop("kind", "worker")
            services.append(ServiceSpec(
                name=name, kind=kind,
                **{k: v for k, v in svc.items() if k in known},
            ))
        return cls(
            name=obj["name"],
            namespace=obj.get("namespace", "default"),
            services=services,
            envs={k: str(v) for k, v in (obj.get("envs") or {}).items()},
        )

    @classmethod
    def load(cls, path: str) -> "GraphSpec":
        with open(path) as f:
            return cls.from_obj(yaml.safe_load(f))


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _labels(graph: GraphSpec, svc: ServiceSpec) -> Dict[str, str]:
    return {
        "app.kubernetes.io/part-of": graph.name,
        "app.kubernetes.io/component": svc.kind,
        "app.kubernetes.io/name": f"{graph.name}-{svc.name}",
    }


def _env_list(graph: GraphSpec, svc: ServiceSpec, extra: Dict[str, str]) -> List[Dict[str, str]]:
    merged = {**graph.envs, **extra, **svc.env}
    return [{"name": k, "value": str(v)} for k, v in sorted(merged.items())]


def _store_address(graph: GraphSpec) -> str:
    return f"{graph.name}-netstore.{graph.namespace}.svc:7460"


def _container(graph: GraphSpec, svc: ServiceSpec, command: List[str],
               extra_env: Dict[str, str], resources: Optional[Dict] = None,
               ports: Optional[List[int]] = None) -> Dict[str, Any]:
    c: Dict[str, Any] = {
        "name": svc.name,
        "image": svc.image,
        "command": command + svc.args,
        "env": _env_list(graph, svc, extra_env),
    }
    if resources:
        c["resources"] = resources
    if ports:
        c["ports"] = [{"containerPort": p} for p in ports]
    return c


def _deployment(graph: GraphSpec, svc: ServiceSpec, container: Dict[str, Any],
                node_selector: Optional[Dict[str, str]] = None,
                kind: str = "Deployment") -> Dict[str, Any]:
    labels = _labels(graph, svc)
    pod_spec: Dict[str, Any] = {"containers": [container]}
    if node_selector:
        pod_spec["nodeSelector"] = node_selector
    obj: Dict[str, Any] = {
        "apiVersion": "apps/v1",
        "kind": kind,
        "metadata": {
            "name": f"{graph.name}-{svc.name}",
            "namespace": graph.namespace,
            "labels": labels,
        },
        "spec": {
            "replicas": svc.replicas,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": pod_spec,
            },
        },
    }
    if kind == "StatefulSet":
        obj["spec"]["serviceName"] = f"{graph.name}-{svc.name}"
        obj["spec"]["podManagementPolicy"] = "Parallel"
    return obj


def _service(graph: GraphSpec, svc: ServiceSpec, port: int,
             headless: bool = False) -> Dict[str, Any]:
    labels = _labels(graph, svc)
    spec: Dict[str, Any] = {"selector": labels}
    if port > 0:
        spec["ports"] = [{"port": port, "targetPort": port}]
    if headless:
        # identity-only Service (StatefulSet serviceName); the API server
        # rejects port 0, and a headless service needs no ports at all
        spec["clusterIP"] = "None"
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": f"{graph.name}-{svc.name}",
            "namespace": graph.namespace,
            "labels": labels,
        },
        "spec": spec,
    }


def _kvbm_address(graph: GraphSpec) -> Optional[str]:
    for s in graph.services:
        if s.kind == "kvbm":
            return f"{graph.name}-{s.name}.{graph.namespace}.svc:7440"
    return None


def render_service(graph: GraphSpec, svc: ServiceSpec) -> List[Dict[str, Any]]:
    store = {"DTPU_STORE": "tcp", "DTPU_STORE_PATH": _store_address(graph)}
    if svc.kind == "netstore":
        c = _container(
            graph, svc,
            ["python", "-m", "dynamo_tpu.runtime.discovery.netstore",
             "--port", "7460"],
            {}, ports=[7460],
        )
        return [_deployment(graph, svc, c), _service(graph, svc, 7460)]

    if svc.kind == "kvbm":
        c = _container(
            graph, svc,
            ["python", "-m", "dynamo_tpu.kvbm", "--port", "7440"],
            {}, ports=[7440],
        )
        return [_deployment(graph, svc, c), _service(graph, svc, 7440)]

    if svc.kind == "frontend":
        port = svc.port or 8000
        c = _container(
            graph, svc,
            ["python", "-m", "dynamo_tpu.frontend", "--port", str(port)],
            store, ports=[port],
        )
        return [_deployment(graph, svc, c), _service(graph, svc, port)]

    if svc.kind == "router":
        c = _container(
            graph, svc,
            ["python", "-m", "dynamo_tpu.router", "--replica-sync"],
            store,
        )
        return [_deployment(graph, svc, c)]

    if svc.kind == "worker":
        chips = svc.tp * svc.sp
        topo = _V5E_TOPO.get(chips)
        if svc.tpu_generation == "v5e" and topo is None:
            raise ValueError(
                f"{svc.name}: tp*sp={chips} has no single-host v5e topology "
                f"(choose from {sorted(_V5E_TOPO)})"
            )
        cmd = ["python", "-m", "dynamo_tpu.engine", "--tp", str(svc.tp),
               "--sp", str(svc.sp), "--dp", str(svc.dp)]
        kvbm_addr = _kvbm_address(graph)
        if kvbm_addr:
            # workers share the graph's G4 block store
            cmd += ["--kvbm-remote", kvbm_addr]
        if svc.model:
            cmd += ["--model", svc.model]
        if svc.preset:
            cmd += ["--preset", svc.preset]
        if svc.disagg:
            cmd += ["--disagg", svc.disagg]
        node_selector = {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": topo or "1x1",
        }
        c = _container(
            graph, svc, cmd, store,
            resources={
                "requests": {"google.com/tpu": chips},
                "limits": {"google.com/tpu": chips},
            },
        )
        return [
            _deployment(graph, svc, c, node_selector, kind="StatefulSet"),
            _service(graph, svc, 0, headless=True),
        ]

    raise ValueError(f"unknown service kind {svc.kind!r} for {svc.name!r}")


def render(graph: GraphSpec) -> List[Dict[str, Any]]:
    objs: List[Dict[str, Any]] = []
    kinds = [s.kind for s in graph.services]
    if "netstore" not in kinds:
        # every graph needs discovery; inject the shared store service
        objs += render_service(graph, ServiceSpec(name="netstore", kind="netstore"))
    for svc in graph.services:
        objs += render_service(graph, svc)
    return objs


def render_yaml(graph: GraphSpec) -> str:
    return yaml.safe_dump_all(render(graph), sort_keys=False)
