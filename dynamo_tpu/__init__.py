"""dynamo_tpu: a TPU-native distributed LLM inference serving framework.

A ground-up rebuild of the capabilities of NVIDIA Dynamo (see SURVEY.md) with a
JAX/XLA/Pallas engine at the core: OpenAI-compatible frontend, component-model
distributed runtime with pluggable request/event planes, KV-cache-aware radix
routing, disaggregated prefill/decode over separate XLA programs, multi-tier
KV block management (HBM -> host DRAM -> disk), request migration, SLA planner.
"""

__version__ = "0.1.0"
