"""TPU-native image diffusion: a DiT-style denoiser + jitted DDIM sampler.

Backs the frontend's /v1/images/generations the way the reference backs it
with a real diffusion engine behind its SGLang worker
(components/src/dynamo/sglang/main.py:309,458 serves diffusion /
image-diffusion model types). This is the TPU-first equivalent, not a port:

- **DiT denoiser** (patchify -> transformer with AdaLN-zero timestep/prompt
  conditioning -> unpatchify), all bf16 matmuls with static shapes so XLA
  tiles every layer onto the MXU.
- **DDIM sampler under lax.fori_loop**: the entire multi-step denoise is ONE
  compiled XLA program — no per-step host round-trips, which on a tunneled
  TPU would otherwise cost an RTT per step.
- Prompt conditioning hashes tokens into an embedding table (weights are
  random unless a checkpoint is loaded — serving capability and the compute
  path are what's exercised; checkpoints drop in via the same param pytree).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DiffusionConfig:
    image_size: int = 64
    patch_size: int = 8
    hidden: int = 256
    layers: int = 6
    heads: int = 4
    mlp_ratio: int = 4
    cond_vocab: int = 8192     # hashed prompt-token conditioning ids
    cond_len: int = 16         # conditioning tokens per prompt
    steps: int = 30            # DDIM steps
    dtype: Any = jnp.bfloat16

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3


def init_params(cfg: DiffusionConfig, seed: int = 0) -> Dict[str, Any]:
    rng = np.random.default_rng(seed)
    h = cfg.hidden

    def w(*shape, scale=None):
        s = scale if scale is not None else (1.0 / math.sqrt(shape[0]))
        return jnp.asarray(rng.standard_normal(shape) * s, cfg.dtype)

    layers = []
    for _ in range(cfg.layers):
        layers.append({
            "wqkv": w(h, 3 * h),
            "wo": w(h, h),
            "w_up": w(h, cfg.mlp_ratio * h),
            "w_down": w(cfg.mlp_ratio * h, h),
            # AdaLN conditioning projection. A TRAINED DiT zero-inits these
            # (AdaLN-zero) and learns them up; random init here keeps the
            # conditioning path live so prompt/timestep actually modulate
            # the random-weight model (a loaded checkpoint replaces all of
            # this via the same pytree)
            "ada": w(h, 6 * h, scale=0.02),
            "ada_b": jnp.zeros((6 * h,), cfg.dtype),
        })
    return {
        "patch_in": w(cfg.patch_dim, h),
        "pos": w(cfg.num_patches, h, scale=0.02),
        "cond_embed": w(cfg.cond_vocab, h, scale=0.02),
        "t_mlp1": w(h, h),
        "t_mlp2": w(h, h),
        "final_ada": w(h, 2 * h, scale=0.02),
        "final_out": w(h, cfg.patch_dim, scale=0.02),
        "layers": layers,
    }


def _timestep_embed(t: jax.Array, dim: int) -> jax.Array:
    """Sinusoidal [B] -> [B, dim] (standard DDPM embedding)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0) * jnp.arange(half) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)


def _ln(x: jax.Array) -> jax.Array:
    x = x.astype(jnp.float32)
    m = x.mean(-1, keepdims=True)
    v = x.var(-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + 1e-6)


def forward(
    params: Dict[str, Any], cfg: DiffusionConfig,
    x_t: jax.Array,        # [B, H, W, 3] noisy image, f32
    t: jax.Array,          # [B] int32 timestep
    cond_ids: jax.Array,   # [B, cond_len] int32 hashed prompt ids
) -> jax.Array:
    """Predict the noise eps for x_t. One fused transformer pass."""
    B = x_t.shape[0]
    p, n_side = cfg.patch_size, cfg.image_size // cfg.patch_size
    # patchify: [B, H, W, 3] -> [B, N, p*p*3]
    x = x_t.reshape(B, n_side, p, n_side, p, 3).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(B, cfg.num_patches, cfg.patch_dim).astype(cfg.dtype)
    h = x @ params["patch_in"] + params["pos"][None]

    # conditioning vector: mean prompt embedding + timestep MLP
    c = params["cond_embed"][cond_ids].mean(axis=1)              # [B, h]
    te = _timestep_embed(t, cfg.hidden).astype(cfg.dtype)
    c = c + jax.nn.silu(te @ params["t_mlp1"]) @ params["t_mlp2"]

    nh, hd = cfg.heads, cfg.hidden // cfg.heads
    for lp in params["layers"]:
        ada = (c @ lp["ada"] + lp["ada_b"]).astype(jnp.float32)
        sh1, sc1, g1, sh2, sc2, g2 = jnp.split(ada, 6, axis=-1)
        # attention with AdaLN-zero modulation
        u = (_ln(h) * (1 + sc1[:, None]) + sh1[:, None]).astype(cfg.dtype)
        qkv = u @ lp["wqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, -1, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, -1, nh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, -1, nh, hd).transpose(0, 2, 1, 3)
        s = (q.astype(jnp.float32) @ k.astype(jnp.float32).transpose(0, 1, 3, 2))
        s = s / math.sqrt(hd)
        a = jax.nn.softmax(s, axis=-1).astype(cfg.dtype)
        o = (a @ v).transpose(0, 2, 1, 3).reshape(B, -1, cfg.hidden)
        h = h + g1[:, None].astype(cfg.dtype) * (o @ lp["wo"])
        # MLP
        u = (_ln(h) * (1 + sc2[:, None]) + sh2[:, None]).astype(cfg.dtype)
        m = jax.nn.silu(u @ lp["w_up"]) @ lp["w_down"]
        h = h + g2[:, None].astype(cfg.dtype) * m

    ada = (c @ params["final_ada"]).astype(jnp.float32)
    sh, sc = jnp.split(ada, 2, axis=-1)
    u = (_ln(h) * (1 + sc[:, None]) + sh[:, None]).astype(cfg.dtype)
    out = u @ params["final_out"]                                # [B, N, pd]
    # unpatchify
    out = out.reshape(B, n_side, n_side, p, p, 3).transpose(0, 1, 3, 2, 4, 5)
    return out.reshape(B, cfg.image_size, cfg.image_size, 3).astype(jnp.float32)


def make_sampler(params: Dict[str, Any], cfg: DiffusionConfig):
    """Returns a jitted DDIM sampler: (key, cond_ids [B, L]) -> [B, H, W, 3]
    in [0, 1]. The whole denoise loop is one XLA program (lax.fori_loop)."""
    T = 1000
    betas = jnp.linspace(1e-4, 0.02, T, dtype=jnp.float32)
    alphas_bar = jnp.cumprod(1.0 - betas)
    # DDIM schedule: cfg.steps evenly spaced timesteps, high -> low
    ts = jnp.linspace(T - 1, 0, cfg.steps).astype(jnp.int32)

    def sample(key: jax.Array, cond_ids: jax.Array) -> jax.Array:
        B = cond_ids.shape[0]
        x = jax.random.normal(
            key, (B, cfg.image_size, cfg.image_size, 3), jnp.float32
        )

        def body(i, x):
            t = ts[i]
            t_next = jnp.where(i + 1 < cfg.steps, ts[jnp.minimum(i + 1, cfg.steps - 1)], -1)
            ab_t = alphas_bar[t]
            ab_next = jnp.where(t_next >= 0, alphas_bar[jnp.maximum(t_next, 0)], 1.0)
            eps = forward(params, cfg, x, jnp.full((B,), t, jnp.int32), cond_ids)
            x0 = (x - jnp.sqrt(1.0 - ab_t) * eps) / jnp.sqrt(ab_t)
            x0 = jnp.clip(x0, -3.0, 3.0)
            return jnp.sqrt(ab_next) * x0 + jnp.sqrt(1.0 - ab_next) * eps

        x = jax.lax.fori_loop(0, cfg.steps, body, x)
        return jnp.clip(x * 0.5 + 0.5, 0.0, 1.0)

    return jax.jit(sample)


def hash_prompt(prompt: str, cfg: DiffusionConfig) -> np.ndarray:
    """Prompt -> [cond_len] stable conditioning ids (FNV-1a over words;
    deterministic across processes — unlike hash())."""
    ids = np.zeros(cfg.cond_len, np.int32)
    words = (prompt.lower().split() or ["-"])[: cfg.cond_len]
    for i, word in enumerate(words):
        acc = 2166136261
        for b in word.encode():
            acc = ((acc ^ b) * 16777619) & 0xFFFFFFFF
        ids[i] = acc % cfg.cond_vocab
    return ids


def encode_png(img: np.ndarray) -> bytes:
    """[H, W, 3] float [0,1] or uint8 -> PNG bytes. Stdlib-only encoder
    (zlib + struct): zero-egress images ship no PIL."""
    import struct
    import zlib

    if img.dtype != np.uint8:
        img = (np.clip(img, 0.0, 1.0) * 255).astype(np.uint8)
    h, w, _ = img.shape
    raw = b"".join(b"\x00" + img[i].tobytes() for i in range(h))

    def chunk(tag: bytes, data: bytes) -> bytes:
        return (
            struct.pack(">I", len(data)) + tag + data
            + struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF)
        )

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)
    return (
        b"\x89PNG\r\n\x1a\n"
        + chunk(b"IHDR", ihdr)
        + chunk(b"IDAT", zlib.compress(raw, 6))
        + chunk(b"IEND", b"")
    )
