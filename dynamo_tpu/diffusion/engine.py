"""DiffusionEngine: serves image-generation requests (op == "image").

The worker half behind /v1/images/generations — the analog of the
reference's SGLang diffusion serving (components/src/dynamo/sglang/
main.py:309,458), engine-owned here: the sampler is a single jitted XLA
program per (batch, size) bucket.
"""

from __future__ import annotations

import asyncio
import base64
from typing import Any, AsyncIterator, Dict, Optional

import jax
import numpy as np

from ..llm.protocols.common import FINISH_STOP, BackendOutput, PreprocessedRequest
from ..runtime.engine import Context
from ..runtime.logging import get_logger
from .model import DiffusionConfig, encode_png, hash_prompt, init_params, make_sampler

log = get_logger("diffusion.engine")


class DiffusionEngine:
    """AsyncEngine serving op=image requests; register with
    ``register_llm(..., raw_token_stream=True)`` and model_type ["images"]."""

    def __init__(
        self,
        cfg: Optional[DiffusionConfig] = None,
        params: Optional[Dict[str, Any]] = None,
        seed: int = 0,
    ):
        self.cfg = cfg or DiffusionConfig()
        self.params = params if params is not None else init_params(self.cfg, seed)
        self._sampler = make_sampler(self.params, self.cfg)
        self._seed = seed
        # itertools.count: one atomic C call per draw, so concurrent renders
        # on executor threads never reuse a PRNG key
        import itertools

        self._req_counter = itertools.count(1)
        self.healthy = True

    def _render(self, prompt: str, n: int) -> list:
        cond = np.tile(hash_prompt(prompt, self.cfg), (n, 1))
        key = jax.random.PRNGKey(self._seed + next(self._req_counter))
        imgs = np.asarray(self._sampler(key, cond))
        return [
            base64.b64encode(encode_png(imgs[i])).decode() for i in range(n)
        ]

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[Dict[str, Any]]:
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_obj(request)
        )
        ann = req.annotations or {}
        if ann.get("op") != "image":
            yield BackendOutput(
                finish_reason="error",
                annotations={"error": "diffusion engine serves op=image only"},
            ).to_obj()
            return
        prompt = str(ann.get("prompt", ""))
        n = max(1, int(ann.get("n", 1)))
        # size is advisory: the compiled sampler has a fixed resolution; the
        # reference's workers likewise serve the deployed model's native size
        images = await asyncio.get_running_loop().run_in_executor(
            None, self._render, prompt, n
        )
        if context.is_stopped():
            return
        yield BackendOutput(
            finish_reason=FINISH_STOP,
            annotations={"images": images, "input_tokens": 0},
        ).to_obj()
