"""Image diffusion serving: DiT denoiser + DDIM sampler + worker main."""

from .engine import DiffusionEngine
from .model import DiffusionConfig, encode_png, hash_prompt, init_params, make_sampler

__all__ = [
    "DiffusionConfig",
    "DiffusionEngine",
    "encode_png",
    "hash_prompt",
    "init_params",
    "make_sampler",
]
