"""python -m dynamo_tpu.diffusion — image-generation worker.

Registers a DiffusionEngine under model_type ["images"] so the frontend's
/v1/images/generations routes to it (reference: SGLang diffusion serving,
components/src/dynamo/sglang/main.py:309,458).
"""

import argparse
import asyncio
import os
import signal

from dynamo_tpu.llm import ModelDeploymentCard, register_llm
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig, init_logging


def parse_args():
    p = argparse.ArgumentParser("dynamo_tpu.diffusion")
    p.add_argument("--model", default="image-model", help="served model name")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="image_backend")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--store", default=None)
    p.add_argument("--store-path", default=None)
    p.add_argument("--event-plane", default=None)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--patch-size", type=int, default=8)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=6)
    p.add_argument("--steps", type=int, default=30, help="DDIM steps")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None, choices=["cpu", "tpu", "axon"],
                   help="force the JAX backend (axon pins itself even under "
                        "JAX_PLATFORMS=cpu)")
    return p.parse_args()


async def main() -> None:
    args = parse_args()
    plat = args.platform or os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat.split(",")[0])
    init_logging()
    from dynamo_tpu.diffusion.engine import DiffusionEngine
    from dynamo_tpu.diffusion.model import DiffusionConfig

    cfg = RuntimeConfig.from_env(
        store=args.store, store_path=args.store_path, event_plane=args.event_plane
    )
    runtime = await DistributedRuntime(cfg).start()
    dcfg = DiffusionConfig(
        image_size=args.image_size, patch_size=args.patch_size,
        hidden=args.hidden, layers=args.layers, steps=args.steps,
    )
    engine = DiffusionEngine(dcfg, seed=args.seed)
    card = ModelDeploymentCard(
        name=args.model,
        namespace=args.namespace,
        component=args.component,
        endpoint=args.endpoint,
        model_type=["images"],
        tokenizer="byte",
    )
    served = await register_llm(runtime, engine, card, raw_token_stream=True)
    print(f"DIFFUSION_READY {args.model} {args.image_size}x{args.image_size}",
          flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await served.stop()
    await runtime.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
