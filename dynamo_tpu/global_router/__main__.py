"""python -m dynamo_tpu.global_router — hierarchical routing service.

Registers as a worker for --model in --namespace (the frontend can't tell),
and forwards each request to a pool namespace chosen by the SLA grid in
--config (reference components/src/dynamo/global_router/__main__.py).
"""

import argparse
import asyncio
import signal

from dynamo_tpu.global_router import GlobalRouterConfig, GlobalRouterHandler
from dynamo_tpu.llm import ModelDeploymentCard, register_llm
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig, init_logging


def parse_args():
    p = argparse.ArgumentParser("dynamo_tpu.global_router")
    p.add_argument("--config", required=True, help="pool + grid JSON")
    p.add_argument("--model", required=True)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="global_router")
    p.add_argument("--store", default=None)
    p.add_argument("--store-path", default=None)
    p.add_argument("--event-plane", default=None)
    p.add_argument("--block-size", type=int, default=16)
    return p.parse_args()


async def main() -> None:
    args = parse_args()
    init_logging()
    cfg = RuntimeConfig.from_env(
        store=args.store, store_path=args.store_path, event_plane=args.event_plane
    )
    runtime = await DistributedRuntime(cfg).start()
    handler = GlobalRouterHandler(runtime, GlobalRouterConfig.load(args.config))
    card = ModelDeploymentCard(
        name=args.model, namespace=args.namespace, component=args.component,
        tokenizer="byte", kv_block_size=args.block_size,
    )
    await register_llm(runtime, handler, card)
    print("GLOBAL_ROUTER_READY", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await handler.stop()
    await runtime.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
