"""Grid-based pool selection for hierarchical routing.

Analog of the reference's global-router pool selection
(components/src/dynamo/global_router/pool_selection.py): a config maps
(ISL, TTFT-target) onto a prefill pool and (context_length, ITL-target) onto
a decode pool via 2-D lookup grids, so SLA-differentiated traffic lands on
pools provisioned for it (the hierarchical-planner story,
examples/hierarchical_planner/global_router_config.json).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional


def _clamp(value: float, resolution: int) -> int:
    return max(0, min(int(value), resolution - 1))


@dataclasses.dataclass
class PrefillPoolSelectionStrategy:
    """(ISL, TTFT-target-ms) -> prefill pool index."""

    ttft_min: float
    ttft_max: float
    ttft_resolution: int
    isl_min: int
    isl_max: int
    isl_resolution: int
    prefill_pool_mapping: List[List[int]]  # [isl_idx][ttft_idx]

    def select_pool(self, isl: int, ttft_target: Optional[float] = None) -> int:
        if ttft_target is None:
            ttft_target = (self.ttft_min + self.ttft_max) / 2
        isl_step = (self.isl_max - self.isl_min) / self.isl_resolution
        ttft_step = (self.ttft_max - self.ttft_min) / self.ttft_resolution
        isl_idx = _clamp((isl - self.isl_min) / isl_step, self.isl_resolution)
        ttft_idx = _clamp((ttft_target - self.ttft_min) / ttft_step, self.ttft_resolution)
        return self.prefill_pool_mapping[isl_idx][ttft_idx]


@dataclasses.dataclass
class DecodePoolSelectionStrategy:
    """(context_length, ITL-target-ms) -> decode pool index."""

    itl_min: float
    itl_max: float
    itl_resolution: int
    context_length_min: int
    context_length_max: int
    context_length_resolution: int
    decode_pool_mapping: List[List[int]]  # [ctx_idx][itl_idx]

    def select_pool(self, context_length: int, itl_target: Optional[float] = None) -> int:
        if itl_target is None:
            itl_target = (self.itl_min + self.itl_max) / 2
        ctx_step = (
            self.context_length_max - self.context_length_min
        ) / self.context_length_resolution
        itl_step = (self.itl_max - self.itl_min) / self.itl_resolution
        ctx_idx = _clamp(
            (context_length - self.context_length_min) / ctx_step,
            self.context_length_resolution,
        )
        itl_idx = _clamp((itl_target - self.itl_min) / itl_step, self.itl_resolution)
        return self.decode_pool_mapping[ctx_idx][itl_idx]


@dataclasses.dataclass
class PoolSpec:
    """One pool: a namespace holding its own workers (+ local router)."""

    namespace: str
    component: str = "backend"
    endpoint: str = "generate"


@dataclasses.dataclass
class GlobalRouterConfig:
    prefill_pools: List[PoolSpec]
    decode_pools: List[PoolSpec]
    prefill_strategy: Optional[PrefillPoolSelectionStrategy]
    decode_strategy: Optional[DecodePoolSelectionStrategy]
    default_ttft_ms: Optional[float] = None
    default_itl_ms: Optional[float] = None

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "GlobalRouterConfig":
        def pools(key: str) -> List[PoolSpec]:
            out = []
            for p in obj.get(key, []):
                if isinstance(p, str):
                    out.append(PoolSpec(namespace=p))
                else:
                    out.append(PoolSpec(**p))
            return out

        ps = obj.get("prefill_selection")
        ds = obj.get("decode_selection")
        return cls(
            prefill_pools=pools("prefill_pools"),
            decode_pools=pools("decode_pools"),
            prefill_strategy=(
                PrefillPoolSelectionStrategy(**ps) if ps else None
            ),
            decode_strategy=(
                DecodePoolSelectionStrategy(**ds) if ds else None
            ),
            default_ttft_ms=obj.get("default_ttft_ms"),
            default_itl_ms=obj.get("default_itl_ms"),
        )

    @classmethod
    def load(cls, path: str) -> "GlobalRouterConfig":
        with open(path) as f:
            return cls.from_obj(json.load(f))
