"""GlobalRouterHandler: an engine-shaped forwarder over pools of workers.

Analog of the reference's GlobalRouterHandler
(components/src/dynamo/global_router/handler.py): registers like a worker
(the frontend can't tell), but ``generate`` picks a pool by the SLA grid and
forwards the request to that pool's own namespace — where a local KV router /
worker set handles it. Two-level routing: global (SLA/pool) then local
(KV-overlap/load).

SLA targets ride the request-plane ``sla`` annotation the frontend stamps
(runtime/slo.py ``SlaSpec.to_annotation``: ``ttft_target_s`` /
``itl_target_s``, seconds) — the same contract the SLO ledger and the
worker read, converted to the strategy grid's milliseconds here. The
reference reads equivalent targets from nvext."""

from __future__ import annotations

from typing import Any, AsyncIterator, Dict

from ..llm.protocols.common import PreprocessedRequest
from ..runtime.component import Client, RouterMode
from ..runtime.engine import Context
from ..runtime.logging import get_logger
from .pool_selection import GlobalRouterConfig, PoolSpec

log = get_logger("global_router")


class GlobalRouterHandler:
    def __init__(self, runtime, config: GlobalRouterConfig):
        self.runtime = runtime
        self.config = config
        self._clients: Dict[str, Client] = {}
        # observability: how many requests each pool received
        self.pool_counts: Dict[str, int] = {}

    async def _client(self, pool: PoolSpec) -> Client:
        key = f"{pool.namespace}/{pool.component}/{pool.endpoint}"
        c = self._clients.get(key)
        if c is None:
            c = await (
                self.runtime.namespace(pool.namespace)
                .component(pool.component)
                .endpoint(pool.endpoint)
                .client(RouterMode.ROUND_ROBIN)
            )
            self._clients[key] = c
        return c

    def _pick_pool(self, req: PreprocessedRequest) -> PoolSpec:
        isl = len(req.token_ids)
        ann = req.annotations or {}
        # the frontend's sla annotation carries targets in SECONDS; the
        # pool-selection grid is calibrated in milliseconds
        sla = ann.get("sla") or {}
        if ann.get("disagg") == "prefill" and self.config.prefill_pools:
            ttft = (
                float(sla.get("ttft_target_s") or 0.0) * 1e3
                or self.config.default_ttft_ms
            )
            idx = (
                self.config.prefill_strategy.select_pool(isl, ttft)
                if self.config.prefill_strategy else 0
            )
            pools = self.config.prefill_pools
        else:
            itl = (
                float(sla.get("itl_target_s") or 0.0) * 1e3
                or self.config.default_itl_ms
            )
            ctx = isl + (req.stop.max_tokens or 0)
            idx = (
                self.config.decode_strategy.select_pool(ctx, itl)
                if self.config.decode_strategy else 0
            )
            pools = self.config.decode_pools
        if not pools:
            raise ValueError(
                "global router config defines no pool for this request kind "
                "(decode_pools is empty)"
            )
        return pools[max(0, min(idx, len(pools) - 1))]

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[Any]:
        req = (
            request if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_obj(request)
        )
        pool = self._pick_pool(req)
        self.pool_counts[pool.namespace] = self.pool_counts.get(pool.namespace, 0) + 1
        log.debug(
            "global route %s (isl=%d) -> pool %s",
            req.request_id[:8], len(req.token_ids), pool.namespace,
        )
        client = await self._client(pool)
        await client.wait_for_instances(1, timeout=10.0)
        stream = await client.generate(req.to_obj(), context=context)
        async for item in stream:
            yield item

    async def stop(self) -> None:
        for c in self._clients.values():
            await c.stop()
