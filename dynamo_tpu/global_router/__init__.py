"""Hierarchical 2-level routing: SLA-grid pool selection on top of per-pool
local KV routers (reference components/src/dynamo/global_router)."""

from .handler import GlobalRouterHandler
from .pool_selection import (
    DecodePoolSelectionStrategy,
    GlobalRouterConfig,
    PoolSpec,
    PrefillPoolSelectionStrategy,
)

__all__ = [
    "GlobalRouterHandler",
    "GlobalRouterConfig",
    "PoolSpec",
    "PrefillPoolSelectionStrategy",
    "DecodePoolSelectionStrategy",
]
