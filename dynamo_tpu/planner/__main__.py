"""python -m dynamo_tpu.planner — SLA autoscaler service.

Analog of `python -m dynamo.planner.planner_sla` (components/src/dynamo/
planner/planner_sla.py:36-55): observes worker metrics over the event plane
and writes target replica counts through the virtual connector (an external
launcher or operator converges on them), or spawns local workers directly
with --connector subprocess (fleet-in-a-box).
"""

import argparse
import asyncio
import signal
import sys

from dynamo_tpu.planner.connectors import SubprocessConnector, VirtualConnector
from dynamo_tpu.planner.core import DisaggPlanner, PerfInterpolator, PlannerConfig, SlaTargets
from dynamo_tpu.planner.metrics_source import EventPlaneMetricsSource
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig, init_logging


def parse_args():
    p = argparse.ArgumentParser("dynamo_tpu.planner")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--store", default=None)
    p.add_argument("--store-path", default=None)
    p.add_argument("--event-plane", default=None)
    p.add_argument("--prefill-component", default="backend_prefill")
    p.add_argument("--decode-component", default="backend")
    p.add_argument("--adjustment-interval", type=float, default=10.0)
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=8)
    p.add_argument("--total-budget", type=int, default=0, help="chip budget across pools")
    p.add_argument("--ttft-sla", type=float, default=0.5)
    p.add_argument("--itl-sla", type=float, default=0.05)
    p.add_argument("--predictor", default="holt",
                   choices=["constant", "moving-average", "holt", "arima"])
    p.add_argument("--profile", default=None,
                   help="profile JSON from python -m dynamo_tpu.profiler; "
                   "scales on MEASURED capacities instead of defaults")
    p.add_argument("--connector", default="virtual", choices=["virtual", "subprocess"])
    p.add_argument("--worker-cmd", default=None,
                   help="subprocess connector: shell command template with "
                        "{component} placeholder")
    return p.parse_args()


async def main() -> None:
    args = parse_args()
    init_logging()
    cfg = RuntimeConfig.from_env(
        store=args.store, store_path=args.store_path, event_plane=args.event_plane
    )
    runtime = await DistributedRuntime(cfg).start()

    if args.connector == "subprocess":
        if not args.worker_cmd:
            print("--worker-cmd required with --connector subprocess", file=sys.stderr)
            sys.exit(2)

        def make_cmd(component, index):
            return args.worker_cmd.format(component=component).split()

        connector = SubprocessConnector(make_cmd)
    else:
        connector = VirtualConnector(runtime.store, args.namespace)

    planner = DisaggPlanner(
        connector,
        PlannerConfig(
            adjustment_interval_s=args.adjustment_interval,
            predictor=args.predictor,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            total_budget=args.total_budget,
            sla=SlaTargets(ttft_s=args.ttft_sla, itl_s=args.itl_sla),
        ),
        PerfInterpolator.from_profile(args.profile)
        if args.profile
        else PerfInterpolator(),
        prefill_component=args.prefill_component,
        decode_component=args.decode_component,
    )
    source = await EventPlaneMetricsSource(
        runtime.event_plane, args.namespace,
        [args.prefill_component, args.decode_component],
    ).start()
    planner.start(source.snapshot)
    print("PLANNER_READY", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    planner.stop()
    source.stop()
    if isinstance(connector, SubprocessConnector):
        connector.shutdown()
    await runtime.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
