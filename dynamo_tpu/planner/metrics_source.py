"""Demand observation for the planner.

The reference scrapes Prometheus (components/src/dynamo/planner/utils/
prometheus.py); here the primary source is the event plane the workers
already publish to (WorkerMetrics: waiting queue, active blocks), plus an
optional Prometheus scrape of the frontend for request/token rates.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional

import msgpack

from ..kv_router.protocols import WorkerMetrics, WorkerWithDpRank
from ..kv_router.publisher import metrics_topic
from ..runtime.event_plane.base import EventPlane
from ..runtime.logging import get_logger
from .core import LoadSnapshot

log = get_logger("planner.metrics")


class EventPlaneMetricsSource:
    """Aggregates worker metrics into LoadSnapshots."""

    def __init__(self, plane: EventPlane, namespace: str, components: list):
        self.plane = plane
        self.namespace = namespace
        self.components = components
        self._latest: Dict[WorkerWithDpRank, WorkerMetrics] = {}
        self._tasks = []
        self._subs = []
        # cumulative token counters for rate estimation
        self._last_rate_calc = time.time()
        self._decode_tokens_window = 0
        self._prefill_tokens_window = 0

    async def start(self) -> "EventPlaneMetricsSource":
        for comp in self.components:
            sub = await self.plane.subscribe(metrics_topic(self.namespace, comp))
            self._subs.append(sub)
            self._tasks.append(asyncio.create_task(self._consume(sub)))
        return self

    async def _consume(self, sub) -> None:
        async for _topic, payload in sub:
            try:
                m = WorkerMetrics.from_obj(msgpack.unpackb(payload, raw=False))
                self._latest[m.worker] = m
            except Exception:
                log.exception("bad worker metrics")

    def record_request(self, prefill_tokens: int) -> None:
        self._prefill_tokens_window += prefill_tokens

    def record_decode_tokens(self, n: int) -> None:
        self._decode_tokens_window += n

    def snapshot(self) -> LoadSnapshot:
        now = time.time()
        dt = max(now - self._last_rate_calc, 1e-6)
        fresh = [m for m in self._latest.values() if now - m.ts < 30.0]
        snap = LoadSnapshot(
            prefill_tokens_rate=self._prefill_tokens_window / dt,
            decode_tokens_rate=self._decode_tokens_window / dt,
            num_waiting=sum(m.num_requests_waiting for m in fresh),
            active_seqs=sum(m.active_decode_blocks for m in fresh),
        )
        self._last_rate_calc = now
        self._prefill_tokens_window = 0
        self._decode_tokens_window = 0
        return snap

    def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for s in self._subs:
            s.cancel()
