"""Demand observation for the planner.

The reference scrapes Prometheus (components/src/dynamo/planner/utils/
prometheus.py); here the sources are the event plane the workers already
publish to (WorkerMetrics: waiting queue, active sequences/blocks) plus a
frontend stats topic (FrontendStatsPublisher below — per-request prompt/
completion token counts and measured TTFT/ITL, the inputs to both the demand
predictors and the correction factors).
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Dict

import msgpack

from ..kv_router.protocols import WorkerMetrics, WorkerWithDpRank
from ..kv_router.publisher import metrics_topic
from ..runtime.event_plane.base import EventPlane
from ..runtime.logging import get_logger
from .core import LoadSnapshot

log = get_logger("planner.metrics")


def frontend_stats_topic(namespace: str) -> str:
    return f"v1.frontend_stats.{namespace}"


class FrontendStatsPublisher:
    """Frontend side: publish one compact stats event per completed request.

    Wired as the HttpService ``stats_hook`` (llm/http/service.py _observed):
    the HTTP layer already measures TTFT/ITL per stream for its Prometheus
    histograms; this fans the same numbers out to the planner."""

    def __init__(self, plane: EventPlane, namespace: str = "dynamo",
                 clock: Callable[[], float] = time.time):
        self.plane = plane
        self.topic = frontend_stats_topic(namespace)
        # injectable clock so simulated frontends stamp stats on the sim
        # timeline (sim/clock.py); live frontends keep wall time
        self._clock = clock
        # strong refs: the loop only weak-refs tasks, and a GC'd publish
        # task silently drops the stats event
        self._inflight: set = set()

    def on_request(self, prompt_tokens: int, completion_tokens: int,
                   ttft_s: float, itl_s: float, sla_class: str = "",
                   ttft_target_s: float = 0.0,
                   itl_target_s: float = 0.0,
                   sla_met: "bool | None" = None) -> None:
        obj = {
            "pt": int(prompt_tokens), "ct": int(completion_tokens),
            "ttft": float(ttft_s), "itl": float(itl_s), "ts": self._clock(),
        }
        if sla_class:
            # class-labeled latency record (runtime/slo.py): the planner
            # derives per-class attainment from these — targets ride along
            # so the aggregator needs no SLA-class table of its own, and
            # the publisher's accountant verdict (when it has one) wins so
            # deadline-bound classes can't drift from /debug/slo
            obj["sla"] = str(sla_class)
            obj["tt"] = float(ttft_target_s)
            obj["it"] = float(itl_target_s)
            if sla_met is not None:
                obj["ok"] = bool(sla_met)
        payload = msgpack.packb(obj, use_bin_type=True)

        async def _send() -> None:
            try:
                await self.plane.publish(self.topic, payload)
            except Exception:
                log.exception("frontend stats publish failed")

        try:
            task = asyncio.get_running_loop().create_task(_send())
            self._inflight.add(task)
            task.add_done_callback(self._inflight.discard)
        except RuntimeError:
            pass  # no loop (teardown): stats are best-effort


class EventPlaneMetricsSource:
    """Aggregates worker metrics + frontend stats into LoadSnapshots."""

    def __init__(self, plane: EventPlane, namespace: str, components: list,
                 clock: Callable[[], float] = time.time):
        self.plane = plane
        self.namespace = namespace
        self.components = components
        # rate windows divide by elapsed *clock* seconds: under the fleet
        # simulator this must be the virtual clock or the planner would see
        # simulated arrivals over wall windows and misread rates by the
        # wall/virtual ratio (ISSUE 6 satellite)
        self._clock = clock
        self._latest: Dict[WorkerWithDpRank, WorkerMetrics] = {}
        self._tasks = []
        self._subs = []
        # per-window accumulators for rate/latency estimation
        self._last_rate_calc = self._clock()
        self._decode_tokens_window = 0
        self._prefill_tokens_window = 0
        self._requests_window = 0
        self._ttft_window: list = []
        self._itl_window: list = []
        # sla_class -> [met_count, total] over the window (met = ttft and,
        # when observed, itl within the record's own targets)
        self._class_window: Dict[str, list] = {}
        # worker_id -> reclaim deadline timestamp (this source's clock):
        # announced planned deaths ride LoadSnapshot.announced_reclaims
        # until their deadline passes (the worker is then gone and the
        # regular replica accounting takes over)
        self._reclaims: Dict[int, float] = {}

    async def start(self) -> "EventPlaneMetricsSource":
        for comp in self.components:
            sub = await self.plane.subscribe(metrics_topic(self.namespace, comp))
            self._subs.append(sub)
            self._tasks.append(asyncio.create_task(self._consume(sub)))
        stats_sub = await self.plane.subscribe(frontend_stats_topic(self.namespace))
        self._subs.append(stats_sub)
        self._tasks.append(asyncio.create_task(self._consume_stats(stats_sub)))
        return self

    async def _consume(self, sub) -> None:
        async for _topic, payload in sub:
            try:
                m = WorkerMetrics.from_obj(msgpack.unpackb(payload, raw=False))
                self._latest[m.worker] = m
            except Exception:
                log.exception("bad worker metrics")

    async def _consume_stats(self, sub) -> None:
        async for _topic, payload in sub:
            try:
                st = msgpack.unpackb(payload, raw=False)
                self.record_request(int(st.get("pt", 0)))
                self.record_decode_tokens(int(st.get("ct", 0)))
                self.record_latency(
                    ttft_s=float(st.get("ttft", 0.0)),
                    itl_s=float(st.get("itl", 0.0)),
                )
                if st.get("sla"):
                    self.record_class_outcome(
                        str(st["sla"]),
                        ttft_s=float(st.get("ttft", 0.0)),
                        ttft_target_s=float(st.get("tt", 0.0)),
                        itl_s=float(st.get("itl", 0.0)),
                        itl_target_s=float(st.get("it", 0.0)),
                        met=(bool(st["ok"]) if "ok" in st else None),
                    )
            except Exception:
                log.exception("bad frontend stats")

    def record_request(self, prefill_tokens: int) -> None:
        self._prefill_tokens_window += prefill_tokens
        self._requests_window += 1

    def record_decode_tokens(self, n: int) -> None:
        self._decode_tokens_window += n

    def record_latency(self, ttft_s: float = 0.0, itl_s: float = 0.0) -> None:
        """Per-stream measured latencies, averaged per window into the
        snapshot so the planner's correction factors track reality."""
        if ttft_s > 0:
            self._ttft_window.append(ttft_s)
        if itl_s > 0:
            self._itl_window.append(itl_s)

    def note_reclaim(self, worker_id: int, deadline_ts: float) -> None:
        """A worker announced a planned reclaim (drain notice) with this
        absolute deadline on the source's clock. Idempotent per worker; a
        later call moves the deadline."""
        self._reclaims[worker_id] = deadline_ts

    def clear_reclaim(self, worker_id: int) -> None:
        """The reclaim resolved early (worker died, or the notice was
        cancelled)."""
        self._reclaims.pop(worker_id, None)

    def record_class_outcome(self, sla_class: str, ttft_s: float,
                             ttft_target_s: float, itl_s: float,
                             itl_target_s: float,
                             met: "bool | None" = None) -> None:
        """One class-labeled request outcome; targets come from the record
        itself (per-model overrides make one class mean different numbers
        on different models). An explicit ``met`` (the publisher-side
        SloAccountant verdict, which also folds in deadlines) overrides
        the local derivation."""
        if met is None:
            met = (
                (ttft_target_s <= 0.0 or ttft_s <= ttft_target_s)
                and (itl_target_s <= 0.0 or itl_s <= 0.0
                     or itl_s <= itl_target_s)
            )
        cell = self._class_window.setdefault(sla_class, [0, 0])
        cell[0] += 1 if met else 0
        cell[1] += 1

    def _count_reclaims(self, now: float) -> int:
        """Live announced reclaims; expired ones are pruned (their workers
        are dead — double-counting them against the replica count would
        hold phantom spares forever)."""
        for wid, deadline in list(self._reclaims.items()):
            if deadline <= now:
                del self._reclaims[wid]
        return len(self._reclaims)

    def snapshot(self) -> LoadSnapshot:
        now = self._clock()
        dt = max(now - self._last_rate_calc, 1e-6)
        fresh = [m for m in self._latest.values() if now - m.ts < 30.0]
        n_req = self._requests_window
        snap = LoadSnapshot(
            request_rate=n_req / dt,
            prefill_tokens_rate=self._prefill_tokens_window / dt,
            decode_tokens_rate=self._decode_tokens_window / dt,
            # correction factors compare measured latency against the
            # profile at THIS window's operating point: mean prompt length
            # and live decode concurrency
            avg_isl=(self._prefill_tokens_window / n_req) if n_req else 0.0,
            num_waiting=sum(m.num_requests_waiting for m in fresh),
            active_seqs=sum(m.num_requests_active for m in fresh),
            measured_ttft=(
                sum(self._ttft_window) / len(self._ttft_window)
                if self._ttft_window else 0.0
            ),
            measured_itl=(
                sum(self._itl_window) / len(self._itl_window)
                if self._itl_window else 0.0
            ),
            class_attainment={
                cls: round(met / max(total, 1), 4)
                for cls, (met, total) in sorted(self._class_window.items())
            },
            announced_reclaims=self._count_reclaims(now),
        )
        self._last_rate_calc = now
        self._prefill_tokens_window = 0
        self._decode_tokens_window = 0
        self._requests_window = 0
        self._ttft_window = []
        self._itl_window = []
        self._class_window = {}
        return snap

    def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for s in self._subs:
            s.cancel()
