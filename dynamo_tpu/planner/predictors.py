"""Load predictors for the SLA planner.

Analog of the reference's predictor zoo (components/src/dynamo/planner/utils/
load_predictor.py:28,97,110 — constant / ARIMA / Prophet). statsmodels is not
in this image, so the trend-aware predictor is Holt's double exponential
smoothing implemented directly — same role as the ARIMA default: smooth the
recent window, extrapolate one planning interval ahead.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Optional


class ConstantPredictor:
    """Predict the last observation (reference: load_predictor.py:97)."""

    def __init__(self, window: int = 1):
        self._last: float = 0.0

    def observe(self, value: float) -> None:
        self._last = value

    def predict(self, steps_ahead: int = 1) -> float:
        return self._last


class MovingAveragePredictor:
    def __init__(self, window: int = 6):
        self._buf: Deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._buf.append(value)

    def predict(self, steps_ahead: int = 1) -> float:
        if not self._buf:
            return 0.0
        return sum(self._buf) / len(self._buf)


class HoltPredictor:
    """Double exponential smoothing: level + trend, extrapolated ahead."""

    def __init__(self, alpha: float = 0.5, beta: float = 0.3):
        self.alpha = alpha
        self.beta = beta
        self._level: Optional[float] = None
        self._trend: float = 0.0

    def observe(self, value: float) -> None:
        if self._level is None:
            self._level = value
            self._trend = 0.0
            return
        prev_level = self._level
        self._level = self.alpha * value + (1 - self.alpha) * (self._level + self._trend)
        self._trend = self.beta * (self._level - prev_level) + (1 - self.beta) * self._trend

    def predict(self, steps_ahead: int = 1) -> float:
        if self._level is None:
            return 0.0
        return max(0.0, self._level + steps_ahead * self._trend)


PREDICTORS = {
    "constant": ConstantPredictor,
    "moving-average": MovingAveragePredictor,
    "holt": HoltPredictor,
    "arima": HoltPredictor,  # alias: the trend-aware default
}


def make_predictor(kind: str):
    try:
        return PREDICTORS[kind]()
    except KeyError:
        raise ValueError(f"unknown predictor {kind!r}; options: {sorted(PREDICTORS)}")
