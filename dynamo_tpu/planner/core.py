"""SLA-driven autoscaling planner for prefill/decode pools.

Analog of the reference's planner core (components/src/dynamo/planner/
planner_core.py: BasePlanner :258, observe_metrics :511, plan_adjustment :631,
_apply_scaling :691; PrefillPlanner :764, DecodePlanner :801, DisaggPlanner
:859): observe load, predict one interval ahead, convert predicted load into
required replicas through a per-worker capacity model (the profiler
interpolation analog), clamp to budgets, and apply through a connector.
"""

from __future__ import annotations

import asyncio
import dataclasses
import math
import time
from typing import Dict, List, Optional

from ..runtime.logging import get_logger
from .connectors import Connector
from .predictors import make_predictor

log = get_logger("planner")


@dataclasses.dataclass
class PerfInterpolator:
    """Per-worker capacity model from profiled sweeps.

    Analog of the reference's perf_interpolation.py over profiler NPZ sweeps:
    given (isl, osl) predicts a single worker's sustainable rates. Defaults
    are linear models; calibrate with measured points via fit_*()."""

    # prefill: tokens/sec one worker sustains at a given ISL
    prefill_tokens_per_s: float = 20000.0
    # decode: tokens/sec/worker at the target ITL
    decode_tokens_per_s: float = 2000.0
    # profiled (isl, tokens_per_s) points for interpolation
    prefill_points: List = dataclasses.field(default_factory=list)
    decode_points: List = dataclasses.field(default_factory=list)

    def prefill_capacity(self, isl: float) -> float:
        return self._interp(self.prefill_points, isl, self.prefill_tokens_per_s)

    def decode_capacity(self, active_seqs: float) -> float:
        return self._interp(self.decode_points, active_seqs, self.decode_tokens_per_s)

    # -- expected latencies (the correction-factor reference curves) --------
    # The profiled points already encode them: a prefill point is
    # (isl, isl/ttft), a decode point is (concurrency, aggregate rate) so
    # per-stream ITL = concurrency / rate. Mirrors the reference's
    # interpolate_ttft / interpolate_itl (perf_interpolation.py).
    def expected_ttft(self, isl: float) -> float:
        return max(isl, 1.0) / max(self.prefill_capacity(isl), 1e-9)

    def expected_itl(self, active_seqs: float) -> float:
        return max(active_seqs, 1.0) / max(self.decode_capacity(active_seqs), 1e-9)

    # -- calibration from measured sweeps (profiler/sweep.py) ----------------
    def fit_prefill(self, points) -> "PerfInterpolator":
        self.prefill_points = [tuple(p) for p in points]
        if self.prefill_points:
            self.prefill_tokens_per_s = self.prefill_points[-1][1]
        return self

    def fit_decode(self, points) -> "PerfInterpolator":
        self.decode_points = [tuple(p) for p in points]
        if self.decode_points:
            # the planner divides aggregate load by one worker's sustainable
            # rate: use the highest measured concurrency's throughput
            self.decode_tokens_per_s = max(r for _, r in self.decode_points)
        return self

    @classmethod
    def from_profile(cls, profile) -> "PerfInterpolator":
        """profile: profiler.ProfileResult, its dict form, or a JSON path."""
        if isinstance(profile, str):
            import json

            with open(profile) as f:
                profile = json.load(f)
        if not isinstance(profile, dict):
            profile = profile.to_obj()
        interp = cls()
        interp.fit_prefill(profile.get("prefill_points", []))
        interp.fit_decode(profile.get("decode_points", []))
        return interp

    @staticmethod
    def _interp(points: List, x: float, default: float) -> float:
        if not points:
            return default
        pts = sorted(points)
        if x <= pts[0][0]:
            return pts[0][1]
        if x >= pts[-1][0]:
            return pts[-1][1]
        for (x0, y0), (x1, y1) in zip(pts, pts[1:]):
            if x0 <= x <= x1:
                t = (x - x0) / (x1 - x0) if x1 > x0 else 0.0
                return y0 + t * (y1 - y0)
        return default


@dataclasses.dataclass
class SlaTargets:
    ttft_s: float = 0.5
    itl_s: float = 0.05


@dataclasses.dataclass
class PlannerConfig:
    adjustment_interval_s: float = 10.0
    predictor: str = "holt"
    min_replicas: int = 1
    max_replicas: int = 8
    # total accelerator budget across pools (reference GPU budgets,
    # planner_core.py:132-256); 0 = unbounded
    total_budget: int = 0
    scale_down_headroom: float = 0.8   # only shrink when utilization < this
    # bound on how much of the pool one tick may remove (1.0 = unbounded,
    # the historical behavior). Without it a demand trough collapses the
    # whole fleet in one decision and the next ramp pays full boot latency
    # for every worker — the 100->1->rebuild oscillation the fleet
    # simulator's diurnal no-oscillation invariant caught (sim/scenarios.py)
    max_scale_down_frac: float = 1.0
    # EMA weight kept on the previous correction factor each window (0 =
    # jump straight to the latest measurement)
    correction_smoothing: float = 0.5
    # queue-pressure floor: num_waiting/divisor extra replicas when work is
    # queued (0 disables). Justified by the burst-recovery loadgen
    # validation (profiler/loadgen.py planner_sim; tests/test_loadgen.py
    # pins that recovery with the bump beats without under a step burst).
    queue_bump_divisor: float = 4.0
    sla: SlaTargets = dataclasses.field(default_factory=SlaTargets)


@dataclasses.dataclass
class LoadSnapshot:
    """One observation window of demand."""

    request_rate: float = 0.0          # requests/s
    prefill_tokens_rate: float = 0.0   # prompt tokens/s arriving
    decode_tokens_rate: float = 0.0    # output tokens/s being generated
    avg_isl: float = 0.0
    num_waiting: int = 0
    active_seqs: int = 0
    # measured serving latencies over the window (0 = not observed): feed
    # the correction factors (reference planner_core.py:766-820)
    measured_ttft: float = 0.0
    measured_itl: float = 0.0
    # per-SLA-class attainment over the window (runtime/slo.py classes;
    # empty when no class-labeled stats arrived): the signal that lets the
    # planner scale against promises instead of raw load
    class_attainment: Dict[str, float] = dataclasses.field(default_factory=dict)
    # workers that announced a planned reclaim (engine/drain.py) whose
    # deadline has not passed: forecast signal — each one is capacity that
    # WILL vanish, so the planner pre-warms its replacement before the kill
    # instead of reacting to the load spike after it
    announced_reclaims: int = 0
    ts: float = dataclasses.field(default_factory=time.time)


class PoolPlanner:
    """Scales one worker pool (prefill or decode) against its capacity model."""

    def __init__(
        self,
        name: str,
        component: str,
        connector: Connector,
        config: PlannerConfig,
        capacity_fn,
    ):
        self.name = name
        self.component = component
        self.connector = connector
        self.config = config
        self.capacity_fn = capacity_fn  # (snapshot) -> tokens/s one worker sustains
        self.load_predictor = make_predictor(config.predictor)
        self.last_decision: Optional[int] = None
        # measured-vs-profiled latency ratio, EMA-smoothed: >1 means the
        # fleet runs slower than its profile (stale sweep, noisy neighbors,
        # longer contexts), so every profiled capacity is scaled down by it.
        # Reference: p_correction_factor / d_correction_factor
        # (planner_core.py:766-829). Clamped — one bad window must not 4x
        # the fleet.
        self.correction = 1.0

    def observe(self, rate: float) -> None:
        self.load_predictor.observe(rate)

    def update_correction(self, measured: float, expected: float) -> None:
        if measured <= 0 or expected <= 0:
            return
        raw = min(max(measured / expected, 0.25), 4.0)
        self.correction = (
            self.config.correction_smoothing * self.correction
            + (1.0 - self.config.correction_smoothing) * raw
        )

    def _capacity(self, snapshot: LoadSnapshot) -> float:
        return max(self.capacity_fn(snapshot), 1e-9) / self.correction

    def desired_replicas(self, snapshot: LoadSnapshot) -> int:
        predicted = self.load_predictor.predict(1)
        capacity = self._capacity(snapshot)
        needed = math.ceil(predicted / capacity)
        # queue pressure bumps the floor: waiting work means we're behind
        div = self.config.queue_bump_divisor
        if snapshot.num_waiting > 0 and div > 0:
            needed = max(needed, math.ceil(snapshot.num_waiting / div) + 1)
        # announced reclaims are capacity already spoken for: ask for their
        # replacements NOW so spares are warm before the deadline (the
        # connector's replica count still includes the draining workers)
        needed += snapshot.announced_reclaims
        return max(self.config.min_replicas, min(self.config.max_replicas, max(needed, 1)))

    async def plan_and_apply(self, snapshot: LoadSnapshot) -> int:
        desired = self.desired_replicas(snapshot)
        current = await self.connector.get_replicas(self.component)
        if desired < current:
            # hysteresis: only scale down with real headroom
            predicted = self.load_predictor.predict(1)
            capacity = self._capacity(snapshot)
            if predicted > capacity * desired * self.config.scale_down_headroom:
                desired = current
            elif self.config.max_scale_down_frac < 1.0:
                # bounded descent: never drop more than the configured
                # fraction of the current pool in one tick
                floor = math.ceil(
                    current * (1.0 - self.config.max_scale_down_frac)
                )
                desired = max(desired, int(floor))
        if desired != current:
            log.info(
                "%s pool: scaling %s %d -> %d (predicted load %.1f)",
                self.name, self.component, current, desired, self.load_predictor.predict(1),
            )
            await self.connector.set_replicas(self.component, desired)
        self.last_decision = desired
        return desired


class DisaggPlanner:
    """Coordinates prefill + decode pools under one budget (DisaggPlanner :859)."""

    def __init__(
        self,
        connector: Connector,
        config: Optional[PlannerConfig] = None,
        interpolator: Optional[PerfInterpolator] = None,
        prefill_component: str = "backend_prefill",
        decode_component: str = "backend",
    ):
        self.config = config or PlannerConfig()
        self.interp = interpolator or PerfInterpolator()
        self.connector = connector
        self.prefill = PoolPlanner(
            "prefill", prefill_component, connector, self.config,
            lambda s: self.interp.prefill_capacity(s.avg_isl),
        )
        self.decode = PoolPlanner(
            "decode", decode_component, connector, self.config,
            lambda s: self.interp.decode_capacity(s.active_seqs),
        )
        self._task: Optional[asyncio.Task] = None

    def observe(self, snapshot: LoadSnapshot) -> None:
        self.prefill.observe(snapshot.prefill_tokens_rate)
        self.decode.observe(snapshot.decode_tokens_rate)
        # close the loop on the profile: measured TTFT/ITL vs what the sweep
        # predicted at this load (reference _update_correction_factor)
        if snapshot.measured_ttft > 0:
            self.prefill.update_correction(
                snapshot.measured_ttft, self.interp.expected_ttft(snapshot.avg_isl)
            )
        if snapshot.measured_itl > 0:
            self.decode.update_correction(
                snapshot.measured_itl, self.interp.expected_itl(snapshot.active_seqs)
            )
        self._last_snapshot = snapshot

    async def plan(self) -> Dict[str, int]:
        snap = getattr(self, "_last_snapshot", LoadSnapshot())
        p = self.prefill.desired_replicas(snap)
        d = self.decode.desired_replicas(snap)
        budget = self.config.total_budget
        if budget and p + d > budget:
            # proportional squeeze under budget (reference GPU budgets)
            scale = budget / (p + d)
            p = max(self.config.min_replicas, int(p * scale))
            d = max(self.config.min_replicas, budget - p)
        await self._apply(self.prefill, p)
        await self._apply(self.decode, d)
        return {"prefill": p, "decode": d}

    async def _apply(self, pool: PoolPlanner, desired: int) -> None:
        current = await self.connector.get_replicas(pool.component)
        if desired != current:
            log.info("scaling %s %d -> %d", pool.component, current, desired)
            await self.connector.set_replicas(pool.component, desired)
        pool.last_decision = desired

    def start(self, metrics_fn) -> None:
        """metrics_fn() -> LoadSnapshot, polled every adjustment interval."""

        async def loop() -> None:
            try:
                while True:
                    await asyncio.sleep(self.config.adjustment_interval_s)
                    try:
                        self.observe(metrics_fn())
                        await self.plan()
                    except Exception:
                        log.exception("planning cycle failed")
            except asyncio.CancelledError:
                pass

        self._task = asyncio.create_task(loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
