"""Planner connectors: how scaling decisions become running workers.

Analogs of the reference's connectors (components/src/dynamo/planner/
kubernetes_connector.py:48,333 and virtual_connector.py:28):

- VirtualConnector: writes target replica counts into the discovery store
  under ``v1/planner/...``; an external launcher (or the subprocess connector
  below) watches and converges. Non-k8s coordination, like the reference's.
- SubprocessConnector: actually spawns/stops local worker processes (mocker
  or TPU engine) to match the target — the fleet-in-a-box used by scaling
  e2e tests (reference tests/planner/test_scaling_e2e.py runs on mockers).
- KubernetesConnector: patches Deployment replicas through the in-repo kube
  API client (deploy/kube.py) — no kubernetes-package dependency; CI drives
  it against the mock API server (tests/kube_mock.py).
"""

from __future__ import annotations

import asyncio
import os
import signal
import subprocess
from typing import Dict, List, Optional, Protocol

from ..runtime.discovery.store import KVStore
from ..runtime.logging import get_logger
from ..runtime.resilience import retry_policy

log = get_logger("planner.connectors")


def _planner_policy():
    """Shared retry for connector side effects (scope planner.connector):
    scale decisions are level-triggered and idempotent, so a dropped store
    write / kube patch replays instead of losing the scaling step."""
    return retry_policy(
        "planner.connector", max_attempts=3, base_delay_s=0.1, max_delay_s=2.0,
    )

PLANNER_PREFIX = "v1/planner"


def target_key(namespace: str, component: str) -> str:
    return f"{PLANNER_PREFIX}/{namespace}/{component}/target_replicas"


class Connector(Protocol):
    async def get_replicas(self, component: str) -> int: ...

    async def set_replicas(self, component: str, n: int) -> None: ...


class VirtualConnector:
    """Store-backed coordination (reference virtual_connector.py:28)."""

    def __init__(self, store: KVStore, namespace: str = "dynamo"):
        self.store = store
        self.namespace = namespace

    async def get_replicas(self, component: str) -> int:
        obj = await _planner_policy().acall(
            self.store.get_obj, target_key(self.namespace, component)
        )
        return int(obj["target"]) if obj else 0

    async def set_replicas(self, component: str, n: int) -> None:
        await _planner_policy().acall(
            self.store.put_obj,
            target_key(self.namespace, component), {"target": int(n)},
        )


class SubprocessConnector:
    """Spawns real local workers to match the target (fleet-in-a-box).

    The minimal direct-drive connector for benches and tests. For the full
    process lifecycle (crash restarts with backoff, SIGKILL escalation,
    spec-driven fleets, status reporting) use the operator analog,
    deploy/controller.py GraphController, with a VirtualConnector."""

    def __init__(self, make_cmd, poll_ready_s: float = 0.0):
        """make_cmd(component, index) -> argv list for one worker process."""
        self.make_cmd = make_cmd
        self.poll_ready_s = poll_ready_s
        self._procs: Dict[str, List[subprocess.Popen]] = {}
        self._stopping: List[subprocess.Popen] = []

    def _reap_stopping(self) -> None:
        still = []
        for p in self._stopping:
            if p.poll() is None:
                still.append(p)
            else:
                p.wait()  # reap: SIGTERM'd workers must not linger as zombies
        self._stopping = still

    async def get_replicas(self, component: str) -> int:
        self._reap_stopping()
        procs = self._procs.get(component, [])
        procs = [p for p in procs if p.poll() is None]
        self._procs[component] = procs
        return len(procs)

    async def set_replicas(self, component: str, n: int) -> None:
        self._reap_stopping()
        procs = self._procs.setdefault(component, [])
        procs[:] = [p for p in procs if p.poll() is None]
        while len(procs) < n:
            cmd = self.make_cmd(component, len(procs))
            log.info("spawning %s worker: %s", component, " ".join(cmd))
            procs.append(
                subprocess.Popen(
                    cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                    env=os.environ.copy(),
                )
            )
            if self.poll_ready_s:
                await asyncio.sleep(self.poll_ready_s)
        while len(procs) > n:
            p = procs.pop()
            log.info("stopping %s worker pid %d", component, p.pid)
            p.send_signal(signal.SIGTERM)
            self._stopping.append(p)

    def shutdown(self) -> None:
        for procs in self._procs.values():
            for p in procs:
                if p.poll() is None:
                    p.kill()
                p.wait()
        for p in self._stopping:
            if p.poll() is None:
                p.kill()
            p.wait()
        self._stopping = []


class KubernetesConnector:
    """Patch Deployment replicas straight through the kube API (reference
    components/src/dynamo/planner/kubernetes_connector.py:48,333).

    Built on the in-repo KubeClient (deploy/kube.py) — no `kubernetes`
    package dependency; in-cluster service-account config is picked up
    automatically when base_url is omitted, and CI drives the same code
    against the mock API server (tests/kube_mock.py)."""

    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        kube_namespace: str = "default",
        deployment_prefix: str = "dynamo-",
    ):
        from ..deploy.kube import KubeClient

        self.kube = KubeClient(base_url, token)
        self.kube_namespace = kube_namespace
        self.prefix = deployment_prefix

    def _name(self, component: str) -> str:
        return f"{self.prefix}{component}"

    async def get_replicas(self, component: str) -> int:
        dep = await _planner_policy().acall(
            self.kube.get,
            "apps/v1", self.kube_namespace, "deployments", self._name(component),
        )
        if dep is None:
            return 0
        return int((dep.get("spec") or {}).get("replicas") or 0)

    async def set_replicas(self, component: str, n: int) -> None:
        await _planner_policy().acall(
            self.kube.patch,
            "apps/v1", self.kube_namespace, "deployments", self._name(component),
            {"spec": {"replicas": int(n)}},
        )

    async def close(self) -> None:
        await self.kube.close()
