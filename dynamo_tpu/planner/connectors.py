"""Planner connectors: how scaling decisions become running workers.

Analogs of the reference's connectors (components/src/dynamo/planner/
kubernetes_connector.py:48,333 and virtual_connector.py:28):

- VirtualConnector: writes target replica counts into the discovery store
  under ``v1/planner/...``; an external launcher (or the subprocess connector
  below) watches and converges. Non-k8s coordination, like the reference's.
- SubprocessConnector: actually spawns/stops local worker processes (mocker
  or TPU engine) to match the target — the fleet-in-a-box used by scaling
  e2e tests (reference tests/planner/test_scaling_e2e.py runs on mockers).
- KubernetesConnector: patches deployment replicas via the k8s API (gated:
  no cluster in this environment; import kubernetes lazily).
"""

from __future__ import annotations

import asyncio
import os
import signal
import subprocess
from typing import Dict, List, Protocol

from ..runtime.discovery.store import KVStore
from ..runtime.logging import get_logger

log = get_logger("planner.connectors")

PLANNER_PREFIX = "v1/planner"


def target_key(namespace: str, component: str) -> str:
    return f"{PLANNER_PREFIX}/{namespace}/{component}/target_replicas"


class Connector(Protocol):
    async def get_replicas(self, component: str) -> int: ...

    async def set_replicas(self, component: str, n: int) -> None: ...


class VirtualConnector:
    """Store-backed coordination (reference virtual_connector.py:28)."""

    def __init__(self, store: KVStore, namespace: str = "dynamo"):
        self.store = store
        self.namespace = namespace

    async def get_replicas(self, component: str) -> int:
        obj = await self.store.get_obj(target_key(self.namespace, component))
        return int(obj["target"]) if obj else 0

    async def set_replicas(self, component: str, n: int) -> None:
        await self.store.put_obj(
            target_key(self.namespace, component), {"target": int(n)}
        )


class SubprocessConnector:
    """Spawns real local workers to match the target (fleet-in-a-box).

    The minimal direct-drive connector for benches and tests. For the full
    process lifecycle (crash restarts with backoff, SIGKILL escalation,
    spec-driven fleets, status reporting) use the operator analog,
    deploy/controller.py GraphController, with a VirtualConnector."""

    def __init__(self, make_cmd, poll_ready_s: float = 0.0):
        """make_cmd(component, index) -> argv list for one worker process."""
        self.make_cmd = make_cmd
        self.poll_ready_s = poll_ready_s
        self._procs: Dict[str, List[subprocess.Popen]] = {}
        self._stopping: List[subprocess.Popen] = []

    def _reap_stopping(self) -> None:
        still = []
        for p in self._stopping:
            if p.poll() is None:
                still.append(p)
            else:
                p.wait()  # reap: SIGTERM'd workers must not linger as zombies
        self._stopping = still

    async def get_replicas(self, component: str) -> int:
        self._reap_stopping()
        procs = self._procs.get(component, [])
        procs = [p for p in procs if p.poll() is None]
        self._procs[component] = procs
        return len(procs)

    async def set_replicas(self, component: str, n: int) -> None:
        self._reap_stopping()
        procs = self._procs.setdefault(component, [])
        procs[:] = [p for p in procs if p.poll() is None]
        while len(procs) < n:
            cmd = self.make_cmd(component, len(procs))
            log.info("spawning %s worker: %s", component, " ".join(cmd))
            procs.append(
                subprocess.Popen(
                    cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                    env=os.environ.copy(),
                )
            )
            if self.poll_ready_s:
                await asyncio.sleep(self.poll_ready_s)
        while len(procs) > n:
            p = procs.pop()
            log.info("stopping %s worker pid %d", component, p.pid)
            p.send_signal(signal.SIGTERM)
            self._stopping.append(p)

    def shutdown(self) -> None:
        for procs in self._procs.values():
            for p in procs:
                if p.poll() is None:
                    p.kill()
                p.wait()
        for p in self._stopping:
            if p.poll() is None:
                p.kill()
            p.wait()
        self._stopping = []


class KubernetesConnector:
    """Patch deployment/scale subresource (reference kubernetes_connector.py).

    Gated: requires the `kubernetes` package + in-cluster/SA config, neither
    of which exists in this image; construction raises a clear error so the
    planner falls back to the virtual connector."""

    def __init__(self, namespace: str = "default", deployment_prefix: str = "dynamo-"):
        try:
            import kubernetes  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "kubernetes client not available; use VirtualConnector and an "
                "external operator instead"
            ) from e
        from kubernetes import client, config

        config.load_incluster_config()
        self._apps = client.AppsV1Api()
        self.namespace = namespace
        self.prefix = deployment_prefix

    async def get_replicas(self, component: str) -> int:
        dep = self._apps.read_namespaced_deployment_scale(
            f"{self.prefix}{component}", self.namespace
        )
        return dep.status.replicas or 0

    async def set_replicas(self, component: str, n: int) -> None:
        self._apps.patch_namespaced_deployment_scale(
            f"{self.prefix}{component}", self.namespace, {"spec": {"replicas": n}}
        )
