"""Request flight recorder: bounded in-memory ring of per-request timelines.

Every request leaves a timeline of milestone events (received, tokenized,
routed, queued, admitted, prefill start/end, first token, migration, KV
transfer, finish/abort) in a fixed-capacity ring, so "what happened to
request X" is answerable after the fact without tracing infrastructure.
Exposed as ``/debug/requests`` on the component status servers
(runtime/health.py StatusServer, llm/http frontend).

Events use ``runtime/recorder.py``'s JSONL event model — each entry is
``{"timestamp": <unix_ns>, "event": {...}}`` — so a failure dump
(``DTPU_FLIGHT_DUMP``) is directly loadable with ``Recorder.load()`` and
replayable with ``Recorder.replay()``.

The recorder is always on: it is a few dicts and a lock, no I/O on the
record path (the failure dump writes on the abort path only). Producers on
any thread are fine — the engine stamps events from its executor threads.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Dict, Optional

from .config import ENV_FLIGHT_CAPACITY, ENV_FLIGHT_DUMP, env_int, env_str
from .logging import get_logger

log = get_logger("flight_recorder")

DEFAULT_CAPACITY = 512
# per-request event cap: a pathological stream (one migration per token) must
# not grow a single timeline without bound; the tail event notes the drop
MAX_EVENTS_PER_REQUEST = 64


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 dump_path: Optional[str] = None):
        self.capacity = max(1, capacity)
        self.dump_path = dump_path
        self._lock = threading.Lock()
        # request_id -> flight; insertion-ordered so eviction drops the
        # oldest request wholesale (a ring of timelines, not of events)
        self._flights: "collections.OrderedDict[str, Dict[str, Any]]" = (
            collections.OrderedDict()
        )

    # -- producer side --------------------------------------------------------
    def record(self, request_id: Optional[str], kind: str,
               _terminal: bool = False, **fields: Any) -> None:
        """Append one milestone event to the request's timeline."""
        if not request_id:
            return
        entry = {
            "timestamp": time.time_ns(),
            "event": {"kind": kind, **fields},
        }
        with self._lock:
            flight = self._flights.get(request_id)
            if flight is None:
                flight = self._flights[request_id] = {
                    "request_id": request_id,
                    "started_ns": entry["timestamp"],
                    "done": False,
                    "error": None,
                    "events": [],
                    "dropped_events": 0,
                }
                while len(self._flights) > self.capacity:
                    self._flights.popitem(last=False)
            # the cap bounds runaway mid-flight streams only: the terminal
            # finish/abort event (error class, status) must always land —
            # it is the record a failure dump exists to preserve
            if not _terminal and len(flight["events"]) >= MAX_EVENTS_PER_REQUEST:
                flight["dropped_events"] += 1
                return
            flight["events"].append(entry)

    def finish(self, request_id: Optional[str], error: Optional[str] = None,
               error_class: Optional[str] = None, **fields: Any) -> None:
        """Close the request's timeline; an ``error`` marks it failed and
        dumps the full timeline (log + optional JSONL file)."""
        if not request_id:
            return
        kind = "abort" if error else "finish"
        if error:
            fields["error"] = str(error)[:500]
            fields["error_class"] = error_class or "internal"
        self.record(request_id, kind, _terminal=True, **fields)
        with self._lock:
            flight = self._flights.get(request_id)
            if flight is None:
                return
            flight["done"] = True
            if error:
                flight["error"] = str(error)[:500]
            dump = dict(flight, events=list(flight["events"])) if error else None
        if dump is not None:
            self._dump_failure(dump)

    # -- consumer side --------------------------------------------------------
    def timeline(self, request_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            flight = self._flights.get(request_id)
            return None if flight is None else dict(
                flight, events=list(flight["events"])
            )

    def snapshot(self, limit: int = 64) -> Dict[str, Any]:
        """The ``/debug/requests`` payload: most-recent-first timelines."""
        with self._lock:
            # limit<=0 means none: [-0:] would be the WHOLE ring
            recent = list(self._flights.values())[-limit:] if limit > 0 else []
            recent = [dict(f, events=list(f["events"])) for f in recent]
        recent.reverse()
        return {
            "capacity": self.capacity,
            "retained": len(self._flights),
            "requests": recent,
        }

    def __len__(self) -> int:
        with self._lock:
            return len(self._flights)

    # -- failure dump ---------------------------------------------------------
    def _dump_failure(self, flight: Dict[str, Any]) -> None:
        log.warning(
            "request %s failed: %s; timeline: %s",
            flight["request_id"][:16], flight["error"],
            json.dumps([e["event"] for e in flight["events"]]),
        )
        if not self.dump_path:
            return
        try:
            with open(self.dump_path, "a") as f:
                for entry in flight["events"]:
                    line = dict(entry)
                    line["event"] = dict(
                        line["event"], request_id=flight["request_id"]
                    )
                    f.write(json.dumps(line) + "\n")
        except OSError:
            log.exception("flight-recorder failure dump to %s failed",
                          self.dump_path)


def debug_requests_payload(
    recorder: "FlightRecorder",
    request_id: Optional[str],
    limit_raw: Optional[str],
) -> tuple:
    """(http_status, json payload) for a ``/debug/requests`` query — the ONE
    implementation both the worker StatusServer and the HTTP frontend serve
    (same ?id= lookup, 404 wording, and limit parsing)."""
    if request_id:
        flight = recorder.timeline(request_id)
        if flight is None:
            return 404, {
                "error": f"request {request_id!r} not in the flight recorder"
            }
        # single-request view gains the SLO budget breakdown (queue/prefill/
        # decode share of the TTFT target, remaining deadline) when the
        # engine stamped the request's sla class onto its queued event
        from .attribution import attribution_breakdown
        from .slo import budget_breakdown

        slo = budget_breakdown(flight)
        if slo is not None:
            flight = dict(flight, slo=slo)
        # the critical-path phase decomposition (runtime/attribution.py):
        # exhaustive, non-overlapping, sums to the e2e duration — present
        # for any flight with >= 2 events, classed or not
        attribution = attribution_breakdown(flight)
        if attribution is not None:
            flight = dict(flight, attribution=attribution)
        return 200, flight
    try:
        limit = int(limit_raw) if limit_raw is not None else 64
    except ValueError:
        limit = 64
    return 200, recorder.snapshot(limit=limit)


_global_recorder: Optional[FlightRecorder] = None
_global_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    global _global_recorder
    if _global_recorder is None:
        with _global_lock:
            if _global_recorder is None:
                _global_recorder = FlightRecorder(
                    capacity=env_int(ENV_FLIGHT_CAPACITY, DEFAULT_CAPACITY),
                    dump_path=env_str(ENV_FLIGHT_DUMP, "") or None,
                )
    return _global_recorder


def set_flight_recorder(recorder: Optional[FlightRecorder]) -> None:
    global _global_recorder
    _global_recorder = recorder
