"""The injectable time source for pace-and-stamp code.

``Clock`` is the funnel every sim-path component takes (mocker engine
steps, loadgen arrival pacing, planner rate windows, worker-metrics
timestamps). The default ``WALL`` instance preserves live behavior
(``time.monotonic`` + ``asyncio.sleep``); the fleet simulator injects
``sim.clock.VirtualClock`` instead. It lives in ``runtime`` — not ``sim``
— so core modules (mocker, profiler, planner) never import from the sim
package and no import cycle can form; tools/lint.py's SIM-WALLCLOCK pass
enforces that sim-path modules route pacing through a Clock rather than
calling ``time.time()`` / ``time.sleep()`` / ``asyncio.sleep()`` directly,
and this module is the one exempt wall-clock funnel.
"""

from __future__ import annotations

import asyncio
import time


class Clock:
    """Wall-clock time source: ``time()`` seconds + async ``sleep``.

    Intervals only — ``time()`` is monotonic, not epoch-anchored, so callers
    must treat values as differences (exactly how the sim path uses them).
    """

    def time(self) -> float:
        return time.monotonic()

    async def sleep(self, dt: float) -> None:
        await asyncio.sleep(max(dt, 0.0))


WALL = Clock()
