"""Typed error taxonomy shared by engine, preprocessor and HTTP frontend.

The frontend used to classify failures by substring-matching exception
messages ("guided grammar", "prompt length", ...), which misfires on any
unrelated error that happens to contain those words and silently breaks
when wording changes (ADVICE round 5). Instead: the engine/preprocessor
raise typed errors carrying a stable ``code``; the request plane already
propagates ``.code`` in its err frames (request_plane/tcp.py), so the
frontend classifies by type locally and by code across the wire.

Every class subclasses ValueError so existing ``except ValueError`` request
-validation paths keep working unchanged. The ``code`` doubles as the retry
predicate's terminal-error marker: a typed 4xx-class failure is never worth
retrying (runtime/resilience.py).
"""

from __future__ import annotations

from typing import Tuple


class InvalidRequestError(ValueError):
    """The request itself is wrong (bad option, unsupported modality, ...)."""

    code = "invalid_request"
    http_status = 400
    err_type = "invalid_request_error"


class ContextLengthError(InvalidRequestError):
    """Prompt (or prompt + requested output) exceeds the model's context."""

    code = "context_length"
    err_type = "context_length_exceeded"


class GuidedRejectedError(InvalidRequestError):
    """A guided-decoding grammar the engine cannot (or will not) serve."""

    code = "guided_rejected"


# worker-side code -> (http status, OpenAI-style error type); the request
# plane delivers remote typed errors as RequestPlaneError(msg, code)
HTTP_BY_CODE = {
    InvalidRequestError.code: (400, InvalidRequestError.err_type),
    ContextLengthError.code: (400, ContextLengthError.err_type),
    GuidedRejectedError.code: (400, GuidedRejectedError.err_type),
    "circuit_open": (503, "service_unavailable"),
    "no_responders": (503, "service_unavailable"),
}


def http_status_for(exc: BaseException) -> Tuple[int, str]:
    """(status, err_type) for a request that failed before/while streaming."""
    if isinstance(exc, InvalidRequestError):
        return exc.http_status, exc.err_type
    entry = HTTP_BY_CODE.get(getattr(exc, "code", None))
    if entry is not None:
        return entry
    return 500, "internal_error"


def is_terminal(exc: BaseException) -> bool:
    """True when retrying cannot help (client error, not transport loss)."""
    if isinstance(exc, InvalidRequestError):
        return True
    code = getattr(exc, "code", None)
    return code in HTTP_BY_CODE and code != "no_responders"
